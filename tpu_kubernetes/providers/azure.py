"""Azure (ARM) provider.

reference: create/manager_azure.go:27-47 (subscription/client/tenant creds,
location, image), create/cluster_azure.go:25-36, create/node_azure.go:27-52
(size, image publisher/offer/SKU).
"""

from __future__ import annotations

from typing import Any

from tpu_kubernetes.providers.base import (
    BuildContext,
    Provider,
    base_cluster_config,
    base_manager_config,
    base_node_config,
    register,
)

DEFAULT_LOCATION = "eastus"
DEFAULT_SIZE = "Standard_D4s_v5"
DEFAULT_IMAGE_PUBLISHER = "Canonical"
DEFAULT_IMAGE_OFFER = "0001-com-ubuntu-server-jammy"
DEFAULT_IMAGE_SKU = "22_04-lts-gen2"


def _azure_common(ctx: BuildContext, out: dict[str, Any]) -> None:
    cfg = ctx.cfg
    out["azure_subscription_id"] = cfg.get(
        "azure_subscription_id", prompt="Azure subscription id"
    )
    out["azure_client_id"] = cfg.get("azure_client_id", prompt="Azure client id")
    out["azure_client_secret"] = cfg.get(
        "azure_client_secret", prompt="Azure client secret", secret=True
    )
    out["azure_tenant_id"] = cfg.get("azure_tenant_id", prompt="Azure tenant id")
    # live location listing when ARM credentials work (reference does the
    # equivalent through the SDK, create/manager_azure.go:49)
    from tpu_kubernetes.catalog import get_catalog
    from tpu_kubernetes.providers.base import catalog_get

    cat = get_catalog("azure", cfg)
    out["azure_location"] = catalog_get(
        cfg, cat, "azure_location", "location",
        prompt="Azure location", default=DEFAULT_LOCATION,
    )


def _azure_image(ctx: BuildContext, out: dict[str, Any]) -> None:
    cfg = ctx.cfg
    out["azure_image_publisher"] = cfg.get(
        "azure_image_publisher", default=DEFAULT_IMAGE_PUBLISHER
    )
    out["azure_image_offer"] = cfg.get("azure_image_offer", default=DEFAULT_IMAGE_OFFER)
    out["azure_image_sku"] = cfg.get("azure_image_sku", default=DEFAULT_IMAGE_SKU)
    from tpu_kubernetes.catalog import get_catalog
    from tpu_kubernetes.providers.base import catalog_get

    cat = get_catalog("azure", cfg)
    out["azure_size"] = catalog_get(
        cfg, cat, "azure_size", "size", prompt="VM size", default=DEFAULT_SIZE,
        scope={"location": out.get("azure_location")},
    )
    out["azure_ssh_user"] = cfg.get("azure_ssh_user", default="ubuntu")
    out["azure_public_key_path"] = cfg.get(
        "azure_public_key_path", prompt="SSH public key path",
        default="~/.ssh/id_rsa.pub",
    )


def build_manager(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/manager_azure.go:27-47."""
    out = base_manager_config(ctx, "azure")
    _azure_common(ctx, out)
    _azure_image(ctx, out)
    # private key for the api-key scrape (manager module only)
    out["azure_private_key_path"] = ctx.cfg.get(
        "azure_private_key_path", default="~/.ssh/id_rsa"
    )
    return out


def build_cluster(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/cluster_azure.go:25-36 — cluster owns resource
    group, vnet, and NSG."""
    out = base_cluster_config(ctx, "azure")
    _azure_common(ctx, out)
    return out


def build_node(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/node_azure.go:27-52; RG/subnet/NSG interpolated
    from cluster outputs."""
    out = base_node_config(ctx, "azure")
    _azure_common(ctx, out)
    _azure_image(ctx, out)
    # managed data disk (reference: azure-rancher-k8s-host/main.tf:34-110)
    data_gb = int(ctx.cfg.get("azure_data_disk_size_gb", default=0) or 0)
    if data_gb:
        out["azure_data_disk_size_gb"] = data_gb
    ck = ctx.cluster_key
    out["azure_resource_group_name"] = (
        f"${{module.{ck}.azure_resource_group_name}}"
    )
    out["azure_subnet_id"] = f"${{module.{ck}.azure_subnet_id}}"
    out["azure_network_security_group_id"] = (
        f"${{module.{ck}.azure_network_security_group_id}}"
    )
    return out


register(
    Provider(
        name="azure",
        display="Microsoft Azure",
        build_manager=build_manager,
        build_cluster=build_cluster,
        build_node=build_node,
    )
)
