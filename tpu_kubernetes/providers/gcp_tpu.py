"""GCP Cloud TPU provider — the north-star addition (BASELINE.json).

No reference analog exists; this extends the provider switch the way a
``cluster_gcp_tpu.go`` / ``node_gcp_tpu.go`` pair would extend
create/cluster.go:125-141 and create/node.go:179-194. Key departures from the
VM providers:

* A "node" is a **slice**, not a VM (SURVEY §7 hard part #2): one node module
  instance provisions one v5e/v5p pod slice (``google_tpu_v2_vm``), which may
  span many hosts. ``node_count`` means number of slices.
* The accelerator type is parsed into a typed :class:`TpuTopology` and an
  optionally requested JAX mesh is validated against it at render time —
  before any quota is consumed.
* The module emits the ``jax.distributed`` wiring (coordinator address,
  process count/ids, slice topology) into each host's environment — the TPU
  analog of the rancher agent's --server/--token/--ca-checksum trio
  (reference: install_rancher_agent.sh.tpl:44).
"""

from __future__ import annotations

from typing import Any

from tpu_kubernetes.catalog import CatalogError, catalog_validate, get_catalog
from tpu_kubernetes.providers.base import (
    BuildContext,
    Provider,
    ProviderError,
    base_cluster_config,
    base_node_config,
    catalog_get,
    register,
)
from tpu_kubernetes.providers.gcp import _gcp_common
from tpu_kubernetes.topology import (
    TopologyError,
    parse_accelerator_type,
    parse_mesh_shape,
    validate_mesh,
)

# sensible TPU-VM runtime (software) versions by generation; overridable
DEFAULT_RUNTIME_VERSIONS = {
    "v4": "tpu-ubuntu2204-base",
    "v5e": "v2-alpha-tpuv5-lite",
    "v5p": "v2-alpha-tpuv5",
    "v6e": "v2-alpha-tpuv6e",
}
DEFAULT_COORDINATOR_PORT = 8476
# TPU capacity lives in TPU zones, not generic GCE zones (matches the
# gcp-tpu-node module default)
DEFAULT_TPU_ZONE = "us-east5-a"

ACCELERATOR_CHOICES = [
    "v5e-1", "v5e-4", "v5e-8", "v5e-16", "v5e-64", "v5e-256",
    "v5p-8", "v5p-16", "v5p-32", "v5p-128", "v5p-256",
    "v6e-4", "v6e-8", "v6e-16", "v6e-256",
]


def build_cluster(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """Cluster envelope: registration with the control plane + the network
    the slices land in (mirrors gcp cluster, reference:
    create/cluster_gcp.go:28-34, module gcp-rancher-k8s)."""
    out = base_cluster_config(ctx, "gcp-tpu")
    _gcp_common(ctx, out)
    return out


def build_node(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    out = base_node_config(ctx, "gcp-tpu")
    # TPU slices always join as workers; the control-plane quorum credential
    # must never be shipped to slice hosts — nor the server-join facts
    # (server version / CNI flags) that only quorum joins consume
    out.pop("server_token", None)
    out.pop("server_k8s_version", None)
    out.pop("network_provider", None)
    _gcp_common(ctx, out)
    cfg = ctx.cfg

    # zone first: the accelerator catalog is zone-scoped (what a zone
    # actually offers differs per generation)
    cat = get_catalog("gcp-tpu", cfg)
    out["gcp_zone"] = catalog_get(
        cfg, cat, "gcp_zone", "zone", prompt="TPU zone",
        default=DEFAULT_TPU_ZONE,
    )

    accel = cfg.get(
        "tpu_accelerator_type",
        prompt="TPU accelerator type",
        choices=None if cfg.is_set("tpu_accelerator_type") else ACCELERATOR_CHOICES,
        default="v5e-4",
    )
    try:
        topo = parse_accelerator_type(str(accel))
    except TopologyError as e:
        raise ProviderError(str(e)) from e
    # render-time zone-capacity check (no reference analog — TPU types are
    # zonal in a way GCE machine types aren't): validate the API name the
    # slice will actually request against the zone's acceleratorTypes
    try:
        catalog_validate(
            cat, "accelerator_type", topo.api_name, zone=out["gcp_zone"]
        )
    except CatalogError as e:
        raise ProviderError(str(e)) from e

    mesh_spec = cfg.peek("mesh_shape")
    if mesh_spec:
        try:
            validate_mesh(topo, parse_mesh_shape(str(mesh_spec)))
        except TopologyError as e:
            raise ProviderError(str(e)) from e
    # the API string (v5e → v5litepod-N); canonical form kept alongside
    out["tpu_accelerator_type"] = topo.api_name
    out["tpu_topology"] = topo.topology
    out["tpu_hosts"] = topo.hosts
    out["tpu_chips"] = topo.chips
    out["tpu_runtime_version"] = cfg.get(
        "tpu_runtime_version",
        default=DEFAULT_RUNTIME_VERSIONS.get(topo.generation, "tpu-ubuntu2204-base"),
    )
    out["tpu_coordinator_port"] = int(
        cfg.get("tpu_coordinator_port", default=DEFAULT_COORDINATOR_PORT)
    )
    sched = cfg.get(
        "tpu_provisioning_model",
        default="on-demand",
    )
    if sched not in ("on-demand", "spot", "reserved"):
        raise ProviderError(
            f"tpu_provisioning_model must be on-demand|spot|reserved, got {sched!r}"
        )
    out["tpu_provisioning_model"] = sched
    # cluster module network handles (same contract as gcp nodes,
    # reference: create/node_gcp.go:63-66)
    out["gcp_compute_network_name"] = (
        f"${{module.{ctx.cluster_key}.gcp_compute_network_name}}"
    )
    out["gcp_compute_firewall_host_tag"] = (
        f"${{module.{ctx.cluster_key}.gcp_compute_firewall_host_tag}}"
    )
    return out


register(
    Provider(
        name="gcp-tpu",
        display="Google Cloud TPU (v5e/v5p/v6e pod slices)",
        build_manager=None,  # TPU slices join a manager created by gcp/baremetal/…
        build_cluster=build_cluster,
        build_node=build_node,
    )
)
