from tpu_kubernetes.providers.base import (  # noqa: F401
    BuildContext,
    Provider,
    ProviderError,
    cluster_providers,
    get_provider,
    manager_providers,
    module_source,
    node_providers,
    register,
)

# importing a provider module registers it
from tpu_kubernetes.providers import aws  # noqa: F401,E402
from tpu_kubernetes.providers import azure  # noqa: F401,E402
from tpu_kubernetes.providers import baremetal  # noqa: F401,E402
from tpu_kubernetes.providers import gcp  # noqa: F401,E402
from tpu_kubernetes.providers import gcp_tpu  # noqa: F401,E402
from tpu_kubernetes.providers import triton  # noqa: F401,E402
from tpu_kubernetes.providers import vsphere  # noqa: F401,E402
