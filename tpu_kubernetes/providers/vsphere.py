"""vSphere provider — cluster and node only, no manager.

reference: the vsphere manager is commented out in the provider switch
(create/manager.go:119); cluster/node at create/cluster_vsphere.go:18-30 and
create/node_vsphere.go:20-35 (datacenter/datastore/resource pool/network/
template, pure prompts — no SDK validation).
"""

from __future__ import annotations

from typing import Any

from tpu_kubernetes.providers.base import (
    BuildContext,
    Provider,
    base_cluster_config,
    base_node_config,
    register,
)


def _vsphere_common(ctx: BuildContext, out: dict[str, Any]) -> None:
    cfg = ctx.cfg
    out["vsphere_server"] = cfg.get("vsphere_server", prompt="vSphere server")
    out["vsphere_user"] = cfg.get("vsphere_user", prompt="vSphere user")
    out["vsphere_password"] = cfg.get(
        "vsphere_password", prompt="vSphere password", secret=True
    )
    out["vsphere_datacenter_name"] = cfg.get(
        "vsphere_datacenter_name", prompt="datacenter"
    )
    out["vsphere_datastore_name"] = cfg.get(
        "vsphere_datastore_name", prompt="datastore"
    )
    out["vsphere_resource_pool_name"] = cfg.get(
        "vsphere_resource_pool_name", prompt="resource pool"
    )
    out["vsphere_network_name"] = cfg.get("vsphere_network_name", prompt="network")


def build_cluster(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/cluster_vsphere.go:18-30."""
    out = base_cluster_config(ctx, "vsphere")
    _vsphere_common(ctx, out)
    return out


def build_node(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/node_vsphere.go:20-35 — VMs cloned from a template."""
    out = base_node_config(ctx, "vsphere")
    _vsphere_common(ctx, out)
    cfg = ctx.cfg
    out["vsphere_template_name"] = cfg.get(
        "vsphere_template_name", prompt="VM template to clone"
    )
    out["ssh_user"] = cfg.get("ssh_user", prompt="SSH user", default="ubuntu")
    out["key_path"] = cfg.get("key_path", default="~/.ssh/id_rsa")
    return out


register(
    Provider(
        name="vsphere",
        display="VMware vSphere (cluster/node only)",
        build_cluster=build_cluster,
        build_node=build_node,
    )
)
