"""Bare-metal provider: existing hosts over SSH (optionally via a bastion).

The reference's simplest provider — pure null_resource + remote-exec
(reference: create/manager_bare_metal.go:20-30, cluster_bare_metal.go:14-19,
node_bare_metal.go:34; modules bare-metal-rancher*). Also the e2e smoke-test
path (BASELINE config #1: single-node bare-metal cluster, local backend).
"""

from __future__ import annotations

from typing import Any

from tpu_kubernetes.providers.base import (
    BuildContext,
    Provider,
    base_cluster_config,
    base_manager_config,
    base_node_config,
    register,
)


def _ssh_fields(ctx: BuildContext, out: dict[str, Any]) -> None:
    cfg = ctx.cfg
    out["ssh_user"] = cfg.get("ssh_user", prompt="SSH user", default="root")
    out["key_path"] = cfg.get("key_path", prompt="SSH private key path",
                              default="~/.ssh/id_rsa")
    bastion = cfg.get("bastion_host", default="")
    if bastion:
        out["bastion_host"] = bastion


def build_manager(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/manager_bare_metal.go:20-30 (host/ssh_user/key_path)."""
    out = base_manager_config(ctx, "baremetal")
    out["host"] = ctx.cfg.get("host", prompt="manager host (IP or DNS)")
    _ssh_fields(ctx, out)
    return out


def build_cluster(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """Adds nothing beyond the base (reference: create/cluster_bare_metal.go:14-16)."""
    return base_cluster_config(ctx, "baremetal")


def build_node(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/node_bare_metal.go:34 — takes a ``hosts`` list; one
    module instance per host is fanned out by the workflow."""
    out = base_node_config(ctx, "baremetal")
    hosts = ctx.cfg.get("hosts", prompt="comma-separated host list")
    if isinstance(hosts, str):
        hosts = [h.strip() for h in hosts.split(",") if h.strip()]
    out["hosts"] = hosts
    _ssh_fields(ctx, out)
    return out


register(
    Provider(
        name="baremetal",
        display="Bare Metal (existing hosts over SSH)",
        build_manager=build_manager,
        build_cluster=build_cluster,
        build_node=build_node,
    )
)
