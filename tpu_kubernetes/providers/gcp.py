"""GCP (GCE VM) provider.

reference: create/manager_gcp.go:27-43 (manager config),
create/cluster_gcp.go:28-34 (cluster config), create/node_gcp.go:24-41,58-66
(node config + cluster-output interpolations).

The reference validates regions/zones/machine-types/images by calling the
compute API mid-prompt (create/manager_gcp.go:112-324) — which is why those
paths are untestable in its suite (SURVEY §4 gap). Here values are taken as
given (static defaults offered) and validation is left to terraform plan,
keeping the whole flow hermetic.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

from tpu_kubernetes.catalog import get_catalog
from tpu_kubernetes.providers.base import (
    BuildContext,
    Provider,
    base_cluster_config,
    base_manager_config,
    base_node_config,
    catalog_get,
    register,
)

DEFAULT_REGION = "us-central1"
DEFAULT_ZONE = "us-central1-a"
DEFAULT_MACHINE_TYPE = "n2-standard-4"
DEFAULT_IMAGE = "ubuntu-os-cloud/ubuntu-2204-lts"


def gcp_project_from_credentials(path: str) -> str | None:
    """Derive the project id from a service-account JSON file.
    reference: create/cluster_gcp.go:96-103."""
    try:
        data = json.loads(Path(path).expanduser().read_text())
    except (OSError, ValueError):
        return None
    return data.get("project_id")


def _gcp_common(ctx: BuildContext, out: dict[str, Any]) -> None:
    cfg = ctx.cfg
    creds = cfg.get(
        "gcp_path_to_credentials", prompt="path to GCP service-account JSON"
    )
    out["gcp_path_to_credentials"] = creds
    derived = gcp_project_from_credentials(creds)
    if derived is not None:
        cfg.set("gcp_project_id", cfg.peek("gcp_project_id", derived))
    out["gcp_project_id"] = cfg.get("gcp_project_id", prompt="GCP project id")
    # live region listing when credentials work (reference:
    # create/manager_gcp.go:112-140); static default hermetically
    cat = get_catalog("gcp", cfg)
    out["gcp_compute_region"] = catalog_get(
        cfg, cat, "gcp_compute_region", "region",
        prompt="GCP compute region", default=DEFAULT_REGION,
    )


def _gcp_machine(ctx: BuildContext, out: dict[str, Any]) -> None:
    """Zone / machine-type / image selection with live catalog listings when
    credentials work (reference: create/manager_gcp.go:141-324)."""
    cfg = ctx.cfg
    cat = get_catalog("gcp", cfg)
    region = out.get("gcp_compute_region")
    out["gcp_zone"] = catalog_get(
        cfg, cat, "gcp_zone", "zone", prompt="GCP zone", default=DEFAULT_ZONE,
        scope={"region": region},
    )
    out["gcp_machine_type"] = catalog_get(
        cfg, cat, "gcp_machine_type", "machine_type", prompt="machine type",
        default=DEFAULT_MACHINE_TYPE, scope={"zone": out["gcp_zone"]},
    )
    image = cfg.get("gcp_image", prompt="boot image", default=DEFAULT_IMAGE)
    if "/" not in str(image):
        # unqualified = image in this project (e.g. packer output) — those
        # the catalog can check; `project/family` strings it cannot
        from tpu_kubernetes.catalog import CatalogError, catalog_validate

        from tpu_kubernetes.providers.base import ProviderError

        try:
            catalog_validate(cat, "image", str(image))
        except CatalogError as e:
            raise ProviderError(str(e)) from e
    out["gcp_image"] = image


def build_manager(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/manager_gcp.go:27-41."""
    out = base_manager_config(ctx, "gcp")
    _gcp_common(ctx, out)
    cfg = ctx.cfg
    _gcp_machine(ctx, out)
    # SSH access for the api-key scrape + optional service account
    # (reference: gcp-rancher/main.tf:50-57 sshKeys metadata)
    out["gcp_ssh_user"] = cfg.get("gcp_ssh_user", default="ubuntu")
    out["gcp_public_key_path"] = cfg.get(
        "gcp_public_key_path", prompt="SSH public key path",
        default="~/.ssh/id_rsa.pub",
    )
    out["gcp_private_key_path"] = cfg.get(
        "gcp_private_key_path", default="~/.ssh/id_rsa"
    )
    sa = cfg.peek("gcp_service_account_email")
    if sa:
        out["gcp_service_account_email"] = sa
    return out


def build_cluster(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/cluster_gcp.go:28-34."""
    out = base_cluster_config(ctx, "gcp")
    _gcp_common(ctx, out)
    return out


def build_node(ctx: BuildContext, _unused: dict[str, Any]) -> dict[str, Any]:
    """reference: create/node_gcp.go:24-41; network/firewall-tag interpolated
    from the cluster module's outputs (:63-66)."""
    out = base_node_config(ctx, "gcp")
    _gcp_common(ctx, out)
    cfg = ctx.cfg
    _gcp_machine(ctx, out)
    disk_gb = int(cfg.get("gcp_disk_size_gb", default=0) or 0)
    if disk_gb:
        out["gcp_disk_size_gb"] = disk_gb
    # detachable data disk (reference: gcp-rancher-k8s-host/main.tf:66-73)
    data_gb = int(cfg.get("gcp_data_disk_size_gb", default=0) or 0)
    if data_gb:
        out["gcp_data_disk_size_gb"] = data_gb
    # cloud-platform scope for workload API access — GCS checkpoints
    # (reference: gcp-rancher-k8s-host/main.tf:60-63)
    sa = cfg.peek("gcp_service_account_email")
    if sa:
        out["gcp_service_account_email"] = sa
    # cluster module network handles (reference: create/node_gcp.go:63-66)
    out["gcp_compute_network_name"] = (
        f"${{module.{ctx.cluster_key}.gcp_compute_network_name}}"
    )
    out["gcp_compute_firewall_host_tag"] = (
        f"${{module.{ctx.cluster_key}.gcp_compute_firewall_host_tag}}"
    )
    return out


register(
    Provider(
        name="gcp",
        display="Google Cloud Platform (GCE VMs)",
        build_manager=build_manager,
        build_cluster=build_cluster,
        build_node=build_node,
    )
)
