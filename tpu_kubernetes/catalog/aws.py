"""AWS catalog: AMI + instance-type validation, instance-type discovery.

Reference analog: create/node_aws.go:87-120 — ``DescribeImages`` validates
the AMI and ``DescribeInstanceTypeOfferings``-style listing backs the
instance-type prompt. boto3 is optional: construction raises without it and
``get_catalog`` degrades to the null catalog. The client is injectable so
the logic is hermetically testable (the reference's equivalent is untested
for exactly this reason).
"""

from __future__ import annotations

from typing import Any

from tpu_kubernetes.config import Config


def _default_client(cfg: Config):
    import boto3  # not baked into the image; degrade when absent

    return boto3.client(
        "ec2",
        region_name=str(cfg.peek("aws_region") or "us-east-1"),
        aws_access_key_id=str(cfg.peek("aws_access_key") or "") or None,
        aws_secret_access_key=str(cfg.peek("aws_secret_key") or "") or None,
    )


class AwsCatalog:
    """``client`` mirrors the boto3 EC2 client surface actually used:
    ``describe_images``, ``describe_instance_type_offerings``."""

    MAX_PAGES = 20

    def __init__(self, client: Any):
        self.client = client
        self._types: tuple[list[str], bool] | None = None
        self._types_fetched = False

    def _instance_types(self) -> tuple[list[str], bool] | None:
        """→ (types, complete), following NextToken (us-east-1 offers 800+
        types — one page is NOT the universe)."""
        if not self._types_fetched:
            self._types_fetched = True
            try:
                names: list[str] = []
                kwargs: dict[str, Any] = {"LocationType": "region"}
                complete = False
                for _ in range(self.MAX_PAGES):
                    resp = self.client.describe_instance_type_offerings(**kwargs)
                    names += [
                        o["InstanceType"]
                        for o in resp.get("InstanceTypeOfferings", [])
                    ]
                    token = resp.get("NextToken")
                    if not token:
                        complete = True
                        break
                    kwargs["NextToken"] = token
                self._types = (sorted(names), complete) if names else None
            except Exception:
                self._types = None
        return self._types

    def choices(self, kind: str, **scope: Any) -> list[str] | None:
        if kind != "instance_type":
            return None
        got = self._instance_types()
        return got[0] if got else None

    def validate(self, kind: str, value: str, **scope: Any) -> str | None:
        if kind == "ami":
            try:
                resp = self.client.describe_images(ImageIds=[value])
                images = resp.get("Images", [])
            except Exception as e:
                # InvalidAMIID.* is a definitive "no such image"; anything
                # else (auth, network) is degradation, not failure
                if "InvalidAMIID" in str(e):
                    return f"AMI {value!r} does not exist in this region"
                return None
            if not images:
                return f"AMI {value!r} does not exist in this region"
            state = images[0].get("State", "available")
            if state != "available":
                return f"AMI {value!r} is not available (state: {state})"
            return None
        if kind == "instance_type":
            got = self._instance_types()
            if got is None or not got[1] or value in got[0]:
                # incomplete listings never reject
                return None
            return f"instance type {value!r} is not offered in this region"
        return None


def factory(cfg: Config):
    return AwsCatalog(_default_client(cfg))
