"""Azure catalog: location + VM-size discovery over plain ARM REST.

Reference analog: the azure provider collects subscription/client/tenant
credentials and location/size by prompt (reference:
create/manager_azure.go:27-47) with the SDK underneath. No Azure SDK is
assumed here: client-credentials OAuth against login.microsoftonline.com
plus two management-plane GETs cover the discovery surface, with the
session injectable for hermetic tests.
"""

from __future__ import annotations

from typing import Any

from tpu_kubernetes.config import Config

LOGIN = "https://login.microsoftonline.com"
ARM = "https://management.azure.com"
API = "2023-07-01"


def _default_session(cfg: Config):
    import requests

    tenant = cfg.peek("azure_tenant_id")
    client = cfg.peek("azure_client_id")
    secret = cfg.peek("azure_client_secret")
    if not (tenant and client and secret):
        raise LookupError("azure credentials not configured")
    resp = requests.post(
        f"{LOGIN}/{tenant}/oauth2/v2.0/token",
        data={
            "grant_type": "client_credentials",
            "client_id": client,
            "client_secret": secret,
            "scope": f"{ARM}/.default",
        },
        timeout=15,
    )
    resp.raise_for_status()
    session = requests.Session()
    session.headers["Authorization"] = f"Bearer {resp.json()['access_token']}"
    return session


class AzureCatalog:
    def __init__(self, subscription: str, session: Any):
        self.subscription = subscription
        self.session = session
        self._cache: dict[tuple, list[str] | None] = {}

    def _list(self, url: str, field: str = "name") -> list[str] | None:
        try:
            resp = self.session.get(url, timeout=15)
            if resp.status_code != 200:
                return None
            return [it.get(field, "") for it in resp.json().get("value", [])] or None
        except Exception:
            return None

    def choices(self, kind: str, **scope: Any) -> list[str] | None:
        sub = self.subscription
        if kind == "location":
            key = ("loc",)
            if key not in self._cache:
                self._cache[key] = self._list(
                    f"{ARM}/subscriptions/{sub}/locations?api-version={API}"
                )
            return self._cache[key]
        if kind == "size":
            location = scope.get("location")
            if not location:
                return None
            key = ("size", location)
            if key not in self._cache:
                self._cache[key] = self._list(
                    f"{ARM}/subscriptions/{sub}/providers/Microsoft.Compute"
                    f"/locations/{location}/vmSizes?api-version={API}"
                )
            return self._cache[key]
        return None

    def validate(self, kind: str, value: str, **scope: Any) -> str | None:
        known = self.choices(kind, **scope)
        if known is None or value in known:
            return None
        return f"Azure {kind} {value!r} not found in subscription {self.subscription}"


def factory(cfg: Config):
    sub = cfg.peek("azure_subscription_id")
    if not sub:
        raise LookupError("azure subscription not configured")
    return AzureCatalog(str(sub), _default_session(cfg))
