"""GCP catalog: compute + Cloud TPU REST discovery.

Reference analog: create/manager_gcp.go:112-324 lists regions, zones,
machine types, and images through the compute SDK mid-prompt. Here the same
surface is plain REST (the SDK client isn't baked into minimal images) with
an injectable session so the parsing is hermetically testable, plus a
TPU-native addition the reference has no analog of: listing the
``acceleratorTypes`` a zone actually offers (``tpu.googleapis.com``), so a
``v5p-32`` typo or an unavailable generation is caught at prompt time, not
after quota is burned.
"""

from __future__ import annotations

from typing import Any

from tpu_kubernetes.config import Config

COMPUTE = "https://compute.googleapis.com/compute/v1"
TPU = "https://tpu.googleapis.com/v2"
_SCOPES = ["https://www.googleapis.com/auth/cloud-platform"]


def _default_session(creds_path: str):
    """OAuth'd requests session from a service-account file. Raises on any
    missing dependency/credential — get_catalog degrades that to Null."""
    import google.auth.transport.requests
    import requests
    from google.oauth2 import service_account

    creds = service_account.Credentials.from_service_account_file(
        creds_path, scopes=_SCOPES
    )
    creds.refresh(google.auth.transport.requests.Request())
    session = requests.Session()
    session.headers["Authorization"] = f"Bearer {creds.token}"
    return session


class GcpCatalog:
    """``session`` needs only ``.get(url) -> resp`` with ``.status_code``
    and ``.json()`` — tests inject a stub."""

    def __init__(self, project: str, session: Any):
        self.project = project
        self.session = session
        self._cache: dict[tuple, list[str] | None] = {}

    MAX_PAGES = 20

    def _list(
        self, url: str, field: str = "name", items_key: str = "items"
    ) -> tuple[list[str], bool] | None:
        """→ (names, complete) following nextPageToken; None on any failure.
        ``complete=False`` (page cap hit) means the list may only be used
        for prompt choices, never to reject a value as nonexistent."""
        try:
            names: list[str] = []
            token = ""
            for _ in range(self.MAX_PAGES):
                page_url = url + (f"?pageToken={token}" if token else "")
                resp = self.session.get(page_url, timeout=15)
                if resp.status_code != 200:
                    return None
                body = resp.json()
                for it in body.get(items_key, []):
                    name = it.get(field, "")
                    # acceleratorTypes/locations come fully qualified:
                    # …/acceleratorTypes/v5p-32 — keep the leaf
                    names.append(name.rsplit("/", 1)[-1] if "/" in name else name)
                token = body.get("nextPageToken", "")
                if not token:
                    return (names, True) if names else None
            return (names, False) if names else None
        except Exception:
            return None

    def _cached(
        self, key: tuple, url: str, **kw
    ) -> tuple[list[str], bool] | None:
        if key not in self._cache:
            self._cache[key] = self._list(url, **kw)
        return self._cache[key]

    def _lookup(self, kind: str, **scope: Any) -> tuple[list[str], bool] | None:
        p = self.project
        if kind == "region":
            return self._cached(("region",), f"{COMPUTE}/projects/{p}/regions")
        if kind == "zone":
            region = scope.get("region")
            got = self._cached(("zone",), f"{COMPUTE}/projects/{p}/zones")
            if got and region:
                zones = [z for z in got[0] if z.startswith(f"{region}-")]
                return (zones, got[1]) if zones else None
            return got
        if kind == "machine_type":
            zone = scope.get("zone")
            if not zone:
                return None
            return self._cached(
                ("mt", zone), f"{COMPUTE}/projects/{p}/zones/{zone}/machineTypes"
            )
        if kind == "image":
            # family-qualified public images are what the prompts offer;
            # project images are the custom/packer output
            proj = scope.get("image_project", p)
            return self._cached(
                ("img", proj), f"{COMPUTE}/projects/{proj}/global/images"
            )
        if kind == "tpu_location":
            # zones where the Cloud TPU API is present at all — the right
            # prompt set for "TPU zone" (most GCE zones have no TPUs)
            return self._cached(
                ("tpuloc",), f"{TPU}/projects/{p}/locations",
                field="locationId", items_key="locations",
            )
        if kind == "accelerator_type":
            zone = scope.get("zone")
            if not zone:
                return None
            return self._cached(
                ("tpu", zone),
                f"{TPU}/projects/{p}/locations/{zone}/acceleratorTypes",
                items_key="acceleratorTypes",
            )
        return None

    def choices(self, kind: str, **scope: Any) -> list[str] | None:
        got = self._lookup(kind, **scope)
        return got[0] if got else None

    def validate(self, kind: str, value: str, **scope: Any) -> str | None:
        got = self._lookup(kind, **scope)
        if got is None or not got[1] or value in got[0]:
            # unknown or INCOMPLETE listings never reject (a valid value
            # could live past the page cap)
            return None
        hint = ", ".join(sorted(got[0])[:8])
        return (
            f"GCP {kind.replace('_', ' ')} {value!r} not found in project "
            f"{self.project}" + (f" (e.g. {hint})" if hint else "")
        )


def factory(cfg: Config):
    creds_path = cfg.peek("gcp_path_to_credentials")
    project = cfg.peek("gcp_project_id")
    if not creds_path or not project:
        raise LookupError("gcp credentials/project not configured")
    return GcpCatalog(str(project), _default_session(str(creds_path)))
