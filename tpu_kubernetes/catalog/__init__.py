"""Cloud resource catalogs: discovery + validation with graceful degradation.

The reference spends ~1.5 kLoC calling cloud SDKs mid-prompt — listing GCP
regions/zones/machine-types/images (reference: create/manager_gcp.go:112-324),
validating AWS AMIs/instance types (reference: create/node_aws.go:87-120),
listing Triton networks/images/packages (reference:
create/manager_triton.go:45-120) — which is precisely why those flows are
untestable in its suite (SURVEY §4 gap).

This layer keeps the capability but inverts the design:

* one generic :class:`Catalog` surface (``choices``/``validate`` by *kind*)
  instead of per-provider ad-hoc calls scattered through prompts;
* every catalog DEGRADES to "unknown" (``None``) when credentials, SDKs, or
  the network are absent — the workflows then accept input as given and let
  ``terraform plan`` be the validator, keeping every test hermetic;
* catalogs are constructed through a registry the tests (and users) can
  override with :class:`FakeCatalog`.

Kinds used by the providers: ``region`` ``zone`` ``machine_type`` ``image``
``instance_type`` ``ami`` ``location`` ``size`` ``network`` ``package``
``accelerator_type``.
"""

from __future__ import annotations

from typing import Any, Callable, Protocol

from tpu_kubernetes.config import Config


class CatalogError(Exception):
    """A *definitive* validation failure (the resource does not exist) —
    distinct from degradation (catalog can't tell), which is never an
    error."""


class Catalog(Protocol):
    def choices(self, kind: str, **scope: Any) -> list[str] | None:
        """Known-valid values for ``kind`` (e.g. zone machine types), or
        ``None`` when the catalog cannot tell (no creds / no network)."""
        ...

    def validate(self, kind: str, value: str, **scope: Any) -> str | None:
        """``None`` when valid or unknown; an error message when the value
        definitively does not exist."""
        ...


class NullCatalog:
    """Knows nothing, validates nothing — the hermetic default."""

    def choices(self, kind: str, **scope: Any) -> list[str] | None:
        return None

    def validate(self, kind: str, value: str, **scope: Any) -> str | None:
        return None


class FakeCatalog:
    """Test/dry-run catalog: seeded with {kind: [values]}."""

    def __init__(self, entries: dict[str, list[str]] | None = None):
        self.entries = dict(entries or {})
        self.queries: list[tuple[str, dict[str, Any]]] = []

    def choices(self, kind: str, **scope: Any) -> list[str] | None:
        self.queries.append((kind, scope))
        return self.entries.get(kind)

    def validate(self, kind: str, value: str, **scope: Any) -> str | None:
        self.queries.append((kind, scope))
        known = self.entries.get(kind)
        if known is None or value in known:
            return None
        return f"{kind} {value!r} not found (known: {sorted(known)})"


CatalogFactory = Callable[[Config], Catalog]
_FACTORIES: dict[str, CatalogFactory] = {}


def register_catalog_factory(provider: str, factory: CatalogFactory) -> None:
    _FACTORIES[provider] = factory


def get_catalog(provider: str, cfg: Config) -> Catalog:
    """Construct the provider's catalog; any construction failure (missing
    SDK, unreadable credentials) degrades to :class:`NullCatalog`.
    Memoized on the Config object: construction can mean a blocking OAuth
    token exchange, and the per-instance listing caches must survive across
    the several calls one build makes."""
    injected = cfg.peek("_catalog")
    if injected is not None:  # test/dry-run injection
        return injected
    cache: dict[str, Catalog] | None = getattr(cfg, "_catalog_cache", None)
    if cache is None:
        cache = {}
        setattr(cfg, "_catalog_cache", cache)
    if provider not in cache:
        factory = _FACTORIES.get(provider)
        try:
            cache[provider] = factory(cfg) if factory else NullCatalog()
        except Exception:
            cache[provider] = NullCatalog()
    return cache[provider]


def catalog_choices(
    catalog: Catalog, kind: str, fallback: list[str] | None = None,
    **scope: Any,
) -> list[str] | None:
    """Live choices when the catalog knows them, else ``fallback``."""
    live = catalog.choices(kind, **scope)
    return live if live else fallback


def catalog_validate(catalog: Catalog, kind: str, value: str, **scope: Any) -> None:
    """Raise :class:`CatalogError` on a definitive mismatch; silent when the
    catalog cannot tell (hermetic runs validate nothing)."""
    err = catalog.validate(kind, value, **scope)
    if err is not None:
        raise CatalogError(err)


def _register_builtin_factories() -> None:
    # deferred imports so an absent SDK never breaks `import tpu_kubernetes`
    from tpu_kubernetes.catalog import aws, azure, gcp, triton

    register_catalog_factory("gcp", gcp.factory)
    register_catalog_factory("gcp-tpu", gcp.factory)
    register_catalog_factory("aws", aws.factory)
    register_catalog_factory("azure", azure.factory)
    register_catalog_factory("triton", triton.factory)


_register_builtin_factories()
