"""Triton (Joyent) catalog: networks / images / packages via CloudAPI.

Reference analog: create/manager_triton.go:45-120 lists networks, images,
and packages through triton-go mid-prompt. CloudAPI authenticates with an
HTTP-Signature header over the ``Date`` header (RSA-SHA256 with the
account's SSH key, key id ``/<account>/keys/<md5-fingerprint>``) — done
here with ``cryptography`` directly, session injectable for tests.
"""

from __future__ import annotations

import base64
from email.utils import formatdate
from typing import Any

from tpu_kubernetes.config import Config


def _signer(key_path: str):
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding

    from pathlib import Path

    key = serialization.load_pem_private_key(
        Path(key_path).expanduser().read_bytes(), password=None
    )

    def sign(message: bytes) -> str:
        sig = key.sign(message, padding.PKCS1v15(), hashes.SHA256())
        return base64.b64encode(sig).decode()

    return sign


class TritonCatalog:
    """``session.get(url, headers=...)`` is the whole surface used."""

    def __init__(self, url: str, account: str, key_id: str,
                 sign, session: Any):
        self.url = url.rstrip("/")
        self.account = account
        self.key_id = key_id
        self.sign = sign
        self.session = session
        self._cache: dict[str, list[str] | None] = {}

    def _headers(self) -> dict[str, str]:
        date = formatdate(usegmt=True)
        signature = self.sign(f"date: {date}".encode())
        return {
            "Date": date,
            "Authorization": (
                f'Signature keyId="/{self.account}/keys/{self.key_id}",'
                f'algorithm="rsa-sha256",headers="date",'
                f'signature="{signature}"'
            ),
            "Accept": "application/json",
        }

    def _list(self, path: str, field: str = "name") -> list[str] | None:
        try:
            resp = self.session.get(
                f"{self.url}/{self.account}{path}",
                headers=self._headers(), timeout=15,
            )
            if resp.status_code != 200:
                return None
            return [it.get(field, "") for it in resp.json()] or None
        except Exception:
            return None

    def choices(self, kind: str, **scope: Any) -> list[str] | None:
        paths = {"network": "/networks", "image": "/images",
                 "package": "/packages"}
        if kind not in paths:
            return None
        if kind not in self._cache:
            self._cache[kind] = self._list(paths[kind])
        return self._cache[kind]

    def validate(self, kind: str, value: str, **scope: Any) -> str | None:
        known = self.choices(kind, **scope)
        if known is None or value in known:
            return None
        return f"Triton {kind} {value!r} not found for account {self.account}"


def factory(cfg: Config):
    import requests

    url = cfg.peek("triton_url")
    account = cfg.peek("triton_account")
    key_path = cfg.peek("triton_key_path")
    key_id = cfg.peek("triton_key_id")
    if not (url and account and key_path and key_id):
        raise LookupError("triton credentials not configured")
    return TritonCatalog(
        str(url), str(account), str(key_id), _signer(str(key_path)),
        requests.Session(),
    )
