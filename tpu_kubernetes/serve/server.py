"""HTTP inference server: the live-serving analog of the batch job
(serve/job.py) — what sits behind a Kubernetes Service instead of a Job.

A minimal stdlib server (zero dependencies, air-gap friendly) exposing:

  GET  /healthz            → {"status": "ok", "model": ..., ...}
                             (readiness probe; returns 503 until the
                             first compile has finished warming; carries
                             a one-glance metrics summary)
  GET  /metrics            → Prometheus text exposition of the process
                             registry (obs/metrics.py): request-latency /
                             TTFT / batch queue-wait / batch-size
                             histograms, tokens-generated and speculation
                             counters, compile-cache hits — scrape-ready
                             (docs/guide/observability.md)
  GET  /debug/trace/<id>   → the span tree of one request — queue wait,
                             batch/prefill/decode — looked up by the id
                             every response returns in ``X-Request-Id``
                             (inbound ``X-Request-Id`` is honored, so a
                             gateway's id traces end-to-end)
  GET  /v1/models          → the one resident model, OpenAI-list shaped
  POST /v1/completions     → {"prompt": str, "max_new_tokens"?: int,
                              "temperature"?: float, "top_k"?: int,
                              "top_p"?: float, "seed"?: int,
                              "stream"?: bool}  ("max_tokens" aliases)
                             ⇒ {"text": str, "tokens": int,
                              "prompt_tokens": int, "finish_reason":
                              "stop"|"length", "model": str}
                             — or, with "stream": true, a Server-Sent
                             Events response (``data: {json}`` frames
                             with OpenAI-shaped chunks, terminal
                             ``data: [DONE]``) whose pieces arrive as
                             tokens decode (a per-token decode_step loop
                             instead of the fused generate program;
                             UTF-8-safe: each piece is the delta of the
                             decoded prefix, so multi-byte characters
                             never split across frames)
  POST /v1/chat/completions→ OpenAI chat shape: {"messages": [{"role",
                             "content"}...], "max_tokens"?, ...,
                             "stream"?} ⇒ chat.completion (or SSE
                             chat.completion.chunk deltas). Messages
                             render as a plain role-prefixed transcript
                             (no model-specific template)

Model bring-up reuses the batch job's env contract exactly
(``load_serving_stack``: SERVE_MODEL / SERVE_HF_CHECKPOINT /
SERVE_TOKENIZER / SERVE_QUANT), plus SERVE_KV_QUANT for the int8 KV
cache, SERVE_EOS_ID (tokens after it are truncated from responses),
SERVER_HOST/SERVER_PORT, SERVER_BATCH/SERVER_BATCH_WINDOW_MS (dynamic
batching), SERVE_MAX_NEW as the per-request ``max_new_tokens`` cap,
SERVE_PREFIX_CACHE_MB (> 0 enables the prefix KV-cache: requests whose
prompts share a token prefix with earlier traffic prefill only the
suffix; bounded LRU, bytes gauge + hit/partial/miss counter),
SERVE_EARLY_EXIT_STEPS (the greedy decode loop's host-side liveness
check interval — finished rows stop costing decode steps; doubles as
the continuous engine's segment length),
SERVE_CONTINUOUS_BATCHING (=1: greedy default requests serve through a
persistent slot-based decode engine instead of round-based batching —
new requests are admitted into the running batch as finished rows
drain, per-row width buckets, SERVER_BATCH doubles as the slot count;
composes with SERVE_MESH — the engine's caches shard kv-heads over the
tensor axis and its programs run sharded (parallel/serving.py) — and
with MoE models, whose slots ride the same engine),
SERVE_MESH (e.g. ``tensor=4``) — tensor-sharded serving over this
host's chips, so models bigger than one chip's HBM serve live: the
fused generate program for sampled requests, and the slot engine
end-to-end when continuous batching is on (``expert`` axes are allowed
for MoE models; streaming and prompt-lookup stay single-device and say
so) — and
SERVE_PROMPT_LOOKUP (+SERVE_DRAFT_K/SERVE_NGRAM) — draft-model-free
speculative decoding for greedy requests, streaming included: host-side
n-gram proposals verified by one jitted (k+1)-token chunk per round, so
accepted guesses cut the per-token weight streams the request pays.
Token-exact (verification keeps the target's own greedy choices); the
per-request acceptance telemetry rides the response ``spec`` field and
cumulative totals ride /healthz.

TPU-first serving discipline:

* **Bucketed compiles.** Prompts are right-padded to power-of-two widths
  and served ragged (``prompt_lengths``), so the number of distinct
  compiled programs is O(log max_seq) per sampling configuration — not
  one per prompt length. Programs are cached by their static signature
  (max_new, sampling knobs) in ServingState, one jitted callable each,
  and jax.jit's shape cache handles the width buckets under it.
* **One program on the chip at a time.** A lock serializes generation
  (the chip is the bottleneck; queueing in the server beats queueing in
  PJRT), while the ThreadingHTTPServer keeps health checks responsive
  during long generations.
* **Dynamic batching** (SERVER_BATCH > 1): concurrent GREEDY
  default-sampling requests coalesce into one ragged right-padded batch
  — the amortize-the-weight-stream lever (docs/design/
  serving-performance.md) applied to live traffic. Only greedy requests
  batch, because the ragged-row identity (models/decode.py) makes a
  batched greedy row token-identical to serving it alone (up to the
  documented cache-span float-tie caveat, generate ``cache_span``); MoE
  models serve solo (their capacity is batch-width-dependent) and the
  dispatcher only joins requests whose COMBINED width/max_new stays in
  max_seq — two individually-valid requests can be jointly invalid.
  Sampled/streamed requests run solo. The batch dimension is static
  (pad rows replicate row 0), so ANY load shares one compiled program
  per bucket; SERVER_BATCH_WINDOW_MS (default 5) bounds the added
  latency waiting for co-riders.
* Startup warms the default bucket so the readiness probe flips only
  when real traffic would be served at full speed.

The reference provisioner has no inference plane (SURVEY §0); this
completes provision → import weights → quantize → serve-over-HTTP.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpu_kubernetes.obs import REGISTRY, events
from tpu_kubernetes.obs import metrics as obs_metrics
from tpu_kubernetes.obs import tracing
from tpu_kubernetes.obs.faults import FAULTS
from tpu_kubernetes.obs.ledger import LEDGER
from tpu_kubernetes.obs.profile import PhaseProfiler
from tpu_kubernetes.serve.resilience import (
    CANCELLED_TOTAL,
    DEADLINE_TOTAL,
    ENGINE_RESTARTS,
    AdmissionController,
    Cancelled,
    DeadlineExceeded,
    DrainController,
    Draining,
    Overloaded,
    Watchdog,
    deadline_from,
    expired,
    warn_once,
)
from tpu_kubernetes.util import log
from tpu_kubernetes.util.trace import TRACER, span_tree

# -- serving telemetry (obs/metrics.py): registered at import so every
# family is present in GET /metrics from the first scrape, samples or not.
_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0, 60.0,
)
REQUEST_SECONDS = REGISTRY.histogram(
    "tpu_serve_request_seconds",
    "end-to-end request latency by endpoint",
    labelnames=("endpoint",), buckets=_LATENCY_BUCKETS,
)
TTFT_SECONDS = REGISTRY.histogram(
    "tpu_serve_time_to_first_token_seconds",
    "streaming requests: receipt to first emitted piece",
    buckets=_LATENCY_BUCKETS,
)
QUEUE_SECONDS = REGISTRY.histogram(
    "tpu_serve_batch_queue_seconds",
    "dynamic batching: enqueue to dispatch wait",
    buckets=_LATENCY_BUCKETS,
)
BATCH_SIZE = REGISTRY.histogram(
    "tpu_serve_batch_size",
    "dynamic batching: rows per dispatched batch",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
)
REQUESTS_TOTAL = REGISTRY.counter(
    "tpu_serve_requests_total",
    "HTTP requests served, by endpoint and status code",
    labelnames=("endpoint", "code"),
)
TOKENS_GENERATED = REGISTRY.counter(
    "tpu_serve_tokens_generated_total",
    "completion tokens emitted (warm-up excluded)",
)
PROMPT_TOKENS = REGISTRY.counter(
    "tpu_serve_prompt_tokens_total",
    "prompt tokens consumed (warm-up excluded)",
)
SPEC_ROUNDS = REGISTRY.counter(
    "tpu_serve_spec_rounds_total",
    "speculative decoding: target verify passes (solo lookup rounds, "
    "prefill included, plus slot-engine verify rounds)",
)
SPEC_DRAFTED = REGISTRY.counter(
    "tpu_serve_spec_drafted_total",
    "speculative decoding: tokens proposed, by proposer (ngram = "
    "host-side prompt lookup, draft = the draft model)",
    labelnames=("source",),
)
SPEC_ACCEPTED = REGISTRY.counter(
    "tpu_serve_spec_accepted_total",
    "speculative decoding: proposed tokens the target kept, by "
    "proposer (accepted/drafted is the acceptance rate the monitor's "
    "SPEC% column shows)",
    labelnames=("source",),
)
PROGRAM_CACHE = REGISTRY.counter(
    "tpu_serve_program_cache_total",
    "compiled-program cache lookups (miss = a fresh jit wrapper)",
    labelnames=("result",),
)
INFLIGHT = REGISTRY.gauge(
    "tpu_serve_inflight_requests",
    "requests currently inside a handler (the server-side queue depth "
    "a fleet monitor watches — generation serializes on one lock)",
)
PREFIX_CACHE_TOTAL = REGISTRY.counter(
    "tpu_serve_prefix_cache_total",
    "prefix KV-cache lookups by outcome (hit = a stored prefix fully "
    "reused, partial = the prompt diverged inside a stored prefix, "
    "miss = cold prefill; warm-up excluded)",
    labelnames=("result",),
)
PREFIX_CACHED_TOKENS = REGISTRY.histogram(
    "tpu_serve_prefix_cached_tokens",
    "prompt tokens served from the prefix cache per warm prefill",
    buckets=(16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0, 2048.0),
)
PREFIX_CACHE_BYTES = REGISTRY.gauge(
    "tpu_serve_prefix_cache_bytes",
    "resident bytes of cached KV prefix segments (LRU-evicted under "
    "the SERVE_PREFIX_CACHE_MB cap)",
)
DECODE_STEPS_SAVED = REGISTRY.counter(
    "tpu_serve_decode_steps_saved_total",
    "decode scan steps skipped by the all-rows-done early exit "
    "(per dispatched generation, vs the bucketed run length)",
)
BATCH_TAINT = REGISTRY.counter(
    "tpu_serve_batch_taint_total",
    "dispatcher selection failures that tainted a whole pending round "
    "(every selected entry fails out; submit() never hangs)",
)
SLOT_OCCUPANCY = REGISTRY.gauge(
    "tpu_serve_slot_occupancy",
    "continuous batching: slots holding a live request (out of the "
    "engine's fixed slot count — sustained saturation means add slots "
    "or replicas)",
)
ADMISSION_WAIT = REGISTRY.histogram(
    "tpu_serve_admission_wait_seconds",
    "continuous batching: enqueue to slot-insert wait (how long a "
    "request waited for a free slot + its prefill)",
    buckets=_LATENCY_BUCKETS,
)
SLOTS_RECYCLED = REGISTRY.counter(
    "tpu_serve_slots_recycled_total",
    "continuous batching: finished rows drained and their slots freed "
    "for the next queued request",
)
KV_PAGES = REGISTRY.gauge(
    "tpu_serve_kv_pages",
    "paged KV pool pages by state (free = wiped, on the free list; "
    "live = referenced by a resident slot; pinned = owned by the "
    "prefix store) — free+live+pinned always equals the pool size, a "
    "drifting sum is a page leak",
    labelnames=("state",),
)
PAGE_STALLS = REGISTRY.counter(
    "tpu_serve_kv_page_stalls_total",
    "paged engine: admissions or segment top-ups blocked on an empty "
    "free list (the paged analog of slot exhaustion — sustained stalls "
    "mean grow SERVE_KV_POOL_MB; each stall feeds the ledger's bubble "
    "fraction an explanation)",
)
PAGE_PREEMPTIONS = REGISTRY.counter(
    "tpu_serve_kv_page_preemptions_total",
    "paged engine: resident rows preempted (pages reclaimed, entry "
    "requeued for deterministic greedy recompute) to top up an older "
    "row's table",
)
# device-synced phase attribution (obs/profile.py): prefill / decode /
# fused-generate device seconds split by mode — "compile" is a program's
# first call (jit trace + XLA compile ride on it), "execute" is steady
# state. Summarized at GET /debug/profile, scraped as this histogram.
PROFILER = PhaseProfiler(
    metric="tpu_serve_phase_seconds",
    help="device-synced serving phase seconds (mode=compile is a "
         "program's first call including trace+compile; mode=execute "
         "is steady state)",
)
# the *_info idiom (register_build_info): constant-1 gauge whose payload
# is the label — the fleet aggregator joins this onto its saturation
# family and the monitor's ROLE column, so disaggregated tiers
# (SERVE_ROLE=prefill / decode / …) balance independently
ROLE_INFO = REGISTRY.gauge(
    "tpu_serve_role_info",
    "this instance's serving role (SERVE_ROLE; constant 1, the role "
    "rides the label)",
    labelnames=("role",),
)


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


# prompts below this length are not worth a prefix-cache entry (warm-up
# probes would pollute the store) and matches below it are not worth a
# resume program; also the floor of _pow2_floor
MIN_PREFIX_TOKENS = 16

# how long an IDLE slot-engine scheduler waits before waking to run an
# alert tick anyway (alerts must resolve and incidents must close on a
# quiet engine, not only while traffic flows)
_ALERT_IDLE_WAIT_S = 0.5


def _pow2_floor(n: int, lo: int = MIN_PREFIX_TOKENS) -> int:
    """Largest power of two <= n (0 when n < lo). Reused-prefix lengths
    are quantized DOWN to powers of two so the resume programs keep the
    O(log max_seq) shape discipline of everything else — a raw match
    length would mean one compile per distinct prefix."""
    if n < lo:
        return 0
    b = lo
    while b * 2 <= n:
        b *= 2
    return b


def _is_greedy(temperature, top_k, top_p) -> bool:
    """Default-sampling test shared by complete()/stream(): only these
    requests may batch or speculate (both rely on greedy determinism)."""
    return (
        float(temperature) == 0.0 and int(top_k) == 0
        and float(top_p) == 0.0
    )


def _bucket_max_new(n: int, cap: int) -> int:
    """Round a requested max_new up to a power-of-two bucket (≤ cap):
    compiled programs are keyed on max_new, so raw client values would
    mean one compile per distinct request size — a trivially triggerable
    availability hole with compiles serialized under the generation
    lock. Responses still truncate to the REQUESTED budget."""
    return min(_bucket(n, lo=8), cap)


class _Batcher:
    """Coalesce concurrent greedy requests into one ragged batch.

    Requests enqueue and block; a dispatcher thread wakes on the first
    arrival, waits ``window_ms`` for co-riders, takes up to
    ``max_batch``, runs ONE batched program, and fans the per-row
    results back. Each row is truncated to its own requested
    max_new_tokens (the batch runs to the max — or until the early-exit
    check finds no row live), so co-riding never changes a response."""

    def __init__(self, run_batch, max_batch: int, window_ms: float,
                 fits=None, on_wait=None):
        self._run_batch = run_batch        # (entries) → None, sets results
        self.max_batch = max_batch
        self.window_s = window_ms / 1e3
        # fits(selected, entry): may entry join this batch? (e.g. the
        # combined width/max_new span must stay within max_seq — two
        # individually-valid requests can be jointly invalid)
        self._fits = fits or (lambda selected, entry: True)
        # on_wait(seconds): admission control learns its queue-wait
        # estimate from the same waits QUEUE_SECONDS records
        self._on_wait = on_wait
        self._queue: list[dict] = []
        self._cond = threading.Condition()
        self._thread = threading.Thread(target=self._dispatch, daemon=True)
        self._thread.start()

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def enqueue(self, ids: list, max_new: int,
                budget: int | None = None,
                deadline: float | None = None) -> dict:
        """Queue a request; returns the entry. ``entry["dispatched"]``
        fires when the dispatcher selects it into a batch (the end of its
        queue wait) and ``entry["event"]`` when its result is ready —
        split so the caller can time the two stages as separate trace
        spans. ``budget`` is the REQUESTED max_new (≤ the bucketed
        ``max_new`` the program runs) — the early-exit decode loop stops
        counting a row live once its own budget is emitted. ``deadline``
        (monotonic) makes the dispatcher fail the entry out instead of
        selecting it once expired."""
        entry = {
            "ids": ids, "max_new": max_new, "t_enq": time.monotonic(),
            "budget": max_new if budget is None else budget,
            "deadline": deadline,
            "event": threading.Event(), "dispatched": threading.Event(),
            "tokens": None, "error": None,
        }
        with self._cond:
            self._queue.append(entry)
            self._cond.notify()
        return entry

    @staticmethod
    def result(entry: dict) -> list:
        entry["event"].wait()
        if entry["error"] is not None:
            raise entry["error"]
        return entry["tokens"]

    def submit(self, ids: list, max_new: int) -> list:
        return self.result(self.enqueue(ids, max_new))

    def _dispatch(self) -> None:
        # the loop body may never raise: submit() blocks forever on a
        # dead dispatcher, so ANY failure (a fits() bug, a selection
        # invariant break) fails the affected entries out instead
        while True:
            with self._cond:
                while not self._queue:
                    self._cond.wait()
                # let co-riders arrive — but wake EARLY the moment a
                # full batch is queued (sleeping out the rest of the
                # window with max_batch entries already waiting would
                # be pure added latency); enqueue() notifies per entry
                deadline = time.monotonic() + self.window_s
                while len(self._queue) < self.max_batch:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(timeout=left)
                pending, self._queue = self._queue, []
            # deadline reaping happens HERE, the one point every entry
            # passes before costing device time: an expired entry is
            # failed out (504), never selected — one slow round cannot
            # cascade a queue full of already-dead requests into more
            # dead rounds
            now = time.monotonic()
            live: list[dict] = []
            for entry in pending:
                if expired(entry.get("deadline"), now):
                    DEADLINE_TOTAL.labels("queued").inc()
                    entry["error"] = DeadlineExceeded(
                        "deadline expired while queued for dispatch"
                    )
                    entry["dispatched"].set()
                    entry["event"].set()
                else:
                    live.append(entry)
            pending = live
            if not pending:
                continue
            batch: list[dict] = []
            rest: list[dict] = []
            err: Exception | None = None
            try:
                for entry in pending:
                    if (len(batch) < self.max_batch
                            and self._fits(batch, entry)):
                        batch.append(entry)
                    else:
                        rest.append(entry)   # next dispatch round
                if not batch:
                    # every entry passed _validate, so fits([], head)
                    # always admits the head; if that invariant ever
                    # breaks, fail the round out rather than spinning
                    # on an unselectable head
                    raise RuntimeError(
                        "dispatcher selected nothing from a nonempty queue"
                    )
            except Exception as e:  # noqa: BLE001 — selection failure
                batch, rest = pending, []    # taints the whole round
                err = e
                BATCH_TAINT.inc()
            else:
                # queue-wait = enqueue → dispatch (the latency cost of
                # waiting for co-riders); batch size = rows that co-rode
                now = time.monotonic()
                for entry in batch:
                    QUEUE_SECONDS.observe(now - entry["t_enq"])
                    if self._on_wait is not None:
                        self._on_wait(now - entry["t_enq"])
                    entry["dispatched"].set()
                BATCH_SIZE.observe(len(batch))
                try:
                    self._run_batch(batch)
                except Exception as e:  # noqa: BLE001 — fan the error out
                    err = e
            for entry in batch:
                if err is not None:
                    entry["error"] = err
                entry["dispatched"].set()  # idempotent; covers the taint path
                entry["event"].set()
            if rest:
                # re-appending under the lock is enough: the dispatcher
                # (the only _cond waiter) is this thread, and it loops
                # straight back to the queue check
                with self._cond:
                    self._queue = rest + self._queue


class _ContinuousEngine:
    """Continuous in-flight batching: a persistent slot-based decode
    engine (SERVE_CONTINUOUS_BATCHING=1) replacing the round-based
    _Batcher for greedy default-sampling requests.

    One scheduler thread owns a fixed (slots, max_seq) batch cache.
    Between K-step ``decode_segment_slots`` programs it drains finished
    slots (fanning results back to their waiting handlers), pulls
    queued requests, prefills each at its OWN power-of-two width bucket
    (warm-prefix resume included — ``_prefill_any`` is the shared
    policy point, so a prefix hit lands in a slot exactly like a cold
    prefill), and grafts the row into a free slot with a jitted,
    donated ``cache_insert_row``. A request arriving mid-decode waits
    at most one segment for admission instead of a whole stranger
    round, and a finished row's slot is recycled immediately instead of
    riding dead to the round's end. Width bucketing is per-ROW: one
    long prompt no longer inflates every co-rider's width.

    The program set stays O(log max_seq): per-width prefill programs
    (shared with solo serving), ONE insert program, ONE clear program,
    ONE segment program (the batch shape is fixed). Entries share the
    _Batcher dict shape so complete() consumes both identically, and
    per-row decode is token-identical to solo greedy (models/decode.py
    SlotState — the ragged-row independence argument; MoE rides the
    same engine because the fixed slot batch makes expert capacity a
    constant shape no co-rider can change). The generation lock is
    taken per prefill/segment and released between, so
    solo/streaming/sampled requests interleave with a busy engine.

    Under SERVE_MESH the engine runs SHARDED end-to-end: the persistent
    caches are device_put with KV heads over the tensor axis
    (parallel/serving.py kv_tree_shardings), data-movement programs run
    under full-manual shard_map (_kv_program), model programs under
    explicit-sharding jit (_model_program), and page tables / SlotState
    stay replicated — deadline reaps, engine restarts, and prefix
    pins/resumes all operate on the sharded arrays through the same
    code paths."""

    def __init__(self, state: "ServingState", slots: int, seg_steps: int,
                 page_size: int = 16, pool_mb: float = 0.0,
                 flightrec=None, alerts=None):
        import numpy as np

        from tpu_kubernetes.models.decode import init_cache

        self._state = state
        # the flight recorder (obs/flightrec.py) gets one snapshot per
        # segment and a postmortem dump on every reset — set before the
        # scheduler thread starts so the first segment can feed it
        self._flightrec = flightrec
        # the engine-local alert manager (obs/alerts.py): tripwires
        # evaluated on THIS thread between scheduler passes (and on a
        # timed idle wait, so alerts resolve and incidents close without
        # traffic), throttled by TPU_K8S_ALERT_TICK_S
        self._alerts = alerts
        from tpu_kubernetes.util.envparse import env_float

        self._alert_tick_s = env_float("TPU_K8S_ALERT_TICK_S", 1.0,
                                       env=state.env)
        self._last_alert_tick = 0.0
        self.slots = slots
        self.seg_steps = max(1, seg_steps)
        self.span = state.cfg.max_seq
        ep = (state.mesh.shape.get("expert", 1)
              if state.mesh is not None else 1)
        if ep > 1 and slots % ep:
            raise ValueError(
                f"SERVER_BATCH ({slots} slots) must be divisible by "
                f"the expert mesh axis ({ep}) — expert-parallel decode "
                "splits the slot batch over experts"
            )
        self._cond = threading.Condition()
        self._queue: list[dict] = []
        # host-side slot table: _entries[i] is the request occupying
        # slot i (None = free); the int32 arrays mirror SlotState and
        # are owned by the scheduler thread (other threads only read
        # them for one-glance stats)
        self._entries: list[dict | None] = [None] * slots
        self._collected: list[list[int]] = [[] for _ in range(slots)]
        self._pos = np.zeros(slots, np.int32)
        self._tok = np.zeros(slots, np.int32)
        self._rem = np.zeros(slots, np.int32)
        self._pl = np.zeros(slots, np.int32)
        self._ps = np.zeros(slots, np.int32)
        # -- speculative decoding (spec_source != None) -----------------
        # segments become VERIFY ROUNDS: each round runs the target once
        # over a (slots, draft_k+1) window — every live row's current
        # token plus its draft_k proposed continuations — accepts each
        # row's matched prefix (+ the target's own correction token) and
        # rolls the row's cache position back to what it accepted. The
        # proposals are HOST state: one buffer per slot, refilled at
        # admission and after every round from the proposer
        # (ngram_propose_host, or the draft model's bucketed batch-1
        # programs), so the compiled verify program has ONE static
        # signature per draft_k.
        self.spec_source = state.spec_source
        self._proposals: list[list[int]] = [[] for _ in range(slots)]
        self.recycled = 0
        self.restarts = 0
        # per-segment timeline feed: admissions/reaps since the last
        # segment record (scheduler-thread-only, like the slot arrays)
        self._last_admitted = 0
        self._last_reaped = 0
        # -- paged KV mode (SERVE_KV_POOL_MB > 0) -----------------------
        # HBM is a fixed page pool instead of a dense (slots, max_seq)
        # block: concurrency is bounded by LIVE tokens, not worst-case
        # context. The page table + free list are host-owned (pages.py);
        # compiled programs only ever read the table, so every segment
        # is preceded by a host-side top-up and admission gates on the
        # free-page count instead of the slot count.
        self.paged = pool_mb > 0
        self._cache = None
        self._prefix = None
        if self.paged:
            from tpu_kubernetes.models.decode import (
                init_paged_pool,
                page_bytes,
            )
            from tpu_kubernetes.serve.pages import PagePool

            ps = int(page_size)
            if ps < 1 or (ps & (ps - 1)) or MIN_PREFIX_TOKENS % ps:
                raise ValueError(
                    f"SERVE_KV_PAGE_SIZE must be a power of two "
                    f"dividing {MIN_PREFIX_TOKENS}, got {ps} (width "
                    "buckets and reused-prefix lengths must be page-"
                    "aligned)"
                )
            self.page_size = ps
            # every row's table covers a full span: the gathered
            # virtual cache is max_seq wide, the SAME attention shape
            # (and float reduction order) as the dense engine — the
            # token-identity bar
            self.max_pages = self.span // ps
            if self.span % ps:
                raise ValueError(
                    f"SERVE_KV_PAGE_SIZE {ps} must divide max_seq "
                    f"{self.span}"
                )
            self._page_nbytes = page_bytes(state.cfg, ps, state.kv_quant)
            num_pages = int(pool_mb * 2 ** 20) // self._page_nbytes
            if num_pages < self.max_pages:
                raise ValueError(
                    f"SERVE_KV_POOL_MB={pool_mb} holds {num_pages} "
                    f"pages of {ps} positions ({self._page_nbytes} B "
                    f"each); one full-span row needs {self.max_pages}"
                )
            self._pages = PagePool(num_pages)
            self._pool = self._shard_kv(init_paged_pool(
                state.cfg, num_pages, ps, kv_quant=state.kv_quant
            ))
            self._table = np.zeros((slots, self.max_pages), np.int32)
            self._slot_pages: list[list[int]] = [[] for _ in range(slots)]
            # admission order, for youngest-first preemption
            self._admit_seq = np.zeros(slots, np.int64)
            self._seq = 0
            if state.prefix_cache is not None:
                from tpu_kubernetes.serve.prefix_cache import PrefixCache

                # the engine's OWN store holds page ids over THIS pool
                # (zero-copy warm starts); the byte store keeps serving
                # the solo/streaming paths untouched. No on_bytes: the
                # solo store owns that gauge, pinned pages show up in
                # tpu_serve_kv_pages{state="pinned"} instead
                self._prefix = PrefixCache(
                    state.prefix_cache.max_bytes,
                    sig=state.prefix_cache.sig,
                    on_evict=self._on_prefix_evict,
                )
            self._update_page_gauge()
        else:
            self._cache = self._shard_kv(init_cache(
                state.cfg, slots, self.span, kv_quant=state.kv_quant
            ))
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _shard_kv(self, tree):
        """device_put freshly initialized KV storage with the mesh's
        head-sharded layout (parallel/serving.py kv_tree_shardings) —
        identity on single-device serving. Applied at engine init and
        after a cold reset, so every program sees ONE stable sharding
        for the engine's whole life."""
        st = self._state
        if st.mesh is None:
            return tree
        from tpu_kubernetes.parallel.serving import kv_tree_shardings

        return st._jax.device_put(tree, kv_tree_shardings(tree, st.mesh))

    def enqueue(self, ids: list, max_new: int,
                deadline: float | None = None,
                cancel: threading.Event | None = None) -> dict:
        """Queue a request; same entry contract as _Batcher.enqueue
        (``dispatched`` fires at slot insert — the end of the admission
        wait — ``event`` when the row's tokens are ready), so
        complete() consumes engine and batcher entries through one
        code path (_Batcher.result). ``deadline`` (monotonic) and
        ``cancel`` make the scheduler fail the entry out of the queue —
        or retire it MID-FLIGHT from its slot — once it stops being
        worth serving."""
        entry = {
            "ids": ids, "max_new": max_new, "t_enq": time.monotonic(),
            "budget": max_new, "deadline": deadline, "cancel": cancel,
            "event": threading.Event(), "dispatched": threading.Event(),
            "tokens": None, "error": None,
            # the submitter's distributed trace id (contextvar, set by
            # the handler) — segment spans link to every resident
            # request's trace, and histogram exemplars cite it
            "trace": tracing.current_trace_id(),
        }
        with self._cond:
            self._queue.append(entry)
            self._cond.notify()
        return entry

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def stats(self) -> dict:
        """One-glance engine state for /healthz (the gauges/counters
        ride /metrics)."""
        with self._cond:
            queued = len(self._queue)
        out = {
            "slots": self.slots,
            "occupied": sum(e is not None for e in self._entries),
            "queued": queued,
            "segment_steps": self.seg_steps,
            "recycled": self.recycled,
            "restarts": self.restarts,
        }
        if self.paged:
            # the free/live/pinned partition (sums to total — a drift
            # IS a page leak) rides /healthz via continuous_batching
            out["pages"] = dict(self._pages.stats(),
                                page_size=self.page_size)
            if self._prefix is not None:
                out["pages"]["prefix_entries"] = len(self._prefix)
        return out

    # -- scheduler thread ---------------------------------------------------

    def _loop(self) -> None:
        # the loop body may never raise: a dead scheduler would hang
        # every future submitter, so any failure fails the affected
        # entries out and resets the engine cold (the _Batcher stance)
        while True:
            with self._cond:
                while not self._queue and all(
                    e is None for e in self._entries
                ):
                    if self._alerts is None:
                        self._cond.wait()
                    elif not self._cond.wait(timeout=_ALERT_IDLE_WAIT_S):
                        # idle timeout: fall through so the tripwires
                        # below still evaluate (alerts resolve and
                        # incidents close on a quiet engine)
                        break
                idle = not self._queue and all(
                    e is None for e in self._entries
                )
            try:
                if not idle:
                    self._reap()
                    self._admit()
                    self._run_segment()
            except Exception as e:  # noqa: BLE001 — surfaced per entry
                self._fail_out(e)
            self._alerts_tick()

    def _alerts_tick(self) -> None:
        """Evaluate the engine-local tripwires on the scheduler thread,
        throttled to TPU_K8S_ALERT_TICK_S. Never raises, never blocks on
        sink I/O (deliveries ride the manager's notifier thread)."""
        if self._alerts is None:
            return
        now = time.time()
        if now - self._last_alert_tick < self._alert_tick_s:
            return
        self._last_alert_tick = now
        try:
            from tpu_kubernetes.obs.alerts import engine_local_context

            self._alerts.evaluate(engine_local_context(
                self._alerts.rules, now,
                store=(self._flightrec.store
                       if self._flightrec is not None else None),
            ))
        except Exception:  # noqa: BLE001 — alerting must not take down
            pass           # the scheduler

    def _reap(self) -> None:
        """Retire expired/cancelled RESIDENT rows mid-flight: the entry
        fails out (504 / cancelled), its slot is cache_clear_row-reset
        and its budget returns to admission — one slow client's
        deadline never holds a slot against queued traffic. Runs before
        _admit so a freed slot admits in the same scheduler pass."""
        reaped = False
        now = time.monotonic()
        for i, entry in enumerate(self._entries):
            if entry is None:
                continue
            cancel = entry.get("cancel")
            if cancel is not None and cancel.is_set():
                CANCELLED_TOTAL.labels("engine").inc()
                cls = "cancelled"
                entry["error"] = Cancelled(
                    "request cancelled — slot retired mid-flight"
                )
            elif expired(entry.get("deadline"), now):
                DEADLINE_TOTAL.labels("resident").inc()
                cls = "expired"
                entry["error"] = DeadlineExceeded(
                    "deadline expired mid-decode — slot retired"
                )
            else:
                continue
            # settle the row's decoded-but-undelivered tokens BEFORE
            # _retire clears _collected — drop this and the chaos
            # conservation test catches the leak
            if self._state.ready:
                LEDGER.settle(cls, len(self._collected[i]),
                              device_s=entry.get("_device_s") or 0.0)
            entry["dispatched"].set()
            entry["event"].set()
            self._retire(i)
            self._last_reaped += 1
            reaped = True
        if reaped:
            SLOT_OCCUPANCY.set(sum(e is not None for e in self._entries))

    def _admit(self) -> None:
        """Fill free slots from the queue (FIFO). Per-entry failures
        (a bad prefill) fail that entry out; the engine keeps serving.
        Entries whose deadline expired while queued are failed out
        WITHOUT spending a prefill on them."""
        while True:
            free = next(
                (i for i, e in enumerate(self._entries) if e is None),
                None,
            )
            if free is None:
                return
            stalled = False
            with self._cond:
                if not self._queue:
                    return
                entry = self._queue[0]
                if self.paged:
                    # paged admission gates on FREE PAGES, not free
                    # slots: the head entry waits until resident rows
                    # drain enough pages for its prompt + first decode
                    # page (conservative — a warm hit may need fewer)
                    width = _bucket(len(entry["ids"]))
                    required = min(
                        width // self.page_size + 1, self.max_pages,
                    )
                    stalled = self._pages.free_count() < required
                if not stalled:
                    self._queue.pop(0)
            if stalled:
                PAGE_STALLS.inc()
                if self._prefix is not None and len(self._prefix):
                    # reclaim the store's pinned pages before making
                    # the entry wait on live traffic (outside _cond —
                    # the eviction handler wipes device pages)
                    self._prefix.clear(notify=True)
                    self._update_page_gauge()
                    continue
                # resident rows hold the pages: the next segments drain
                # them; admission retries every scheduler pass
                return
            if expired(entry.get("deadline")):
                DEADLINE_TOTAL.labels("queued").inc()
                entry["error"] = DeadlineExceeded(
                    "deadline expired while queued for a slot"
                )
                entry["dispatched"].set()
                entry["event"].set()
                continue
            try:
                self._insert(entry, free)
            except Exception as e:  # noqa: BLE001 — this entry only
                entry["error"] = e
                entry["dispatched"].set()
                entry["event"].set()
                # a prefill (and possibly its sampled token) was spent
                # on an entry that now fails out: shed-spent
                if self._state.ready:
                    LEDGER.settle("shed-spent",
                                  entry.get("_decoded") or 0,
                                  device_s=entry.get("_device_s") or 0.0)
                # the graft may have half-landed: scrub the row so the
                # slot the next admission reuses is bitwise cold
                self._clear_row(free, best_effort=True)

    def _insert(self, entry: dict, slot: int) -> None:
        import numpy as np

        from tpu_kubernetes.models.decode import cache_insert_row

        FAULTS.fire("serve.slot_insert")
        st = self._state
        ids, budget = entry["ids"], entry["budget"]
        width = _bucket(len(ids))
        t0 = time.perf_counter()
        with st._lock:
            if self.paged:
                first = self._admit_paged(entry, slot, ids, budget,
                                          width)
            else:
                # per-row width bucket; span == width (zero generation
                # slots — decode happens in the engine cache, not the
                # row cache), so prefill programs are shared with solo
                # serving and the prefix store serves warm starts into
                # slots too
                logits, row = st._prefill_any(ids, width, width)
                first = int(np.argmax(np.asarray(logits)[0]))
                # production: the prefill's sampled token exists NOW —
                # if the graft below fails, _admit settles it shed-spent
                # via the _decoded mark (a pre-prefill fault produced
                # nothing)
                if st.ready:
                    LEDGER.emitted(1)
                entry["_decoded"] = 1
                if budget <= 1 or (st.eos_id is not None
                                   and first == st.eos_id):
                    # one-token budget or instant EOS: done, no slot
                    entry["tokens"] = [first]
                else:
                    ins = st._kv_program(
                        ("slot_insert",), cache_insert_row,
                        (self._cache, row, slot), donate=(0,),
                    )
                    self._cache = ins(self._cache, row, slot)
        entry["_device_s"] = time.perf_counter() - t0
        wait = time.monotonic() - entry["t_enq"]
        entry["_wait"] = wait    # rides the request's batch-span meta
        ADMISSION_WAIT.observe(wait, exemplar=entry.get("trace") or None)
        st.admission.observe_service(wait)
        if entry["tokens"] is not None:
            entry["dispatched"].set()
            entry["event"].set()
            return
        self._entries[slot] = entry
        self._collected[slot] = [first]
        self._pos[slot] = width
        self._tok[slot] = first
        self._rem[slot] = budget - 1     # the first token is emitted
        self._pl[slot] = len(ids)
        self._ps[slot] = width
        if self.spec_source is not None:
            self._refill_proposal(slot)
        self._last_admitted += 1
        if self.paged:
            # admission order feeds youngest-first preemption; the
            # store insert runs OUTSIDE st._lock — an eviction it
            # triggers wipes device pages, which takes that lock
            self._admit_seq[slot] = self._seq
            self._seq += 1
            self._prefix_store_paged(ids, slot)
            self._update_page_gauge()
        entry["dispatched"].set()
        SLOT_OCCUPANCY.set(sum(e is not None for e in self._entries))

    # -- paged-mode internals (scheduler thread only) -----------------------

    def _admit_paged(self, entry: dict, slot: int, ids: list,
                     budget: int, width: int) -> int:
        """Paged admission (caller holds st._lock): warm-or-cold
        prefill, then allocate the prompt's pages — a warm hit pins the
        store's shared pages into the table by REFERENCE, zero-copy —
        and scatter only the freshly computed suffix. Allocation is
        all-or-nothing: a shortfall raises, the entry fails out, and
        _admit's best-effort scrub releases whatever this method
        recorded in _slot_pages first. Returns the prefill's first
        sampled token."""
        import functools

        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models.decode import paged_insert_row

        st = self._state
        jax = st._jax
        logits, row, shared = self._prefill_paged(ids, width)
        first = int(np.argmax(np.asarray(logits)[0]))
        if st.ready:
            LEDGER.emitted(1)
        entry["_decoded"] = 1
        if budget <= 1 or (st.eos_id is not None and first == st.eos_id):
            # one-token budget or instant EOS: done without touching
            # the pool at all
            entry["tokens"] = [first]
            return first
        ps = self.page_size
        n_prompt = width // ps
        # +1 (when the span has room): the first decode write lands at
        # position `width`, in the page AFTER the prompt — taking it
        # now makes admission itself gate on the pool (free pages, not
        # free slots, are the concurrency bound the paged engine
        # advertises)
        extra = 1 if n_prompt < self.max_pages else 0
        need = n_prompt - len(shared) + extra
        got = self._pages.allocate(need)
        if got is None:
            PAGE_STALLS.inc()
            raise RuntimeError(
                f"page pool exhausted at admission ({need} pages "
                f"needed, {self._pages.free_count()} free)"
            )
        self._pages.ref(shared)
        row_pages = shared + got[:n_prompt - len(shared)]
        # record the holdings BEFORE the device scatter: if it throws,
        # the scrub in _admit releases exactly these
        self._slot_pages[slot] = row_pages + got[n_prompt - len(shared):]
        self._table[slot, :] = 0
        self._table[slot, :len(self._slot_pages[slot])] = \
            self._slot_pages[slot]
        skip = len(shared) * ps
        pages_arr = jnp.asarray(row_pages, jnp.int32)
        ins = st._kv_program(
            ("paged_insert", width, skip),
            functools.partial(paged_insert_row, skip=skip),
            (self._pool, row, pages_arr), donate=(0,),
        )
        self._pool = ins(self._pool, row, pages_arr)
        return first

    def _prefill_paged(self, ids: list, width: int):
        """→ (last-position logits, full-width row cache, shared page
        ids). The engine's OWN paged prefix store serves warm starts: a
        hit gathers the pinned pages into the resume base (bytes
        identical to what paged_insert_row scattered), prefills only
        the suffix, and reports the pages so _admit_paged can reference
        instead of copy them. Caller holds st._lock."""
        import types

        import jax.numpy as jnp

        from tpu_kubernetes.models.decode import gather_pages

        st = self._state
        FAULTS.fire("serve.prefill")
        q, entry = 0, None
        if self._prefix is not None:
            m, entry = self._prefix.lookup(ids)
            q = _pow2_floor(min(m, len(ids) - 1))
            if entry is None or q < MIN_PREFIX_TOKENS:
                q, entry, result = 0, None, "miss"
            else:
                result = "hit" if m >= len(entry.ids) else "partial"
            if st.ready:
                PREFIX_CACHE_TOTAL.labels(result).inc()
                if q:
                    PREFIX_CACHED_TOKENS.observe(float(q))
        if entry is None:
            logits, row = st._prefill_cold(
                st._pad_rows([ids], width), [len(ids)], width,
            )
            return logits, row, []
        # q is pow2 >= MIN_PREFIX_TOKENS and page_size divides
        # MIN_PREFIX_TOKENS, so the reuse length is page-aligned and
        # q < len(ids) <= width keeps at least one suffix page to
        # scatter
        shared = list(entry.pages[:q // self.page_size])
        shared_arr = jnp.asarray(shared, jnp.int32)
        gat = st._kv_program(
            ("page_gather", len(shared)), gather_pages,
            (self._pool, shared_arr),
        )
        base = gat(self._pool, shared_arr)
        arrays = {"k": base.k, "v": base.v}
        if base.k_scale is not None:
            arrays["k_scale"] = base.k_scale
            arrays["v_scale"] = base.v_scale
        shim = types.SimpleNamespace(arrays=arrays)
        logits, row = st._prefill_warm(ids, shim, q, width, width)
        return logits, row, shared

    def _prefix_store_paged(self, ids: list, slot: int) -> None:
        """Pin the new row's whole-page prompt prefix into the paged
        store (best-effort, like ServingState._prefix_insert): a True
        insert pins the pages, so they outlive the slot for future
        zero-copy warm hits. Whole pages only — the stored ids are
        trimmed to a page boundary so ids→pages maps exactly. Must NOT
        hold st._lock: an eviction this triggers wipes device pages."""
        if self._prefix is None:
            return
        n_pages = len(ids) // self.page_size
        n_store = n_pages * self.page_size
        if n_store < MIN_PREFIX_TOKENS:
            return
        try:
            FAULTS.fire("serve.prefix_insert")
            pages = self._slot_pages[slot][:n_pages]
            if self._prefix.insert_paged(
                ids[:n_store], pages, n_pages * self._page_nbytes,
            ):
                self._pages.pin(pages)
        except Exception as e:  # noqa: BLE001 — accelerator only
            warn_once(
                "prefix_insert_failed",
                f"prefix-cache insert failed (serving continues without "
                f"storing): {type(e).__name__}: {e}",
            )

    def _on_prefix_evict(self, entry) -> None:
        """Paged store eviction → pool unpin; pages no resident slot
        still reads come back wiped. PrefixCache fires this after
        releasing its own lock, and the engine only mutates the store
        outside st._lock, so the device wipe can take it."""
        freed = self._pages.unpin(list(entry.pages))
        self._wipe_pages(freed)
        self._update_page_gauge()

    def _wipe_pages(self, pages: list[int]) -> None:
        """Device-wipe freed pages back to init values before the free
        list reuses them — reuse of stale K/V would break the bitwise
        cold-start guarantee the identity tests pin down. Chunked
        through ONE compiled program: a fixed max_pages-length index
        array padded with an out-of-range sentinel (scatter mode="drop"
        ignores the padding)."""
        if not pages:
            return
        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models.decode import paged_clear_pages

        st = self._state
        sentinel = self._pages.total + 1
        with st._lock:
            for i in range(0, len(pages), self.max_pages):
                chunk = np.full(self.max_pages, sentinel, np.int32)
                part = pages[i:i + self.max_pages]
                chunk[:len(part)] = part
                chunk_arr = jnp.asarray(chunk)
                clr = st._kv_program(
                    ("page_clear", self.max_pages), paged_clear_pages,
                    (self._pool, chunk_arr), donate=(0,),
                )
                self._pool = clr(self._pool, chunk_arr)

    def _topup_pages(self, adv_cap: int | None = None) -> None:
        """Pre-segment host allocation: grow every live row's table to
        cover the positions the next segment will write (compiled
        programs never allocate — static shapes). ``adv_cap`` overrides
        the per-row advance bound (the verify loop passes draft_k+1 —
        one round's emittable extent; window writes past it fall
        through the zero table entries into the page-0 sink, and the
        garbage tokens those produce are clipped by the budget before
        they can be emitted). Pool pressure escalates in strict order:
        (1) drop the prefix store's pinned pages, (2) preempt the
        YOUNGEST other resident row — greedy decode is deterministic,
        so readmission re-emits its tokens identically — (3) fail the
        row out (the pool cannot hold even this one row). Each rung
        strictly shrinks demand, so the loop terminates."""
        import math

        cap = self.seg_steps if adv_cap is None else adv_cap
        for i, entry in enumerate(self._entries):
            if entry is None:
                continue
            adv = min(cap, int(self._rem[i]))
            need = min(
                math.ceil((int(self._pos[i]) + adv) / self.page_size),
                self.max_pages,
            )
            while (self._entries[i] is not None
                   and len(self._slot_pages[i]) < need):
                got = self._pages.allocate(
                    need - len(self._slot_pages[i])
                )
                if got is not None:
                    self._slot_pages[i].extend(got)
                    n = len(self._slot_pages[i])
                    self._table[i, n - len(got):n] = got
                    continue
                PAGE_STALLS.inc()
                if self._prefix is not None and len(self._prefix):
                    self._prefix.clear(notify=True)
                    continue
                victim = self._pick_victim(exclude=i)
                if victim is not None:
                    self._preempt(victim)
                    continue
                self._fail_entry(i, RuntimeError(
                    f"page pool ({self._pages.total} pages) too small "
                    f"for a resident row needing {need}"
                ))
        self._update_page_gauge()

    def _pick_victim(self, exclude: int) -> int | None:
        """Youngest resident row by admission order (it has the least
        sunk decode work to recompute), never the row being topped up."""
        best, best_seq = None, -1
        for j, e in enumerate(self._entries):
            if e is None or j == exclude:
                continue
            if self._admit_seq[j] > best_seq:
                best, best_seq = j, int(self._admit_seq[j])
        return best

    def _preempt(self, slot: int) -> None:
        """Evict a resident row to reclaim its pages, requeuing the
        entry at the HEAD of the queue for recompute-from-scratch.
        Tokens already decoded are settled shed-spent (that device work
        is repeated); greedy determinism means the readmitted row
        re-emits them identically, so preemption costs latency, never
        correctness."""
        PAGE_PREEMPTIONS.inc()
        entry = self._entries[slot]
        if self._state.ready:
            LEDGER.settle("shed-spent", len(self._collected[slot]),
                          device_s=entry.get("_device_s") or 0.0)
        entry["_decoded"] = 0
        entry["_device_s"] = 0.0
        self._release_slot(slot)
        with self._cond:
            self._queue.insert(0, entry)
            self._cond.notify()

    def _fail_entry(self, slot: int, err: Exception) -> None:
        """Fail ONE resident row out (the paged top-up dead end):
        settle its decoded tokens shed-spent, surface the error, free
        its pages. The engine keeps serving everything else."""
        entry = self._entries[slot]
        if self._state.ready:
            LEDGER.settle("shed-spent", len(self._collected[slot]),
                          device_s=entry.get("_device_s") or 0.0)
        entry["error"] = err
        entry["dispatched"].set()
        entry["event"].set()
        self._retire(slot)

    def _update_page_gauge(self) -> None:
        s = self._pages.stats()
        for state in ("free", "live", "pinned"):
            KV_PAGES.labels(state).set(s[state])

    def _run_segment(self) -> None:
        """One K-step mixed-batch segment, then drain finished rows.
        The lock is held only for the segment itself, so other request
        modes interleave between segments."""
        import functools

        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models.decode import (
            SlotState,
            decode_segment_slots,
        )

        st = self._state
        if all(e is None for e in self._entries):
            return
        if self.spec_source is not None:
            self._run_segment_spec()
            return
        FAULTS.fire("serve.segment")
        if st.mesh is not None:
            # the sharded-segment chaos site: a mesh-mode segment can
            # die in the sharded program itself (a chip drops out of
            # the collective) — injected failures here must leave page
            # and ledger conservation intact
            FAULTS.fire("serve.shard_segment")
        steps = self.seg_steps
        if self.paged:
            # host-side allocation happens HERE, outside the compiled
            # program: every live row's table must cover the positions
            # this segment writes (may preempt or fail rows under pool
            # pressure — re-check liveness after)
            self._topup_pages()
            if all(e is None for e in self._entries):
                return
        state = SlotState(
            tok=jnp.asarray(self._tok), pos=jnp.asarray(self._pos),
            remaining=jnp.asarray(self._rem),
            prompt_lengths=jnp.asarray(self._pl),
            prompt_slots=jnp.asarray(self._ps),
        )
        occupied = sum(e is not None for e in self._entries)
        row_steps = steps * self.slots
        t0 = time.perf_counter()
        with st._lock:
            if self.paged:
                from tpu_kubernetes.models.decode import (
                    decode_segment_paged,
                )

                key = ("paged_segment", steps)
                args = (st.params, self._pool,
                        jnp.asarray(self._table), state)
                seg = st._model_program(
                    key, functools.partial(
                        decode_segment_paged, cfg=st.cfg, steps=steps,
                        eos_id=st.eos_id, pad_id=0,
                    ), args, donate=(1,), ep=True,
                )
            else:
                key = ("slot_segment", steps)
                args = (st.params, self._cache, state)
                seg = st._model_program(
                    key, functools.partial(
                        decode_segment_slots, cfg=st.cfg, steps=steps,
                        eos_id=st.eos_id, pad_id=0,
                    ), args, donate=(1,), ep=True,
                )
            PROFILER.record_cost(
                "decode", seg, args, tokens=row_steps, key=key,
            )
            with PROFILER.phase(
                "decode", key=key, tracer=TRACER,
            ) as pd:
                if self.paged:
                    toks, state, self._pool = pd.sync(seg(*args))
                else:
                    toks, state, self._cache = pd.sync(seg(*args))
        elapsed = time.perf_counter() - t0
        toks = np.asarray(toks)
        new_pos = np.asarray(state.pos)
        old_pos, self._pos = self._pos, new_pos.copy()
        self._tok = np.asarray(state.tok).copy()
        self._rem = np.asarray(state.remaining).copy()
        live = 0
        for i, entry in enumerate(self._entries):
            if entry is None:
                continue
            # a row emitted exactly as many tokens as its pos advanced
            # (frozen rows never advance) — pads never reach results
            emitted = int(new_pos[i] - old_pos[i])
            live += emitted
            self._collected[i].extend(toks[i][:emitted].tolist())
            # apportion the segment's device time by advance share; the
            # dead share (empty slots, frozen rows) settles as bubble
            entry["_device_s"] = (entry.get("_device_s") or 0.0) + \
                elapsed * emitted / row_steps
        # the traces this segment served — captured BEFORE the drain
        # below retires finished rows, so a request that completes in
        # this very segment still links to it
        seg_traces = sorted({
            e["trace"] for e in self._entries
            if e is not None and e.get("trace")
        })
        drained = 0
        if st.ready:
            # production: the device ran steps x slots row-steps; rows
            # that advanced are settled by their terminal site, the rest
            # (empty slots, done rows inside the segment) are bubble NOW
            LEDGER.emitted(row_steps)
            LEDGER.bubble(row_steps - live,
                          device_s=elapsed * (row_steps - live) / row_steps)
        for i, entry in enumerate(self._entries):
            if entry is not None and self._rem[i] <= 0:
                entry["tokens"] = self._collected[i]
                entry["event"].set()
                self._retire(i)
                drained += 1
        if self._flightrec is not None:
            # the black-box feed, BEFORE the per-segment counters zero:
            # a postmortem's last ring entry must carry this segment's
            # admissions/reaps, the page partition, and the ledger state
            self._flightrec.record_segment(
                steps=steps, slots=self.slots, occupied=occupied,
                live_steps=live, admitted=self._last_admitted,
                drained=drained, reaped=self._last_reaped,
                seconds=round(elapsed, 6), queued=self.depth(),
                pages=(dict(self._pages.stats()) if self.paged else None),
                ledger={
                    "emitted_delta": row_steps if st.ready else 0,
                    "unsettled": LEDGER.unsettled(),
                },
                # postmortem ↔ trace cross-ref: the resident requests'
                # distributed trace ids at segment time
                trace_ids=seg_traces,
            )
        if seg_traces:
            # one segment serves many requests: a span with LINKS to
            # every resident trace, so `get trace <id>` renders the
            # decode segments a request rode (annotated with the
            # segment's device-seconds and ledger token classes)
            TRACER.record(
                "segment", elapsed, links=seg_traces,
                steps=steps, live_steps=live, drained=drained,
                device_s=round(elapsed, 6),
                tokens_live=live, tokens_bubble=row_steps - live,
            )
        if st.ready:
            LEDGER.segment(
                steps=steps, slots=self.slots, occupied=occupied,
                live_steps=live, admitted=self._last_admitted,
                drained=drained, reaped=self._last_reaped,
                seconds=elapsed,
            )
            self._last_admitted = 0
            self._last_reaped = 0
        # intra-segment occupancy: MEAN live rows across the segment's
        # steps (a row that finishes mid-segment counts fractionally),
        # not the between-segments resident count the old gauge showed;
        # a fully drained engine still reads 0
        resident = sum(e is not None for e in self._entries)
        SLOT_OCCUPANCY.set(live / steps if resident else 0.0)

    # -- speculative segments (spec_source != None) -------------------------

    def _run_segment_spec(self) -> None:
        """The speculative segment: up to ``seg_steps`` VERIFY ROUNDS,
        then drain finished rows. One round = one fixed-shape
        (slots, draft_k+1) target pass — each live row's window is its
        current token plus its draft_k proposed continuations — with
        ragged per-row acceptance: the row keeps its matched draft
        prefix plus the target's own correction token, and its cache
        position rolls back to exactly what it accepted. Dense, the
        SlotState position rewind IS the rollback (rejected K/V is
        garbage above the new position, overwritten by the next window
        before anything can attend to it); paged, _truncate_pages
        returns whole pages past the accepted position to the pool.
        Emitted tokens are the target's own greedy choices at valid
        context, so they are BITWISE the sequential engine's —
        speculation moves throughput, never tokens."""
        import functools

        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models.decode import (
            SlotState,
            decode_verify_paged,
            decode_verify_slots,
        )

        st = self._state
        FAULTS.fire("serve.segment")
        if st.mesh is not None:
            FAULTS.fire("serve.shard_segment")
        k = st.draft_k
        cells = k + 1
        src = self.spec_source
        occupied = sum(e is not None for e in self._entries)
        rounds = 0
        live_total = 0          # accepted tokens → _collected
        cells_total = 0         # device row-cells produced (ledger)
        elapsed_total = 0.0
        for _ in range(self.seg_steps):
            if not any(
                e is not None and self._rem[i] > 0
                for i, e in enumerate(self._entries)
            ):
                break
            # the verify chaos site fires BEFORE the round's program: a
            # fault here leaves every completed round fully settled, so
            # ledger and page conservation hold mid-segment
            FAULTS.fire("serve.spec_verify")
            if self.paged:
                # cover one round's emittable extent (k+1 per row);
                # window writes past it fall through zero table entries
                # into the page-0 sink — the garbage tokens those rows
                # produce are budget-clipped before they can be
                # emitted. May preempt/fail rows: re-check liveness
                self._topup_pages(adv_cap=cells)
                if all(e is None for e in self._entries):
                    break
            old_rem = self._rem.copy()
            drafts_h = np.zeros((self.slots, k), np.int32)
            for i, e in enumerate(self._entries):
                if e is not None and old_rem[i] > 0:
                    buf = self._proposals[i][:k]
                    drafts_h[i, :len(buf)] = buf
            state = SlotState(
                tok=jnp.asarray(self._tok), pos=jnp.asarray(self._pos),
                remaining=jnp.asarray(self._rem),
                prompt_lengths=jnp.asarray(self._pl),
                prompt_slots=jnp.asarray(self._ps),
            )
            drafts = jnp.asarray(drafts_h)
            row_cells = self.slots * cells
            t0 = time.perf_counter()
            with st._lock:
                if self.paged:
                    key = ("paged_verify", k)
                    args = (st.params, self._pool,
                            jnp.asarray(self._table), state, drafts)
                    ver = st._model_program(
                        key, functools.partial(
                            decode_verify_paged, cfg=st.cfg,
                            eos_id=st.eos_id, pad_id=0,
                        ), args, donate=(1,), ep=True,
                    )
                else:
                    key = ("slot_verify", k)
                    args = (st.params, self._cache, state, drafts)
                    ver = st._model_program(
                        key, functools.partial(
                            decode_verify_slots, cfg=st.cfg,
                            eos_id=st.eos_id, pad_id=0,
                        ), args, donate=(1,), ep=True,
                    )
                PROFILER.record_cost(
                    "decode", ver, args, tokens=row_cells, key=key,
                )
                with PROFILER.phase(
                    "decode", key=key, tracer=TRACER,
                ) as pd:
                    if self.paged:
                        toks, state, self._pool = pd.sync(ver(*args))
                    else:
                        toks, state, self._cache = pd.sync(ver(*args))
            elapsed = time.perf_counter() - t0
            elapsed_total += elapsed
            rounds += 1
            cells_total += row_cells
            toks = np.asarray(toks)
            new_pos = np.asarray(state.pos)
            old_pos, self._pos = self._pos, new_pos.copy()
            self._tok = np.asarray(state.tok).copy()
            self._rem = np.asarray(state.remaining).copy()
            emitted_round = 0
            accepted_round = 0
            live_rows = 0
            for i, entry in enumerate(self._entries):
                if entry is None:
                    continue
                n = int(new_pos[i] - old_pos[i])
                out = toks[i][:n].tolist()
                self._collected[i].extend(out)
                emitted_round += n
                entry["_device_s"] = (entry.get("_device_s") or 0.0) + \
                    elapsed * n / row_cells
                if old_rem[i] > 0:
                    live_rows += 1
                    # accepted = the emitted prefix that IS the draft
                    # (the rest of the emission is the target's own
                    # correction token, not speculation credit)
                    for t in range(min(n, k)):
                        if out[t] != int(drafts_h[i, t]):
                            break
                        accepted_round += 1
            live_total += emitted_round
            live_cells = live_rows * cells
            if st.ready:
                # per-round conservation: every device cell is produced
                # here, and every cell that did NOT become a collected
                # token settles NOW — rejected cells of live rows as
                # speculative-waste, dead rows/empty slots as bubble.
                # row_cells == emitted + waste + bubble by construction,
                # and a fault before the NEXT round leaves nothing open
                LEDGER.emitted(row_cells)
                LEDGER.settle(
                    "speculative-waste", live_cells - emitted_round,
                    device_s=elapsed * (live_cells - emitted_round)
                    / row_cells,
                )
                LEDGER.bubble(
                    row_cells - live_cells,
                    device_s=elapsed * (row_cells - live_cells)
                    / row_cells,
                )
            SPEC_ROUNDS.inc(1)
            SPEC_DRAFTED.labels(src).inc(k * live_rows)
            SPEC_ACCEPTED.labels(src).inc(accepted_round)
            with st._spec_lock:
                st.spec_totals["rounds"] += 1
                st.spec_totals["drafted"] += k * live_rows
                st.spec_totals["accepted"] += accepted_round
            if self.paged:
                # rollback: whole pages past each row's accepted
                # position go back to the pool, wiped cold
                for i, e in enumerate(self._entries):
                    if e is not None:
                        self._truncate_pages(i)
                self._update_page_gauge()
            for i, e in enumerate(self._entries):
                if e is not None and self._rem[i] > 0:
                    self._refill_proposal(i)
        # -- drain + per-segment bookkeeping (mirrors _run_segment) -----
        seg_traces = sorted({
            e["trace"] for e in self._entries
            if e is not None and e.get("trace")
        })
        drained = 0
        for i, entry in enumerate(self._entries):
            if entry is not None and self._rem[i] <= 0:
                entry["tokens"] = self._collected[i]
                entry["event"].set()
                self._retire(i)
                drained += 1
        if self._flightrec is not None:
            self._flightrec.record_segment(
                steps=rounds * cells, slots=self.slots,
                occupied=occupied, live_steps=live_total,
                admitted=self._last_admitted, drained=drained,
                reaped=self._last_reaped,
                seconds=round(elapsed_total, 6), queued=self.depth(),
                pages=(dict(self._pages.stats()) if self.paged
                       else None),
                ledger={
                    "emitted_delta": cells_total if st.ready else 0,
                    "unsettled": LEDGER.unsettled(),
                },
                trace_ids=seg_traces,
            )
        if seg_traces:
            TRACER.record(
                "segment", elapsed_total, links=seg_traces,
                steps=rounds * cells, live_steps=live_total,
                drained=drained, device_s=round(elapsed_total, 6),
                tokens_live=live_total,
                tokens_bubble=cells_total - live_total,
            )
        if st.ready:
            LEDGER.segment(
                steps=rounds * cells, slots=self.slots,
                occupied=occupied, live_steps=live_total,
                admitted=self._last_admitted, drained=drained,
                reaped=self._last_reaped, seconds=elapsed_total,
            )
            self._last_admitted = 0
            self._last_reaped = 0
        resident = sum(e is not None for e in self._entries)
        SLOT_OCCUPANCY.set(
            live_total / (rounds * cells) if resident and rounds
            else 0.0
        )

    def _truncate_pages(self, slot: int) -> None:
        """Roll a row's page table back to its accepted extent — the
        pages the verify window wrote past the accepted position go
        back to the pool (wiped, so their next tenant starts bitwise
        cold; serve/pages.py refcounts keep shared prefix pages safe —
        the kept prefix always covers them, pos never rolls below the
        prompt). The kept tail page may hold rejected garbage above
        the accepted position; the next window rewrites exactly those
        positions before anything can attend to them."""
        pos = int(self._pos[slot])
        keep = (pos - 1) // self.page_size + 1 if pos > 0 else 0
        pages = self._slot_pages[slot]
        if len(pages) <= keep:
            return
        excess = pages[keep:]
        self._slot_pages[slot] = pages[:keep]
        self._table[slot, keep:] = 0
        freed = self._pages.release(excess)
        self._wipe_pages(freed)

    def _refill_proposal(self, slot: int) -> None:
        """(Re)fill the slot's host proposal buffer to draft_k tokens
        from the configured proposer, over the row's full served
        context (prompt + everything collected). Runs at admission and
        after every verify round — partial acceptance shifts the
        context, so stale proposals never survive a round."""
        st = self._state
        entry = self._entries[slot]
        ctx = list(entry["ids"]) + self._collected[slot]
        if self.spec_source == "draft":
            self._proposals[slot] = self._draft_propose(ctx)
        else:
            from tpu_kubernetes.models.speculative import (
                ngram_propose_host,
            )

            self._proposals[slot] = ngram_propose_host(
                ctx, st.ngram, st.draft_k, int(self._tok[slot])
            )

    def _draft_propose(self, ctx: list) -> list:
        """draft_k greedy tokens from the draft model over (a suffix
        of) ``ctx`` — one cached batch-1 program per (width, draft_k)
        signature, so the retrace sentinel sees a bounded program set.
        The context truncates to the largest width bucket the draft's
        max_seq can hold; proposals only move the acceptance rate, so
        truncation carries no correctness weight."""
        import functools

        import numpy as np

        from tpu_kubernetes.models import generate

        st = self._state
        k = st.draft_k
        width = _bucket(len(ctx))
        while width + k > st.draft_cfg.max_seq and width > 1:
            width //= 2
        ctx = ctx[-width:]
        padded = np.zeros((1, width), np.int32)
        padded[0, :len(ctx)] = ctx
        lengths = np.asarray([len(ctx)], np.int32)
        prog = st._cached_program(
            ("spec_draft", width, k),
            lambda: st._jax.jit(functools.partial(
                generate, cfg=st.draft_cfg, max_new_tokens=k,
                temperature=0.0, eos_id=None,
                kv_quant=st.draft_kv_quant,
            )),
        )
        jnp = st._jax.numpy
        with st._lock:
            out = prog(
                st.draft_params, jnp.asarray(padded),
                prompt_lengths=jnp.asarray(lengths),
            )
        return np.asarray(out)[0].tolist()

    def _clear_row(self, slot: int, best_effort: bool = False) -> None:
        """Reset slot ``slot`` back to bitwise-cold. Dense: the jitted
        cache_clear_row wipe. Paged: zero the table row (every read and
        write redirects to the page-0 sink), hand the page references
        back to the pool, and device-wipe whichever pages that freed —
        shared prefix pages stay resident for the store or other
        readers. With ``best_effort`` (the failed-insert scrub) a clear
        failure is swallowed: the row is numerically inert for
        attention either way, and the scrub must not mask the original
        error."""
        from tpu_kubernetes.models.decode import cache_clear_row

        st = self._state
        try:
            if self.paged:
                pages, self._slot_pages[slot] = \
                    self._slot_pages[slot], []
                self._table[slot, :] = 0
                freed = self._pages.release(pages)
                self._wipe_pages(freed)
                self._update_page_gauge()
                return
            with st._lock:
                clr = st._kv_program(
                    ("slot_clear",), cache_clear_row,
                    (self._cache, slot), donate=(0,),
                )
                self._cache = clr(self._cache, slot)
        except Exception:  # noqa: BLE001 — scrub only
            if not best_effort:
                raise

    def _release_slot(self, slot: int) -> None:
        """Free the slot's cache row and host mirrors WITHOUT counting
        a recycle — shared by retirement (request finished its slot
        lifetime) and paged preemption (request goes back to the
        queue)."""
        self._clear_row(slot)
        self._entries[slot] = None
        self._collected[slot] = []
        self._proposals[slot] = []
        self._pos[slot] = self._tok[slot] = self._rem[slot] = 0
        self._pl[slot] = self._ps[slot] = 0

    def _retire(self, slot: int) -> None:
        self._release_slot(slot)
        self.recycled += 1
        SLOTS_RECYCLED.inc()

    def _fail_out(self, err: Exception, reason: str = "engine-reset",
                  ) -> None:
        """A scheduler-level failure fails every queued AND resident
        entry out (no submitter may hang) and resets the engine cold.
        The flight recorder dumps a postmortem FIRST — the reset below
        wipes the very state the black box exists to preserve."""
        from tpu_kubernetes.models.decode import init_cache

        log.warn(
            f"continuous engine reset: {type(err).__name__}: {err}"
        )
        if self._flightrec is not None:
            self._flightrec.dump(reason, extra={
                "error": f"{type(err).__name__}: {err}"[:500],
                "restarts": self.restarts,
            })
        with self._cond:
            queued, self._queue = self._queue, []
        affected = queued + [e for e in self._entries if e is not None]
        # settle BEFORE the wipe below destroys _collected: device work
        # already spent on these entries (prefill token + segment
        # advances) dies with the reset — the definition of shed-spent
        if self._state.ready:
            for e in queued:
                LEDGER.settle("shed-spent", e.get("_decoded") or 0,
                              device_s=e.get("_device_s") or 0.0)
            for i, e in enumerate(self._entries):
                if e is not None:
                    LEDGER.settle("shed-spent", len(self._collected[i]),
                                  device_s=e.get("_device_s") or 0.0)
        for i in range(self.slots):
            self._entries[i] = None
            self._collected[i] = []
            self._proposals[i] = []
        for a in (self._pos, self._tok, self._rem, self._pl, self._ps):
            a[:] = 0
        st = self._state
        if self.paged:
            from tpu_kubernetes.models.decode import init_paged_pool
            from tpu_kubernetes.serve.pages import PagePool

            # reinitializing the pool invalidates every page id
            # wholesale: drop the store WITHOUT unpin callbacks (the
            # pages it would unpin no longer exist) and rebuild the
            # accounting cold — conservation holds trivially again
            if self._prefix is not None:
                self._prefix.clear()
            self._pool = self._shard_kv(init_paged_pool(
                st.cfg, self._pages.total, self.page_size,
                kv_quant=st.kv_quant,
            ))
            self._pages = PagePool(self._pages.total)
            self._table[:] = 0
            self._slot_pages = [[] for _ in range(self.slots)]
            self._update_page_gauge()
        else:
            self._cache = self._shard_kv(init_cache(
                st.cfg, self.slots, self.span, kv_quant=st.kv_quant
            ))
        for e in affected:
            e["error"] = err
            e["dispatched"].set()
            e["event"].set()
        SLOT_OCCUPANCY.set(0)

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def restart(self) -> None:
        """Watchdog recovery: fail all in-flight work out, reset the
        engine cold, start a fresh scheduler thread. The _loop contract
        means this only runs when the thread died anyway (an escape the
        per-pass try should have caught), so correctness over grace."""
        self._fail_out(RuntimeError(
            "continuous engine scheduler died — restarted cold"
        ), reason="watchdog-restart")
        self.restarts += 1
        ENGINE_RESTARTS.inc()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()


class ServingState:
    """Model + compiled-program cache + the generation lock."""

    def __init__(self, env: dict):
        import jax  # deferred: the server module must import without jax

        from tpu_kubernetes.serve.job import load_serving_stack, truthy_env
        from tpu_kubernetes.util.envparse import env_float, env_int

        self.env = env
        params, cfg, encode, decode_text = load_serving_stack(env)
        self.params, self.cfg = params, cfg
        self.encode, self.decode_text = encode, decode_text
        self.max_new_cap = env_int("SERVE_MAX_NEW", 64, env=env)
        self.kv_quant = truthy_env(env, "SERVE_KV_QUANT")
        # SERVE_PROMPT_LOOKUP: draft-model-free speculation for GREEDY
        # requests (models/speculative.py's n-gram idea, run host-side
        # so streaming works): proposals cost nothing and never change
        # tokens — acceptance keeps exactly the target's greedy
        # choices. Solo it drives the batch-1 lookup loop
        # (_lookup_rounds); with SERVE_CONTINUOUS_BATCHING=1 it drives
        # the slot engine's per-round (slots, draft_k+1) verify step
        # instead — speculation and slot throughput compose.
        self.prompt_lookup = truthy_env(env, "SERVE_PROMPT_LOOKUP")
        self.draft_k = env_int("SERVE_DRAFT_K", 8, env=env)
        self.ngram = env_int("SERVE_NGRAM", 2, env=env)
        self.draft_kv_quant = truthy_env(env, "SERVE_DRAFT_KV_QUANT")
        # the slot engine's proposer: None (no engine speculation),
        # "ngram" (host prompt lookup) or "draft" (the draft model —
        # wins when both are configured; drafts only propose, never
        # verify, so the choice moves the acceptance rate, not tokens)
        self.spec_source = None
        self.draft_params = None
        self.draft_cfg = None
        # cumulative speculation totals: written by batcher-dispatch /
        # handler threads (the _lookup_rounds finally), read by /healthz
        # handler threads — same lock discipline as the metrics registry
        # (one mutex guarding the shared numbers, held only for the update)
        self.spec_totals = {"rounds": 0, "drafted": 0, "accepted": 0}
        self._spec_lock = threading.Lock()
        eos_env = env.get("SERVE_EOS_ID", "")
        self.eos_id = int(eos_env) if eos_env else None
        self.model_name = env.get("SERVE_HF_CHECKPOINT", "") or env.get(
            "SERVE_MODEL", "llama-test"
        )
        # SERVE_ROLE: this instance's tier in a disaggregated fleet
        # (e.g. prefill / decode). Rides the tpu_serve_role_info gauge,
        # which the aggregator joins onto tpu_serve_saturation and the
        # monitor shows as the ROLE column.
        self.role = (env.get("SERVE_ROLE", "") or "").strip() or "serve"
        ROLE_INFO.labels(self.role).set(1.0)
        # distributed tracing (obs/tracing.py): inbound traceparent
        # extraction, head+tail sampling, and the bounded background
        # span exporter — all knobs via TPU_K8S_TRACE_*
        self.tracing = tracing.TraceRuntime(
            tracing.TraceConfig.from_env(env)
        )
        self._lock = threading.Lock()
        self._jax = jax
        # -- resilience policy (serve/resilience.py) --------------------
        # SERVE_DEADLINE_MS (default 0 = off): every request gets this
        # deadline unless its body carries a "deadline_ms" override; the
        # clock starts at request receipt (queue time counts).
        self.deadline_ms = env_float("SERVE_DEADLINE_MS", 0.0, env=env)
        # SERVE_MAX_QUEUE (default 256, 0 disables): admission control —
        # a full queue sheds with 429 + Retry-After instead of queueing
        # unboundedly behind the generation lock.
        self.admission = AdmissionController(
            env_int("SERVE_MAX_QUEUE", 256, env=env)
        )
        self.drain = DrainController()
        self.failed = False          # watchdog gave up: healthz hard-fails
        self._busy = 0               # requests inside complete()/stream()
        self._busy_lock = threading.Lock()
        self._http_server = None     # set by make_server (drain shutdown)
        self._watchdog = None

        # SERVE_MESH (e.g. "tensor=4"): serve TENSOR-SHARDED over this
        # host's chips (parallel/serving.py) — models bigger than one
        # chip's HBM serve live, and the continuous slot engine shards
        # its persistent KV caches the same way. Batch-carrying axes
        # are rejected (requests are batch-1 rows; sharding the batch
        # dim would make single requests unshardable) — except
        # ``expert`` for MoE models, where it shards expert weights and
        # decode segments route through the expert-parallel grouped
        # path (models/moe_ep.py). The streaming / prompt-lookup paths
        # stay single-device by design.
        self.mesh = None
        self._p_shardings = None
        # read early: speculation routing below depends on whether the
        # slot engine owns the greedy path
        continuous = truthy_env(env, "SERVE_CONTINUOUS_BATCHING")
        self._continuous = continuous
        mesh_spec = env.get("SERVE_MESH", "")
        if mesh_spec:
            from tpu_kubernetes.models import MoEConfig
            from tpu_kubernetes.parallel import (
                create_mesh,
                device_prefix_for,
            )
            from tpu_kubernetes.parallel.mesh import DATA_AXES
            from tpu_kubernetes.parallel.serving import (
                serving_param_shardings,
            )
            from tpu_kubernetes.topology import TopologyError, parse_mesh_shape

            if self.prompt_lookup and not continuous:
                # rejected BEFORE the mesh build + cross-chip device_put
                # below — an always-doomed config must fail cheaply. The
                # slot engine lifts this: its verify step runs through
                # the sharded program builders (parallel/serving.py), so
                # lookup × mesh composes under continuous batching.
                raise ValueError(
                    "SERVE_PROMPT_LOOKUP and SERVE_MESH need "
                    "SERVE_CONTINUOUS_BATCHING=1 (the solo speculation "
                    "loop is single-device; the slot engine's verify "
                    "step shards)"
                )
            try:
                shape = parse_mesh_shape(mesh_spec)
            except TopologyError as e:
                # main() maps ValueError to a one-line config diagnostic
                raise ValueError(f"SERVE_MESH: {e}") from e
            bad = [
                a for a in shape
                if a in DATA_AXES and shape[a] > 1
                and not (a == "expert" and isinstance(cfg, MoEConfig))
            ]
            if bad:
                raise ValueError(
                    f"SERVE_MESH axes {bad} shard the batch — live "
                    "requests are batch-1; use tensor (or sequence) "
                    "axes (expert is allowed for MoE models)"
                )
            devs = device_prefix_for(
                shape, jax.devices(), label="SERVE_MESH"
            )
            # after the device-count check: a mesh that cannot even be
            # built diagnoses as "not enough devices", not head math
            t = int(shape.get("tensor", 1))
            if t > 1 and cfg.n_kv_heads % t:
                raise ValueError(
                    f"SERVE_MESH tensor={t} must divide n_kv_heads "
                    f"({cfg.n_kv_heads}) — the slot engine shards KV "
                    "heads over the tensor axis"
                )
            self.mesh = create_mesh(shape, devices=devs)
            self._p_shardings = serving_param_shardings(
                params, cfg, self.mesh
            )
            self.params = jax.device_put(params, self._p_shardings)
            log.info(f"server: sharded serving: mesh={dict(self.mesh.shape)}")
        # jitted programs keyed by their STATIC arguments — jax.jit's own
        # cache keys on callable identity, so a fresh partial per request
        # would re-trace+compile every time. Handler threads race on
        # inserts; the mutex ensures one wrapper per key survives (two
        # racing wrappers would each pay a full trace+compile under the
        # generation lock)
        self._programs: dict = {}
        self._programs_lock = threading.Lock()
        batch = env_int("SERVER_BATCH", 1, env=env)
        self._batcher = None
        self._engine = None
        self.flightrec = None
        self.alerts = None       # engine-local AlertManager (obs/alerts.py)
        self._incidents = None   # IncidentCorrelator (obs/incidents.py)
        from tpu_kubernetes.models import MoEConfig

        # SERVE_CONTINUOUS_BATCHING=1: replace the round-based batcher
        # with the persistent slot engine (_ContinuousEngine) for greedy
        # default requests. SERVER_BATCH doubles as the slot count
        # (default 4 when unset/1 — slots are decode-batch rows, so the
        # same sizing intuition applies). Composes with SERVE_MESH —
        # the engine's caches and programs shard (parallel/serving.py)
        # — with MoE: the engine always decodes at the fixed slot
        # batch, so expert capacity is a constant shape no co-rider can
        # change, and per-row tokens stay identical to solo greedy —
        # and with speculation (SERVE_PROMPT_LOOKUP or a SERVE_DRAFT_*
        # model): segments become fixed-shape (slots, draft_k+1)
        # verify rounds against per-slot host proposal buffers.

        # speculation config. A draft model (batch job parity:
        # SERVE_DRAFT_MODEL preset or SERVE_DRAFT_HF_CHECKPOINT dir)
        # only drives the slot engine's proposer here — the solo HTTP
        # speculation path stays the draft-free lookup loop.
        draft_hf = env.get("SERVE_DRAFT_HF_CHECKPOINT", "")
        draft_name = env.get("SERVE_DRAFT_MODEL", "")
        speculating = self.prompt_lookup or (
            continuous and (draft_hf or draft_name)
        )
        if speculating:
            if isinstance(cfg, MoEConfig):
                # MoE expert capacity is computed per forward chunk, so
                # (k+1)-token verification can drop tokens sequential
                # decode would keep — exactness would silently void
                raise ValueError(
                    "speculative decoding needs a dense target model "
                    "(MoE chunk verification is not token-exact)"
                )
            if self.draft_k < 1 or self.ngram < 1:
                raise ValueError(
                    f"SERVE_DRAFT_K ({self.draft_k}) and SERVE_NGRAM "
                    f"({self.ngram}) must be >= 1"
                )
        if self.prompt_lookup and not continuous:
            # restrictions of the SOLO lookup loop only — the slot
            # engine lifts both (its verify step decodes the whole slot
            # batch, and int8 verification stays exact: rejected
            # quantized writes are masked and overwritten, accepted
            # ones are bitwise what sequential int8 decode writes)
            if self.kv_quant:
                raise ValueError(
                    "SERVE_PROMPT_LOOKUP with SERVE_KV_QUANT needs "
                    "SERVE_CONTINUOUS_BATCHING=1 (the solo verification "
                    "cache is full-precision)"
                )
            if batch > 1:
                raise ValueError(
                    "SERVE_PROMPT_LOOKUP with SERVER_BATCH needs "
                    "SERVE_CONTINUOUS_BATCHING=1 (the solo speculation "
                    "loop is batch-1; the slot engine verifies the "
                    "whole batch per round)"
                )
        if continuous and (draft_hf or draft_name):
            # the engine's draft-model proposer: load once, propose per
            # slot per round through bucketed batch-1 programs. Wins
            # over lookup when both are set (proposals never change
            # tokens, so precedence moves acceptance rate, not output).
            from tpu_kubernetes.models import (
                CONFIGS,
                init_params,
                load_hf,
            )

            if draft_hf:
                self.draft_params, self.draft_cfg = load_hf(draft_hf)
                log.info(f"server: draft model: HF checkpoint {draft_hf}")
            else:
                self.draft_cfg = CONFIGS[draft_name]
                self.draft_params = init_params(
                    jax.random.PRNGKey(1), self.draft_cfg
                )
                log.info(
                    f"server: draft model: random-init {draft_name} "
                    "(smoke mode)"
                )
            if isinstance(self.draft_cfg, MoEConfig):
                raise ValueError(
                    "the draft model must be dense (the proposer runs "
                    "the plain decode path)"
                )
            if self.draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {self.draft_cfg.vocab_size} != target "
                    f"vocab {cfg.vocab_size}"
                )
            if self.draft_cfg.max_seq < cfg.max_seq:
                raise ValueError(
                    f"draft max_seq {self.draft_cfg.max_seq} < target "
                    f"max_seq {cfg.max_seq} — the proposer re-reads the "
                    "full slot context"
                )
            self.spec_source = "draft"
        elif continuous and self.prompt_lookup:
            self.spec_source = "ngram"
        elif self.draft_kv_quant and not (draft_hf or draft_name):
            raise ValueError(
                "SERVE_DRAFT_KV_QUANT needs a draft model "
                "(SERVE_DRAFT_MODEL / SERVE_DRAFT_HF_CHECKPOINT)"
            )

        if batch > 1 and isinstance(cfg, MoEConfig) and not continuous:
            # the ragged-row identity ROUND batching leans on is weaker
            # for MoE (capacity is computed at the padded width —
            # co-riders could change a response); serve MoE solo rather
            # than quietly. The slot engine is exempt: it always decodes
            # at the fixed slot batch, so capacity is a constant shape.
            warn_once(
                "batch_moe",
                "SERVER_BATCH ignored: MoE capacity is batch-width-"
                "dependent, dynamic batching could change responses",
            )
        elif batch > 1 and not continuous:
            def fits(selected: list, entry: dict) -> bool:
                width = _bucket(max(
                    [len(entry["ids"])] + [len(e["ids"]) for e in selected]
                ))
                max_new = max(
                    [entry["max_new"]] + [e["max_new"] for e in selected]
                )
                # two individually-valid requests can be JOINTLY invalid:
                # the batch runs at (widest bucket, largest max_new)
                return width + max_new <= cfg.max_seq

            self._batcher = _Batcher(
                self._run_greedy_batch, batch,
                env_float("SERVER_BATCH_WINDOW_MS", 5.0, env=env),
                fits=fits,
                on_wait=self.admission.observe_service,
            )

        # SERVE_EARLY_EXIT_STEPS: the host-side liveness check interval
        # of the segmented greedy decode loop — every K jitted steps the
        # host asks "is any row still live?" and stops the generation
        # early instead of running to the bucketed max. <= 0 disables
        # the mid-run checks (one segment runs the whole budget).
        self.early_exit_steps = env_int("SERVE_EARLY_EXIT_STEPS", 8,
                                        env=env)
        # SERVE_PREFIX_CACHE_MB (> 0 enables): bounded LRU of prompt-
        # prefix KV segments (serve/prefix_cache.py). A request sharing
        # a stored prefix prefills only its suffix — into the SAME cache
        # geometry as a cold prefill, so every downstream program is
        # shared and greedy tokens stay identical (up to the documented
        # chunked-scoring float caveat, prefill_chunked). On a mesh the
        # stored segments are the sharded engine's own prefill output —
        # resume programs reshard them via explicit in_shardings, so
        # warm starts serve sharded too (pinned PAGES, in paged mode,
        # already live sharded in the pool).
        self.prefix_cache = None
        prefix_mb = env_float("SERVE_PREFIX_CACHE_MB", 0.0, env=env)
        if prefix_mb > 0:
            from tpu_kubernetes.serve.prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(
                int(prefix_mb * 2 ** 20),
                sig=(
                    self.model_name,
                    getattr(cfg.dtype, "__name__", str(cfg.dtype)),
                    bool(self.kv_quant),
                ),
                on_bytes=PREFIX_CACHE_BYTES.set,
            )
        # SERVE_KV_POOL_MB (> 0 enables, engine only): back the slot
        # engine with a paged KV pool instead of the dense
        # (slots, max_seq) block — concurrency gates on LIVE tokens
        # (free pages), not worst-case context. SERVE_KV_PAGE_SIZE sets
        # the positions per page (power of two dividing the minimum
        # prefix-reuse length, so warm hits stay page-aligned).
        self.kv_page_size = env_int("SERVE_KV_PAGE_SIZE", 16, env=env)
        self.kv_pool_mb = env_float("SERVE_KV_POOL_MB", 0.0, env=env)
        if self.kv_pool_mb > 0 and not self._continuous:
            raise ValueError(
                "SERVE_KV_POOL_MB needs SERVE_CONTINUOUS_BATCHING=1 "
                "(the page pool backs the slot engine; other request "
                "modes keep their dense caches)"
            )
        if self._continuous:
            # the engine's black box (obs/flightrec.py): per-segment
            # snapshots, postmortem dumps on reset/hard-fail/drain,
            # live at GET /debug/flightrec
            from tpu_kubernetes.obs.alerts import (
                AlertManager,
                engine_tripwires,
                sinks_from_env,
            )
            from tpu_kubernetes.obs.flightrec import FlightRecorder
            from tpu_kubernetes.obs.incidents import IncidentCorrelator

            self.flightrec = FlightRecorder.from_env(env)
            # the incident correlator shares the recorder's history
            # store (bundles embed the series the alerts fired on) and
            # cross-refs dumps both ways (obs/incidents.py)
            self._incidents = IncidentCorrelator.from_env(
                env, store=self.flightrec.store, flightrec=self.flightrec,
            )
            self.flightrec.incidents = self._incidents
            # the engine-local tripwires: page partition, ledger
            # conservation, restart/5xx/fault counter deltas, counter
            # stall, queue runaway — evaluated on the scheduler thread,
            # live at GET /debug/alerts, mirrored into /healthz
            self.alerts = AlertManager(
                engine_tripwires(
                    stats_fn=lambda: (self._engine.stats()
                                      if self._engine is not None else None),
                    ledger=LEDGER,
                    for_s=env_float("TPU_K8S_ALERT_FOR_S", 5.0, env=env),
                    resolve_for_s=env_float(
                        "TPU_K8S_ALERT_RESOLVE_FOR_S", 10.0, env=env
                    ),
                    queue_max_depth=float(
                        env_int("SERVE_MAX_QUEUE", 256, env=env)
                    ),
                ),
                sinks=sinks_from_env(env),
                group_interval_s=env_float(
                    "TPU_K8S_ALERT_GROUP_S", 60.0, env=env
                ),
                incidents=self._incidents,
            )
            # created LAST: the scheduler thread uses _prefill_any (the
            # prefix store included), so everything it leans on must be
            # wired first. K = the early-exit interval — admission and
            # drain happen between the same-length segments.
            self._engine = _ContinuousEngine(
                self, slots=batch if batch > 1 else 4,
                seg_steps=(self.early_exit_steps
                           if self.early_exit_steps > 0 else 8),
                page_size=self.kv_page_size,
                pool_mb=self.kv_pool_mb,
                flightrec=self.flightrec,
                alerts=self.alerts,
            )
            # self-healing: a dead scheduler thread would hang every
            # future submitter — restart it cold, bounded times
            # (SERVE_MAX_ENGINE_RESTARTS), then hard-fail /healthz so
            # the fleet replaces this instance.
            self._watchdog = Watchdog(
                self._engine.is_alive, self._engine.restart,
                max_restarts=env_int(
                    "SERVE_MAX_ENGINE_RESTARTS", 3, env=env
                ),
                interval_s=env_float(
                    "SERVE_WATCHDOG_INTERVAL_S", 0.5, env=env
                ),
                on_give_up=self._mark_failed,
            ).start()
        self.ready = False

    def _mark_failed(self) -> None:
        self.failed = True
        events.emit("serve_engine_failed",
                    restarts=self._engine.restarts if self._engine else 0)
        if self.flightrec is not None:
            # the terminal postmortem: the watchdog gave up, the fleet
            # will replace this instance — this dump is what's left
            self.flightrec.dump("hard-fail", extra={
                "restarts": self._engine.restarts if self._engine else 0,
            })

    def warm(self) -> None:
        """Compile the programs DEFAULT requests use — the segmented
        greedy pair (prefill + decode segments) at the full
        max_new_tokens cap AND the streaming step program, smallest
        bucket — before going ready, so the readiness flip means real
        traffic (either mode) runs at full speed. Sharded serving warms
        the fused program instead (its only path)."""
        self.complete("")
        if self.mesh is None:
            for _ in self.stream(""):
                pass
        self.ready = True
        log.info("server: warm — default programs compiled, serving")

    # -- resilience -----------------------------------------------------

    @contextlib.contextmanager
    def _track_busy(self):
        """Count a request through generation — the drain worker waits
        for this to reach zero before declaring the instance quiesced."""
        with self._busy_lock:
            self._busy += 1
        try:
            yield
        finally:
            with self._busy_lock:
                self._busy -= 1

    def _preflight(self, deadline: float | None) -> None:
        """Admission gate, run before any generation work: draining
        instances refuse (503), already-expired deadlines fail fast
        (504 without spending a prefill), and the admission controller
        sheds (429) when the queue is full or the estimated wait has
        already doomed the deadline. Warm-up traffic (pre-ready) is
        exempt — policy applies to live traffic only."""
        if not self.ready:
            return
        if self.failed:
            # the watchdog gave up on this instance: an enqueue would
            # hang on a dead scheduler forever — fail loudly instead
            raise RuntimeError(
                "serving engine failed permanently (watchdog restarts "
                "exhausted) — this instance must be replaced"
            )
        if self.drain.is_draining:
            raise Draining(
                "server is draining — retry against a sibling instance"
            )
        if expired(deadline):
            DEADLINE_TOTAL.labels("preflight").inc()
            raise DeadlineExceeded(
                "deadline expired before generation started"
            )
        if self._engine is not None:
            depth = self._engine.depth()
        elif self._batcher is not None:
            depth = self._batcher.depth()
        else:
            with self._busy_lock:
                depth = self._busy
        self.admission.admit(depth, deadline)

    def _quiesced(self) -> bool:
        with self._busy_lock:
            if self._busy:
                return False
        if self._engine is not None:
            s = self._engine.stats()
            if s["occupied"] or s["queued"]:
                return False
        if self._batcher is not None and self._batcher.depth():
            return False
        return True

    def begin_drain(self, reason: str = "") -> bool:
        """Stop admission NOW (new requests 503), then finish resident
        work in the background and shut the HTTP server down once
        quiesced (bounded by SERVE_DRAIN_TIMEOUT_S). Idempotent — the
        first caller starts the worker, later calls report False."""
        if not self.drain.begin(reason):
            return False
        threading.Thread(
            target=self._drain_worker, daemon=True, name="serve-drain"
        ).start()
        return True

    def _drain_worker(self) -> None:
        timeout = float(self.env.get("SERVE_DRAIN_TIMEOUT_S", "30") or 30)
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end and not self._quiesced():
            time.sleep(0.05)
        forced = not self._quiesced()
        if self._watchdog is not None:
            self._watchdog.stop()
        # the terminal event IS the flush (the sink writes through), and
        # /metrics stays scrapeable until the listener closes below
        events.emit("serve_drained",
                    reason=self.drain.reason, forced=forced)
        if self.flightrec is not None:
            # drain covers SIGTERM too (the handler routes through
            # begin_drain) — the trigger rides in the payload
            self.flightrec.dump("drain", extra={
                "trigger": self.drain.reason, "forced": forced,
            })
        self.drain.mark_drained()
        log.info("server: drained"
                 + (" (timeout — residual work abandoned)" if forced
                    else ""))
        srv = self._http_server
        if srv is not None:
            srv.shutdown()

    @contextlib.contextmanager
    def _locked_phase(self):
        """Acquire the generation lock under a "queue" span — on a busy
        server the wait for the chip IS the queue, and a request's trace
        should show it apart from the generation itself."""
        with TRACER.phase("queue", quiet=True):
            self._lock.acquire()
        try:
            yield
        finally:
            self._lock.release()

    def _cached_program(self, key, build):
        """Get-or-create a jitted program under the cache mutex. The
        mutex covers only the jax.jit WRAPPING (cheap); trace+compile
        happens at first call, serialized by the generation lock."""
        with self._programs_lock:
            fn = self._programs.get(key)
            PROGRAM_CACHE.labels("hit" if fn is not None else "miss").inc()
            if fn is None:
                fn = self._programs[key] = build()
        return fn

    def _kv_program(self, key, fn, example_args, donate: tuple = ()):
        """Get-or-create one of the slot engine's DATA-MOVEMENT programs
        (insert / clear / page wipe / page gather): a plain jax.jit on
        single-device serving, full-manual shard_map over the mesh's
        tensor-sharded KV otherwise (parallel/serving.py kv_shard_map —
        the bodies do no cross-shard math, so each shard runs bitwise
        the single-device program on its head slice). ``example_args``
        must be the concrete call arguments — they fix the in/out specs
        at build time and the first call follows immediately."""
        def build():
            if self.mesh is None:
                return self._jax.jit(fn, donate_argnums=donate)
            from tpu_kubernetes.parallel.serving import kv_shard_map

            return kv_shard_map(fn, self.mesh, example_args,
                                donate_argnums=donate)

        return self._cached_program(key, build)

    def _model_program(self, key, fn, example_args, donate: tuple = (),
                       ep: bool = False):
        """Get-or-create one of the slot engine's MODEL programs
        (prefill, resume, decode segments): a plain jax.jit on
        single-device serving, explicit-sharding jit on a mesh
        (parallel/serving.py kv_jit — params by their logical axes, KV
        storage heads-over-tensor, everything else replicated; GSPMD
        inserts the collectives). With ``ep`` (decode segments only —
        their batch is the fixed slot count) MoE bodies trace under
        expert_parallel_context, routing expert MLPs through the
        grouped all-to-all path (models/moe_ep.py) per segment."""
        def build():
            if self.mesh is None:
                return self._jax.jit(fn, donate_argnums=donate)
            from tpu_kubernetes.parallel.serving import kv_jit

            body = self._ep_wrap(fn) if ep else fn
            return kv_jit(body, self.mesh, example_args,
                          params_shardings=self._p_shardings,
                          donate_argnums=donate)

        return self._cached_program(key, build)

    def _ep_wrap(self, fn):
        """Trace ``fn`` under expert_parallel_context when the mesh has
        a non-trivial expert axis, so MoE layers with
        dispatch_mode="grouped" shard_map themselves over it (identity
        wrapper otherwise — the context is trace-time, models/moe_ep.py).
        Applied to decode segments only: their batch is the fixed slot
        count, which admission checks divides the expert axis; prefill
        rows are batch-1 and stay on the GSPMD path."""
        if self.mesh is None or self.mesh.shape.get("expert", 1) <= 1:
            return fn

        import functools

        from tpu_kubernetes.models.moe_ep import expert_parallel_context

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with expert_parallel_context(self.mesh):
                return fn(*args, **kwargs)

        return wrapped

    def _program(self, max_new: int, temperature: float, top_k: int,
                 top_p: float):
        import functools

        from tpu_kubernetes.models import generate

        if self.mesh is not None:
            def build_sharded():
                from tpu_kubernetes.parallel import make_sharded_generate

                fn, _, _ = make_sharded_generate(
                    self.cfg, self.mesh, self.params,
                    max_new_tokens=max_new, temperature=temperature,
                    top_k=top_k, top_p=top_p, eos_id=self.eos_id,
                    kv_quant=self.kv_quant, shard_batch=False,
                )
                return fn

            # same call convention as the jitted generate below —
            # (params, prompt, rng=, prompt_lengths=) — so every fused
            # call site (solo AND the batcher) shards transparently
            return self._cached_program(
                ("sharded", max_new, temperature, top_k, top_p),
                build_sharded,
            )

        return self._cached_program(
            (max_new, temperature, top_k, top_p),
            lambda: self._jax.jit(functools.partial(
                generate, cfg=self.cfg, max_new_tokens=max_new,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=self.eos_id, kv_quant=self.kv_quant,
            )),
        )

    def _validate(self, prompt: str, max_new_tokens: int | None):
        """Shared request validation → (prompt ids, requested max_new,
        run_max_new, width). ``run_max_new`` is the power-of-two bucket
        the program actually runs (clamped into max_seq); the response
        truncates to the REQUESTED budget — greedy emission is
        left-to-right, so running longer never changes earlier tokens."""
        max_new = (
            self.max_new_cap if max_new_tokens is None
            else int(max_new_tokens)   # 0 is a VALUE (and rejected), not unset
        )
        max_new = min(max_new, self.max_new_cap)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        ids = self.encode(prompt) or [0]      # empty prompt → one pad row
        width = _bucket(len(ids))
        # speculation reserves draft_k cache slots for the transient
        # chunk/window writes past the budget (models/speculative.py's
        # span rule; the slot engine's verify window pokes up to
        # draft_k positions past the last emittable one) — reserved
        # uniformly so every request sees one limit
        head = (self.draft_k
                if (self.prompt_lookup or self.spec_source is not None)
                else 0)
        if width + max_new + head > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(ids)} tokens, bucket {width}) + "
                f"max_new_tokens ({max_new})"
                + (f" + draft_k ({head})" if head else "")
                + f" exceeds max_seq {self.cfg.max_seq}"
            )
        run_max_new = min(
            _bucket_max_new(max_new, self.max_new_cap),
            self.cfg.max_seq - width - head,
        )
        return ids, max_new, run_max_new, width

    @staticmethod
    def _pad_rows(rows: list, width: int):
        import numpy as np

        padded = np.zeros((len(rows), width), np.int32)
        for i, r in enumerate(rows):
            padded[i, :len(r)] = r
        return padded

    # -- prefix KV-cache reuse + early-exit decode (the segmented greedy
    # hot path: single-device, dense; SERVE_PREFIX_CACHE_MB /
    # SERVE_EARLY_EXIT_STEPS) ----------------------------------------------

    def _prefix_lookup(self, ids: list):
        """Longest-match against the prefix store, floored to a power of
        two (compile discipline) and capped at len(ids)-1 — the resume
        chunk needs at least one real token to produce last-position
        logits. Returns (reused_tokens, entry | None) and records the
        hit/partial/miss counter + reused-tokens histogram ("hit" = the
        prompt extends a stored prefix; "partial" = it diverged inside
        one; ready-gated like the token counters)."""
        m, entry = self.prefix_cache.lookup(ids)
        q = _pow2_floor(min(m, len(ids) - 1))
        if entry is None or q < MIN_PREFIX_TOKENS:
            q, entry, result = 0, None, "miss"
        else:
            result = "hit" if m >= len(entry.ids) else "partial"
        if self.ready:
            PREFIX_CACHE_TOTAL.labels(result).inc()
            if q:
                PREFIX_CACHED_TOKENS.observe(float(q))
        return q, entry

    def _prefix_insert(self, ids: list, cache, row: int = 0) -> None:
        """Store row ``row``'s first len(ids) cache slots — its REAL
        prompt positions, which by causality are exactly what a fresh
        prefill of those tokens computes, so the segment is valid for
        ANY continuation. Pad garbage (slots >= len(ids)) never enters
        the store."""
        n = len(ids)
        if self.prefix_cache is None or n < MIN_PREFIX_TOKENS:
            return
        # best-effort: the store is an accelerator, not a correctness
        # dependency — a failed insert must not fail the request that
        # already has its tokens
        try:
            FAULTS.fire("serve.prefix_insert")
            arrays = {
                "k": cache.k[:, row:row + 1, :, :n],
                "v": cache.v[:, row:row + 1, :, :n],
            }
            if cache.k_scale is not None:
                arrays["k_scale"] = cache.k_scale[:, row:row + 1, :, :n]
                arrays["v_scale"] = cache.v_scale[:, row:row + 1, :, :n]
            self.prefix_cache.insert(ids, arrays)
        except Exception as e:  # noqa: BLE001 — accelerator only
            warn_once(
                "prefix_insert_failed",
                f"prefix-cache insert failed (serving continues without "
                f"storing): {type(e).__name__}: {e}",
            )

    def _expand_prefix(self, arrays: dict, q: int, span: int, b: int):
        """A stored segment → the resume base cache: ``q`` real slots,
        padded out to ``span`` and broadcast to ``b`` identical rows
        (batch warm starts replicate row 0, like _pad_rows). Pad values
        match init_cache (zeros; scale 1.0), so outside the real slots a
        warm cache is bitwise a cold one."""
        import jax.numpy as jnp

        from tpu_kubernetes.models.decode import KVCache

        def grow(a, pad_value=0):
            if a is None:
                return None
            a = a[:, :, :, :q] if a.ndim == 4 else a[:, :, :, :q, :]
            if b > 1:
                a = jnp.broadcast_to(a, (a.shape[0], b) + a.shape[2:])
            pad = [(0, 0)] * a.ndim
            pad[3] = (0, span - q)
            return jnp.pad(a, pad, constant_values=pad_value)

        return KVCache(
            k=grow(arrays["k"]), v=grow(arrays["v"]),
            length=jnp.asarray(q, jnp.int32),
            k_scale=grow(arrays.get("k_scale"), 1.0),
            v_scale=grow(arrays.get("v_scale"), 1.0),
        )

    def _prefill_cold(self, padded, lengths: list, span: int):
        """The jitted ragged prefill at this span (shared with the
        streaming path — same ("prefill", span) key)."""
        jax = self._jax
        import functools

        import jax.numpy as jnp

        from tpu_kubernetes.models.decode import prefill

        rows = jnp.asarray(padded)
        lens = jnp.asarray(lengths, jnp.int32)
        if self.mesh is not None:
            # positional wrapper: explicit-sharding jit (kv_jit) does
            # not take kwargs, and the example args must exist at build
            def _pf(params, rows, lens):
                return prefill(params, rows, self.cfg, max_seq=span,
                               kv_quant=self.kv_quant, lengths=lens)

            pf = self._model_program(
                ("prefill", span), _pf, (self.params, rows, lens),
            )
            args, kwargs = (self.params, rows, lens), {}
        else:
            pf = self._cached_program(
                ("prefill", span),
                lambda: jax.jit(functools.partial(
                    prefill, cfg=self.cfg, max_seq=span,
                    kv_quant=self.kv_quant,
                )),
            )
            args, kwargs = (self.params, rows), {"lengths": lens}
        # analytical roofline: capture the program's FLOPs/bytes before
        # its first call (lowering needs live concrete args)
        PROFILER.record_cost(
            "prefill", pf, args, kwargs,
            tokens=int(rows.size), key=("prefill", span),
        )
        with PROFILER.phase(
            "prefill", key=("prefill", span), tracer=TRACER,
        ) as pp:
            logits, cache = pp.sync(pf(*args, **kwargs))
        return logits, cache

    def _prefill_warm(self, ids: list, entry, q: int, width: int,
                      span: int, b: int = 1):
        """Resume from ``q`` cached prefix slots: prefill ONLY the
        suffix, into the same cache geometry as a cold prefill at this
        width — the suffix chunk spans [q, width), so prompt_slots /
        prompt_lengths / span (and therefore every downstream decode
        program) are shared with the cold path. Profiled as its own
        "prefill_warm" phase so /debug/profile splits warm from cold."""
        jax = self._jax
        import functools

        import jax.numpy as jnp

        from tpu_kubernetes.models.decode import prefill_resume

        base = self._expand_prefix(entry.arrays, q, span, b)
        suffix = jnp.asarray(self._pad_rows([ids[q:]] * b, width - q))
        lens = jnp.asarray([len(ids) - q] * b, jnp.int32)
        if self.mesh is not None:
            def _rs(params, suffix, cache, lens):
                return prefill_resume(params, suffix, self.cfg, cache,
                                      lengths=lens)

            rs = self._model_program(
                ("prefill_resume", span), _rs,
                (self.params, suffix, base, lens),
            )
            args, kwargs = (self.params, suffix, base, lens), {}
        else:
            rs = self._cached_program(
                ("prefill_resume", span),
                lambda: jax.jit(functools.partial(
                    prefill_resume, cfg=self.cfg,
                )),
            )
            args = (self.params, suffix)
            kwargs = {"cache": base, "lengths": lens}
        with PROFILER.phase(
            "prefill_warm", key=("prefill_resume", span), tracer=TRACER,
        ) as pp:
            logits, cache = pp.sync(rs(*args, **kwargs))
        return logits, cache

    def _prefill_any(self, ids: list, width: int, span: int, b: int = 1):
        """Warm-or-cold prefill of ``b`` identical rows of ``ids`` →
        (last-position logits, cache). Prefix lookup, the post-prefill
        insert, and the cache metrics all live here so every solo call
        site (complete / stream / single-entry batch rounds) shares one
        policy."""
        FAULTS.fire("serve.prefill")
        q, entry = (0, None)
        if self.prefix_cache is not None:
            q, entry = self._prefix_lookup(ids)
        if entry is not None:
            logits, cache = self._prefill_warm(ids, entry, q, width,
                                               span, b)
        else:
            logits, cache = self._prefill_cold(
                self._pad_rows([ids] * b, width), [len(ids)] * b, span,
            )
        self._prefix_insert(ids, cache)
        return logits, cache

    def _decode_masked(self, cache, first, budgets: list,
                       run_max_new: int, b: int):
        """Greedy decode with per-row done-masking and an all-rows-done
        early exit: jitted ``decode_segment`` programs of
        SERVE_EARLY_EXIT_STEPS steps each, with a host-side liveness
        check BETWEEN segments (off the per-step critical path). A row
        is live until its EOS appears or its own requested ``budget`` is
        emitted; once no row is live the remaining steps are skipped
        (tpu_serve_decode_steps_saved_total counts them). Token-exact
        vs the fused run-to-max scan: masking only decides when the loop
        may STOP, never what a row emits. → (tokens (b, 1+steps_run)
        ndarray, steps_run)."""
        jax = self._jax
        import functools

        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models.decode import decode_segment

        eos = self.eos_id
        done = (
            first == eos if eos is not None
            else jnp.zeros((b,), bool)
        )
        total = run_max_new - 1       # steps left after the first token
        k_steps = (
            self.early_exit_steps if self.early_exit_steps > 0 else total
        )
        pieces = [np.asarray(first)[:, None]]
        tok = first
        emitted = 1
        steps_run = 0
        while steps_run < total:
            done_h = np.asarray(done)
            if not any(
                budgets[i] > emitted and not done_h[i] for i in range(b)
            ):
                break
            steps = min(k_steps, total - steps_run)
            seg = self._cached_program(
                ("segment", steps),
                lambda: jax.jit(functools.partial(
                    decode_segment, cfg=self.cfg, steps=steps,
                    eos_id=eos, pad_id=0,
                )),
            )
            PROFILER.record_cost(
                "decode", seg, (self.params, cache, tok, done),
                tokens=b * steps, key=("segment", steps),
            )
            with PROFILER.phase(
                "decode", key=("segment", steps), tracer=TRACER,
            ) as pd:
                toks, tok, done, cache = pd.sync(
                    seg(self.params, cache, tok, done)
                )
            pieces.append(np.asarray(toks))
            emitted += steps
            steps_run += steps
        saved = total - steps_run
        if saved > 0 and self.ready:
            DECODE_STEPS_SAVED.inc(saved)
        return np.concatenate(pieces, axis=1), steps_run

    def _ledger_batch(self, entries: list, produced: int, b: int,
                      elapsed: float) -> None:
        """Goodput accounting for one dispatched static batch: the
        device produced ``produced`` tokens (the full (b, L) matrix);
        what each entry takes home is settled by complete(), the rest —
        pad rows, decode past a row's own budget — is bubble HERE, so
        production and settlement sum at quiescence. Device seconds
        split evenly across rows (pad rows' share is bubble)."""
        handed = 0
        for e in entries:
            handed += len(e["tokens"])
            e["_device_s"] = elapsed / max(1, b)
        if self.ready:
            LEDGER.emitted(produced)
            LEDGER.bubble(
                produced - handed,
                device_s=elapsed * (b - len(entries)) / max(1, b),
            )

    def _run_greedy_batch(self, entries: list) -> None:
        """Dispatcher callback: run up to SERVER_BATCH queued greedy
        requests as ONE ragged batch (static batch dim — pad rows
        replicate row 0) and set each entry's tokens. A row truncated to
        its own max_new is identical to generating that much alone:
        greedy emission is left-to-right and ragged rows are
        independent. Single-device, this is the segmented hot path —
        warm-prefix prefill for single-entry rounds and early-exit
        decode (pad rows get budget 1, so they never keep the batch
        alive); under SERVE_MESH the fused sharded program runs to the
        bucketed max."""
        jax = self._jax
        import jax.numpy as jnp
        import numpy as np

        b = self._batcher.max_batch
        max_new = max(e["max_new"] for e in entries)
        width = _bucket(max(len(e["ids"]) for e in entries))
        rows = [e["ids"] for e in entries]
        rows += [rows[0]] * (b - len(rows))

        if self.mesh is not None:
            padded = self._pad_rows(rows, width)
            lengths = jnp.asarray([len(r) for r in rows], jnp.int32)
            fn = self._program(max_new, 0.0, 0, 0.0)
            t0 = time.perf_counter()
            with self._lock:
                with PROFILER.phase(
                    "generate",
                    key=("generate", max_new, 0.0, 0, 0.0, width, b),
                    tracer=TRACER,
                ) as pg:
                    out = pg.sync(fn(
                        self.params, jnp.asarray(padded),
                        rng=jax.random.PRNGKey(0), prompt_lengths=lengths,
                    ))
                tokens = np.asarray(out)
            for i, entry in enumerate(entries):
                entry["tokens"] = tokens[i][:entry["max_new"]].tolist()
            self._ledger_batch(entries, int(tokens.size), b,
                               time.perf_counter() - t0)
            return

        span = width + max_new
        budgets = [e.get("budget", e["max_new"]) for e in entries]
        budgets += [1] * (b - len(entries))   # pad rows finish instantly
        t0 = time.perf_counter()
        with self._lock:
            if len(entries) == 1:
                # all rows replicate row 0 → the solo warm-or-cold path
                # (prefix lookup + insert) applies to the whole batch
                logits, cache = self._prefill_any(rows[0], width, span, b)
            else:
                logits, cache = self._prefill_cold(
                    self._pad_rows(rows, width),
                    [len(r) for r in rows], span,
                )
                for i, entry in enumerate(entries):
                    self._prefix_insert(entry["ids"], cache, row=i)
            first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            tokens, _ = self._decode_masked(
                cache, first, budgets, max_new, b
            )
        for i, entry in enumerate(entries):
            entry["tokens"] = tokens[i][:entry["max_new"]].tolist()
        self._ledger_batch(entries, int(tokens.size), b,
                           time.perf_counter() - t0)

    def _ngram_host(self, ctx: list, last: int) -> list:
        """Latest-occurrence n-gram proposal over the host-side context
        (prompt + emitted, real tokens only — pads never pollute it).
        Proposals only set the SPEED of the lookup loop, never its
        tokens: verification keeps exactly the target's greedy choices,
        so a bad guess costs a round, not correctness."""
        n, k = self.ngram, self.draft_k
        if len(ctx) > n:
            tail = ctx[-n:]
            for start in range(len(ctx) - n - 1, -1, -1):
                if ctx[start:start + n] == tail:
                    cont = ctx[start + n:start + n + k]
                    return cont + [last] * (k - len(cont))
        return [last] * k

    def _lookup_rounds(self, ids: list, width: int, run_max_new: int,
                       max_new: int, finish: dict | None = None):
        """Prompt-lookup speculation as a host-driven loop (so streaming
        can surface tokens per ROUND instead of per generation): jitted
        bucketed prefill, then per round one jitted (draft_k+1)-token
        ragged decode_chunk whose argmaxes verify the host's n-gram
        proposals. Yields each round's newly accepted tokens; stops at
        ``max_new`` or EOS. Cache rollback is O(1) — rewind ``length``,
        stale slots are masked (models/speculative.py's invariant).
        Caller holds the generation lock. ``finish`` (when given)
        receives "reason" on natural completion and "spec" (the
        per-request telemetry) even on early close."""
        jax = self._jax
        import functools

        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models.decode import decode_chunk, prefill

        cfg = self.cfg
        k = self.draft_k
        span = width + run_max_new + k
        pf = self._cached_program(
            ("prefill", span),
            lambda: jax.jit(functools.partial(
                prefill, cfg=cfg, max_seq=span, kv_quant=self.kv_quant,
            )),
        )

        def _build_chunk():
            def _chunk(params, cache, chunk):
                logits, cache = decode_chunk(params, cache, chunk[None], cfg)
                return (
                    jnp.argmax(logits[0], axis=-1).astype(jnp.int32), cache
                )

            return jax.jit(_chunk)

        ck = self._cached_program(("lookup_chunk", k), _build_chunk)

        padded = self._pad_rows([ids], width)
        t_pf = time.perf_counter()
        with PROFILER.phase(
            "prefill", key=("prefill", span), tracer=TRACER,
        ) as pp:
            logits, cache = pp.sync(pf(
                self.params, jnp.asarray(padded),
                lengths=jnp.asarray([len(ids)], jnp.int32),
            ))
        # the chunk program's first call pays trace+compile; later rounds
        # accumulate into one steady-state decode observation (finally)
        chunk_first = PROFILER.mark_first("decode", ("lookup_chunk", k))
        chunk_s = 0.0
        chunk_n = 0
        dev_s = time.perf_counter() - t_pf
        last = int(np.argmax(np.asarray(logits)[0]))
        emitted = [last]
        ctx = list(ids) + [last]
        rounds = drafted = accepted = 0
        # goodput: the prefill sample is 1 produced token, each round's
        # chunk produces k+1 more; what reaches a yield is delivered.
        # Both sides settle in the finally (it runs on disconnect too).
        produced = 1
        delivered = 0
        try:
            done = self.eos_id is not None and last == self.eos_id
            first_out = [] if done else [last]    # EOS itself is not emitted
            delivered += len(first_out)
            yield first_out
            while not done and len(emitted) < max_new:
                drafts = self._ngram_host(ctx, last)
                t_ck = time.perf_counter()
                greedy, cache = ck(
                    self.params, cache,
                    jnp.asarray([last] + drafts, jnp.int32),
                )
                g = np.asarray(greedy).tolist()              # k+1 tokens
                d_ck = time.perf_counter() - t_ck
                produced += k + 1
                dev_s += d_ck
                if chunk_first and rounds == 0:
                    PROFILER.observe("decode", d_ck, mode="compile")
                else:
                    chunk_s += d_ck
                    chunk_n += 1
                j = 0
                while j < k and drafts[j] == g[j]:
                    j += 1
                n_emit = min(j + 1, max_new - len(emitted))
                new = g[:n_emit]
                emitted += new
                ctx += new
                last = new[-1]
                rounds += 1
                drafted += k
                accepted += min(j, n_emit)
                # rewind to "everything before the new last token":
                # emitted token t sits at slot width + t
                cache = cache._replace(
                    length=jnp.asarray(width + len(emitted) - 1, jnp.int32)
                )
                if self.eos_id is not None and self.eos_id in new:
                    new = new[:new.index(self.eos_id)]
                    done = True
                delivered += len(new)
                yield new
            if finish is not None:
                finish["reason"] = "stop" if done else "length"
        finally:
            # finally: a streaming disconnect closes this generator at a
            # yield — the work done must still reach the totals
            if chunk_n:
                PROFILER.observe("decode", chunk_s, mode="execute",
                                 calls=chunk_n)
            with self._spec_lock:
                self.spec_totals["rounds"] += rounds + 1   # +1: the prefill
                self.spec_totals["drafted"] += drafted
                self.spec_totals["accepted"] += accepted
            SPEC_ROUNDS.inc(rounds + 1)
            SPEC_DRAFTED.labels("ngram").inc(drafted)
            SPEC_ACCEPTED.labels("ngram").inc(accepted)
            if self.ready:
                TOKENS_GENERATED.inc(len(emitted))
                PROMPT_TOKENS.inc(len(ids))
                LEDGER.emitted(produced)
                LEDGER.settle("useful", delivered, device_s=dev_s)
                LEDGER.bubble(produced - delivered)
            if finish is not None:
                finish["spec"] = {
                    "rounds": rounds + 1, "drafted": drafted,
                    "accepted": accepted,
                }

    def _safe_deltas(self, token_batches):
        """Token batches → UTF-8-safe text deltas (ONE implementation
        for every streaming mode, so the holdback rule cannot diverge):
        a trailing U+FFFD is usually an INCOMPLETE multi-byte sequence —
        the next token completes the character and changes what it
        decodes to — so it is held back until it either resolves or
        stops being the tail; the final text flushes at the end."""
        emitted: list[int] = []
        sent = ""
        for new in token_batches:
            if not new:
                continue
            emitted.extend(new)
            text = self.decode_text(emitted)
            stable = text[:-1] if text.endswith("�") else text
            if stable.startswith(sent) and len(stable) > len(sent):
                yield stable[len(sent):]
                sent = stable
        final = self.decode_text(emitted)
        if final.startswith(sent) and len(final) > len(sent):
            yield final[len(sent):]            # flush any held-back tail

    def _stream_lookup(self, ids, width, run_max_new, max_new,
                       finish: dict | None = None):
        """Stream the lookup loop's rounds as UTF-8-safe text deltas."""
        with self._locked_phase():
            yield from self._safe_deltas(
                self._lookup_rounds(
                    ids, width, run_max_new, max_new, finish
                )
            )

    def complete(self, prompt: str, max_new_tokens: int | None = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0,
                 deadline: float | None = None) -> dict:
        jax = self._jax
        import jax.numpy as jnp
        import numpy as np

        self._preflight(deadline)
        ids, max_new, run_max_new, width = self._validate(
            prompt, max_new_tokens
        )

        greedy_default = _is_greedy(temperature, top_k, top_p)
        spec = None
        ledger_device_s = 0.0
        batch_span = None    # annotated with ledger token classes below
        if self.prompt_lookup and greedy_default and self._engine is None:
            # draft-free speculation: tokens are exactly the greedy
            # decode at this cache span, EOS-trimmed by the loop.
            # (With the slot engine up, the engine owns the greedy path
            # and speculation rides its verify rounds instead)
            finish: dict = {}
            with self._locked_phase():
                with TRACER.phase("batch", quiet=True, mode="lookup"):
                    tokens = [
                        t for new in self._lookup_rounds(
                            ids, width, run_max_new, max_new, finish
                        ) for t in new
                    ]
            spec = finish.get("spec")
        elif self._engine is not None and greedy_default:
            # continuous batching: the scheduler thread owns the decode
            # loop — this request queues, is admitted into a free slot
            # between K-step segments (per-row width bucket, warm
            # prefix included), and its tokens fan back when its row
            # drains. Queue span = enqueue → slot insert (what
            # ADMISSION_WAIT measures); per-row output is
            # token-identical to solo greedy (SlotState independence).
            entry = self._engine.enqueue(ids, max_new, deadline=deadline)
            with TRACER.phase("queue", quiet=True):
                entry["dispatched"].wait()
            with TRACER.phase("batch", quiet=True,
                              mode="continuous") as batch_span:
                tokens = _Batcher.result(entry)
                # the critical-path annotations `get trace` surfaces:
                # slot-admission wait and this row's device-second share
                batch_span.meta["admission_wait_s"] = round(
                    entry.get("_wait") or 0.0, 6
                )
                batch_span.meta["device_s"] = round(
                    entry.get("_device_s") or 0.0, 6
                )
            ledger_device_s = entry.get("_device_s") or 0.0
        elif self._batcher is not None and greedy_default:
            # greedy rows coalesce without changing output, by the
            # ragged-row identity (up to the documented cache-span
            # float-tie caveat — the batch runs at the co-riders' span).
            # The queue span ends when the dispatcher SELECTS the entry,
            # the batch span when its rows come back — the same boundary
            # QUEUE_SECONDS measures.
            entry = self._batcher.enqueue(ids, run_max_new,
                                          budget=max_new,
                                          deadline=deadline)
            with TRACER.phase("queue", quiet=True):
                entry["dispatched"].wait()
            with TRACER.phase("batch", quiet=True, mode="batched"):
                tokens = self._batcher.result(entry)
            ledger_device_s = entry.get("_device_s") or 0.0
        elif greedy_default and self.mesh is None:
            # solo greedy, single device: the segmented hot path —
            # warm-prefix prefill when the store holds a match, then
            # early-exit decode that stops at the REQUESTED budget (or
            # EOS) instead of scanning to the bucketed run length
            span = width + run_max_new
            t0 = time.perf_counter()
            with self._locked_phase():
                with TRACER.phase("batch", quiet=True, mode="solo"):
                    logits, cache = self._prefill_any(ids, width, span)
                    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    out, _ = self._decode_masked(
                        cache, first, [max_new], run_max_new, 1
                    )
                    tokens = out[0].tolist()
            ledger_device_s = time.perf_counter() - t0
            if self.ready:
                # solo production: every decoded cell is handed to this
                # request — complete()'s settlement covers it all
                LEDGER.emitted(int(out.size))
        else:
            fn = self._program(run_max_new, float(temperature), int(top_k),
                               float(top_p))
            # the program retraces per prompt-width bucket, so width is
            # part of what identifies "this compile" to the profiler
            gkey = ("generate", run_max_new, float(temperature), int(top_k),
                    float(top_p), width, 1)
            t0 = time.perf_counter()
            with self._locked_phase():
                with TRACER.phase("batch", quiet=True, mode="solo"):
                    with PROFILER.phase(
                        "generate", key=gkey, tracer=TRACER,
                    ) as pg:
                        out = pg.sync(fn(
                            self.params,
                            jnp.asarray(self._pad_rows([ids], width)),
                            rng=jax.random.PRNGKey(int(seed)),
                            prompt_lengths=jnp.asarray(
                                [len(ids)], jnp.int32),
                        ))
                    tokens = np.asarray(out)[0].tolist()
            ledger_device_s = time.perf_counter() - t0
            if self.ready:
                LEDGER.emitted(len(tokens))
        decoded = len(tokens)
        tokens = tokens[:max_new]              # bucketed run → requested budget
        if self.eos_id is not None and self.eos_id in tokens:
            tokens = tokens[:tokens.index(self.eos_id)]
        if spec is None and self.ready:
            # ready-gated so warm-up traffic doesn't pollute the counters;
            # the lookup path already counted inside _lookup_rounds
            TOKENS_GENERATED.inc(len(tokens))
            PROMPT_TOKENS.inc(len(ids))
            # settle the request's raw decode: what the client takes is
            # useful, the trims (budget cap, trailing EOS) are bubble —
            # the lookup path settles inside _lookup_rounds instead
            LEDGER.settle_request(
                "useful", delivered=len(tokens), decoded=decoded,
                device_s=ledger_device_s,
            )
        if batch_span is not None:
            # ledger token classes on the span the trace view renders:
            # delivered tokens vs the budget/EOS trims (bubble)
            batch_span.meta["tokens"] = {
                "useful": len(tokens), "trimmed": decoded - len(tokens),
            }
        with TRACER.phase("decode", quiet=True, tokens=len(tokens)):
            text = self.decode_text(tokens)
        result = {
            "text": text,
            "tokens": len(tokens),
            "prompt_tokens": len(ids),
            # the budget rule lives HERE (one place): a full budget means
            # truncation, anything shorter means EOS stopped the row
            "finish_reason": "length" if len(tokens) >= max_new else "stop",
            "model": self.model_name,
        }
        if spec is not None:
            # per-request speculation telemetry (the acceptance rate IS
            # the speedup signal: tokens-per-target-pass)
            result["spec"] = spec
        return result

    def stream(self, prompt: str, max_new_tokens: int | None = None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0, seed: int = 0,
               finish: dict | None = None,
               deadline: float | None = None,
               cancel: threading.Event | None = None):
        """Yield text pieces as tokens decode: prefill once, then a
        per-token jitted decode_step+sample loop (the fused generate
        cannot surface tokens before the scan finishes). Each piece is
        the delta of the decoded prefix, so tokenizers whose characters
        span tokens never emit split multi-byte sequences."""
        jax = self._jax
        import numpy as np

        from tpu_kubernetes.models.decode import _sample, decode_step

        if self.mesh is not None:
            # the per-token streaming loop is single-device; the fused
            # sharded path is where a multi-chip model can answer
            raise ValueError(
                "streaming is not available under SERVE_MESH (sharded "
                "serving uses the fused program) — drop \"stream\""
            )
        self._preflight(deadline)
        ids, max_new, run_max_new, width = self._validate(
            prompt, max_new_tokens
        )
        if (self.prompt_lookup and not self.kv_quant
                and _is_greedy(temperature, top_k, top_p)):
            # speculation composes with streaming because the loop is
            # host-driven: whole ROUNDS of tokens surface at once (better
            # than per-token pacing when proposals are accepted). The
            # kv_quant guard: the solo lookup cache is full-precision,
            # so under SERVE_KV_QUANT (engine-composed speculation only)
            # streams take the plain int8 per-token loop instead
            yield from self._stream_lookup(
                ids, width, run_max_new, max_new, finish
            )
            return
        cfg = self.cfg

        # prefill programs are keyed by the SPAN (the only static the
        # compile depends on): different (width, max_new) pairs with one
        # span share a program, keeping the O(log max_seq)-programs
        # discipline. The span and rng schedule use the BUCKETED
        # run_max_new so a seed draws the same tokens as the fused path;
        # the loop stops at the request. _prefill_any serves the prefix
        # store when enabled — a warm prefill cuts time-to-first-token.
        span = width + run_max_new

        def _build_step():
            def _step(params, cache, tok, rng):
                logits, cache = decode_step(params, cache, tok, cfg)
                nxt = _sample(
                    logits, rng, float(temperature), int(top_k),
                    float(top_p),
                )
                return nxt, cache

            return jax.jit(_step)

        step = self._cached_program(
            ("step", float(temperature), int(top_k), float(top_p)),
            _build_step,
        )

        # the SAME rng schedule as generate(): the first token draws from
        # split(rng)[1], step i from split(rng, max_new-1)[i] — so a seed
        # produces identical samples whether or not the client streams
        rng = jax.random.PRNGKey(int(seed))
        rng, first_rng = jax.random.split(rng)
        step_rngs = (
            jax.random.split(rng, run_max_new - 1)
            if run_max_new > 1 else None
        )
        def tokens():
            if self.ready:
                PROMPT_TOKENS.inc(len(ids))
            t_pf = time.perf_counter()
            logits, cache = self._prefill_any(ids, width, span)
            tok = _sample(
                logits, first_rng, float(temperature), int(top_k),
                float(top_p),
            )
            # goodput: each computed token is `pending` until its yield
            # hands it over (then useful) — anything still pending at
            # generator close settles as the terminal class (cancelled
            # by default: a disconnect closes the generator mid-yield)
            pending = 1
            term_cls = "cancelled"
            dev_s = time.perf_counter() - t_pf
            if self.ready:
                LEDGER.emitted(1)
            # decode attribution: the step program's first call carries
            # trace+compile and is phased on its own; the remaining steps
            # accumulate OUTSIDE the yields (consumer pacing must not
            # count as device time) into one steady-state observation
            step_key = ("step", float(temperature), int(top_k),
                        float(top_p))
            tail_s = 0.0
            tail_n = 0
            try:
                for i in range(max_new):
                    if cancel is not None and cancel.is_set():
                        # the client stopped listening: stop paying for
                        # tokens nobody reads (the lock releases on
                        # return); the handler already counted the cause
                        return
                    if expired(deadline):
                        DEADLINE_TOTAL.labels("resident").inc()
                        term_cls = "expired"
                        raise DeadlineExceeded(
                            "deadline expired mid-stream"
                        )
                    t = int(np.asarray(tok)[0])
                    if self.eos_id is not None and t == self.eos_id:
                        if finish is not None:
                            finish["reason"] = "stop"
                        if self.ready:   # the EOS token is never handed
                            LEDGER.bubble(pending, device_s=dev_s)
                        pending = 0
                        return
                    if self.ready:
                        TOKENS_GENERATED.inc()
                    yield [t]
                    if self.ready:
                        LEDGER.settle("useful", pending, device_s=dev_s)
                    pending = 0
                    dev_s = 0.0
                    if i + 1 == max_new:
                        if finish is not None:
                            finish["reason"] = "length"
                        return
                    t0 = time.perf_counter()
                    if i == 0:
                        with PROFILER.phase(
                            "decode", key=step_key, tracer=TRACER,
                        ) as pd:
                            tok, cache = step(
                                self.params, cache, tok, step_rngs[i]
                            )
                            pd.sync(tok)
                    else:
                        tok, cache = step(
                            self.params, cache, tok, step_rngs[i]
                        )
                        jax.block_until_ready(tok)
                        tail_s += time.perf_counter() - t0
                        tail_n += 1
                    dev_s += time.perf_counter() - t0
                    if self.ready:
                        LEDGER.emitted(1)
                    pending = 1
            finally:
                if tail_n:
                    PROFILER.observe("decode", tail_s, mode="execute",
                                     calls=tail_n)
                if pending and self.ready:
                    LEDGER.settle(term_cls, pending, device_s=dev_s)

        with self._locked_phase():
            yield from self._safe_deltas(tokens())


class _Handler(BaseHTTPRequestHandler):
    state: ServingState  # set by make_server
    protocol_version = "HTTP/1.1"  # chunked transfer needs 1.1

    # the bounded endpoint-label vocabulary: anything else is "other" so a
    # path-scanning client can't mint unbounded label cardinality
    _ENDPOINTS = frozenset({
        "/healthz", "/metrics", "/v1/models", "/debug/profile",
        "/debug/ledger", "/debug/flightrec", "/debug/alerts",
        "/v1/completions", "/v1/chat/completions", "/drain",
    })

    def log_message(self, fmt, *args):
        # per-request access lines are verbose-level detail: util/log makes
        # -q/--verbose apply to the serving path like everywhere else
        log.debug(f"server: {self.address_string()} {fmt % args}")

    def _json(self, code: int, obj: dict,
              headers: dict | None = None) -> None:
        self._code = code
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        with self._observed():
            self._get()

    def do_POST(self):  # noqa: N802
        with self._observed():
            self._post()

    def _endpoint(self) -> str:
        # /debug/trace/<run-id> collapses to one label value: the id is
        # per-request, and metric label cardinality must stay bounded
        if self.path.startswith("/debug/trace"):
            return "/debug/trace"
        return self.path if self.path in self._ENDPOINTS else "other"

    def send_response(self, code, message=None):
        super().send_response(code, message)
        # EVERY response — success, error (send_error funnels through
        # here too), SSE — returns the request's correlation id, so a
        # client can quote it and GET /debug/trace/<id> can answer
        rid = getattr(self, "_rid", "")
        if rid:
            self.send_header("X-Request-Id", rid)
        # … and the W3C trace context (our span id as the parent the
        # caller's collector stitches under)
        tctx = getattr(self, "_trace", None)
        if tctx is not None:
            self.send_header(tracing.TRACEPARENT,
                             tracing.render_traceparent(tctx))

    @contextlib.contextmanager
    def _observed(self):
        """Correlate + count + time this request. An inbound
        X-Request-Id becomes the run id (distributed callers propagate
        theirs end-to-end) or one is minted; the whole handler runs
        under it, so every span and event inside carries it and the
        closing http_request event is greppable by it. The registry is
        observed whichever way the handler exits (the status code is
        whatever _json/_stream_sse last wrote; a handler crash counts
        as 500)."""
        endpoint = self._endpoint()
        inbound = (self.headers.get("X-Request-Id") or "").strip()
        self._rid = inbound[:64] or events.new_id()
        # W3C trace context: continue the caller's trace (deterministic
        # head sampling means every instance agrees) or mint a root;
        # the contextvar scope carries it into the engine queue and the
        # SSE producer thread, send_response echoes it back
        self._trace = self.state.tracing.extract(
            self.headers.get(tracing.TRACEPARENT)
        )
        self._code = 500
        self._t0 = time.monotonic()
        INFLIGHT.inc()
        try:
            with events.run_context(self._rid):
                with tracing.trace_scope(self._trace):
                    try:
                        with TRACER.phase("request", quiet=True,
                                          endpoint=endpoint,
                                          trace=self._trace.trace_id):
                            yield
                    finally:
                        events.emit("http_request", path=self.path,
                                    code=getattr(self, "_code", 0))
        finally:
            INFLIGHT.dec()
            REQUESTS_TOTAL.labels(endpoint, str(self._code)).inc()
            wall = time.monotonic() - self._t0
            REQUEST_SECONDS.labels(endpoint).observe(
                wall,
                exemplar=(self._trace.trace_id
                          if self._trace.sampled else None),
            )
            # head/tail export decision + span hand-off to the bounded
            # exporter — never blocks, never raises into the handler
            self.state.tracing.finish_request(
                TRACER, self._rid, self._trace,
                code=self._code, wall_s=wall,
            )

    def _get(self):  # noqa: C901 — one dispatch ladder
        st = self.state
        if self.path == "/v1/models":
            # the OpenAI-client handshake: one resident model
            return self._json(200, {
                "object": "list",
                "data": [{"id": st.model_name, "object": "model"}],
            })
        if self.path == "/metrics":
            # Prometheus text exposition of the process registry — serving
            # histograms/counters plus whatever else this process recorded
            body = REGISTRY.render().encode("utf-8")
            self._code = 200
            self.send_response(200)
            self.send_header("Content-Type", obs_metrics.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return None
        if self.path == "/debug/profile":
            # compile-vs-execute phase attribution + latest HBM sample
            # (obs/profile.py summary) — `tpu-kubernetes get profile`
            # renders this payload
            return self._json(200, PROFILER.summary())
        if self.path == "/debug/ledger":
            # the goodput ledger: token classes, conservation balance,
            # slot-engine timeline, analytical roofline — what
            # `tpu-kubernetes get goodput` renders
            payload = LEDGER.snapshot()
            payload["roofline"] = PROFILER.roofline()
            st = self.state
            if st._engine is not None and getattr(
                st._engine, "paged", False
            ):
                # the page-pool partition next to the token classes:
                # free+live+pinned must equal total (else a leak), and
                # stalls explain bubble/shed-spent entries above
                payload["kv_pages"] = st._engine._pages.stats()
            return self._json(200, payload)
        if self.path == "/debug/flightrec":
            # the engine's black box, live and without writing a file:
            # segment ring, ledger, SLO alerts, recent history samples —
            # what `tpu-kubernetes get flightrec` renders
            if st.flightrec is None:
                return self._json(404, {
                    "error": "no flight recorder on this instance",
                    "hint": "the recorder rides the continuous-batching "
                            "engine (SERVE_CONTINUOUS_BATCHING=1)",
                })
            return self._json(200, st.flightrec.snapshot())
        if self.path == "/debug/alerts":
            # the engine-local tripwire state: active/resolved alerts,
            # silences, the registered rules — what `tpu-kubernetes
            # get alerts` renders
            if st.alerts is None:
                return self._json(404, {
                    "error": "no alert manager on this instance",
                    "hint": "engine-local tripwires ride the continuous-"
                            "batching engine (SERVE_CONTINUOUS_BATCHING=1)",
                })
            return self._json(200, st.alerts.snapshot())
        if self.path.startswith("/debug/trace/"):
            # the span tree of one request/run, looked up by the id the
            # response's X-Request-Id header carried
            rid = self.path[len("/debug/trace/"):]
            tree = span_tree(TRACER.spans, rid)
            if tree:
                return self._json(200, {"run": rid, "spans": tree})
            # not a run id — try it as a DISTRIBUTED trace id: every
            # run whose request span carried it, plus the scheduler
            # segment spans linked to it (`get trace` stitches these
            # payloads across instances)
            payload = tracing.trace_payload(TRACER.spans, rid)
            if payload["spans"] or payload["segments"]:
                return self._json(200, payload)
            return self._json(404, {
                "error": f"no spans recorded for run {rid!r}",
                "hint": "pass an X-Request-Id a response returned; "
                        "old runs age out of the span ring",
            })
        if self.path != "/healthz":
            return self._json(404, {"error": "unknown path"})
        if st.failed:
            # the watchdog gave up: this instance cannot serve — a hard
            # 503 (distinct from "warming"/"draining") tells the fleet
            # to replace it, not wait for it
            return self._json(503, {
                "status": "failed",
                "reason": "engine watchdog exhausted its restarts",
                "restarts": (st._engine.restarts
                             if st._engine is not None else 0),
            })
        if not st.ready:
            return self._json(503, {"status": "warming"})
        if st.drain.is_draining:
            # 503 flips the readiness probe so the load balancer stops
            # routing here while resident work finishes
            return self._json(503, {
                "status": st.drain.state,
                "reason": st.drain.reason,
                "busy": st._busy,
            })
        body = {
            "status": "ok",
            "model": st.model_name,
            "max_new_tokens_cap": st.max_new_cap,
            "kv_quant": st.kv_quant,
            # the one-glance operational summary; the full families (and
            # everything per-label) live at GET /metrics
            "metrics": {
                "tokens_generated": int(TOKENS_GENERATED.value),
                "prompt_tokens": int(PROMPT_TOKENS.value),
            },
            # one-glance goodput mirror (full ledger at /debug/ledger)
            "goodput": {
                "ratio": LEDGER.goodput(),
                "bubble_fraction": LEDGER.bubble_fraction(),
                "unsettled": LEDGER.unsettled(),
            },
        }
        if st.prefix_cache is not None:
            # entries / bytes-vs-cap / signature — the LRU's one-glance
            # mirror (the bytes gauge rides /metrics)
            body["prefix_cache"] = st.prefix_cache.stats()
        if st._engine is not None:
            # slot occupancy / queue depth / recycle total — the
            # engine's one-glance mirror (gauge + counters ride /metrics)
            body["continuous_batching"] = st._engine.stats()
        if st.alerts is not None:
            # firing/pending counts by severity — the pager's one-glance
            # mirror (the full alert list lives at /debug/alerts)
            body["alerts"] = st.alerts.summary()
        # the resilience policy at a glance (shed/deadline/cancel/restart
        # counters ride /metrics)
        body["resilience"] = {
            "state": st.drain.state,
            "deadline_ms_default": st.deadline_ms,
            "max_queue": st.admission.max_queue,
        }
        if st.prompt_lookup or st.spec_source is not None:
            with st._spec_lock:
                t = dict(st.spec_totals)
            body["prompt_lookup"] = {
                "draft_k": st.draft_k, "ngram": st.ngram,
                "drafted": t["drafted"], "accepted": t["accepted"],
                "rounds": t["rounds"],
                "source": st.spec_source or "ngram",
            }
        return self._json(200, body)

    @staticmethod
    def _chat_prompt(messages) -> str:
        """OpenAI-style messages → one prompt string. Deliberately a
        plain role-prefixed transcript (the byte tokenizer has no chat
        template; HF-tokenized models see the same canonical text, so
        responses are reproducible across tokenizers)."""
        if not isinstance(messages, list) or not messages:
            raise ValueError('"messages" must be a non-empty list')
        parts = []
        for m in messages:
            if not isinstance(m, dict):
                raise ValueError("each message must be an object")
            role = m.get("role")
            content = m.get("content")
            if role not in ("system", "user", "assistant"):
                raise ValueError(f"unknown message role {role!r}")
            if not isinstance(content, str):
                raise ValueError('message "content" must be a string')
            parts.append(f"{role}: {content}")
        parts.append("assistant:")
        return "\n".join(parts)

    def _post(self):
        st = self.state
        if self.path == "/drain":
            # the Kubernetes preStop contract: stop admission now,
            # finish resident work, shut the listener down — 202 because
            # the drain completes after this response
            accepted = st.begin_drain("POST /drain")
            return self._json(202, {
                "status": st.drain.state,
                "accepted": accepted,
            })
        chat = self.path == "/v1/chat/completions"
        if self.path != "/v1/completions" and not chat:
            return self._json(404, {"error": "unknown path"})
        if not st.ready:
            return self._json(503, {"error": "warming"})
        if st.failed:
            return self._json(503, {
                "error": "serving engine failed — instance is being "
                         "replaced"})
        stream_ctx = None
        try:
            with st._track_busy():
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                if chat:
                    prompt = self._chat_prompt(body.get("messages"))
                    # OpenAI chat spells the budget "max_tokens"
                    max_new = body.get(
                        "max_tokens", body.get("max_new_tokens")
                    )
                else:
                    if "prompt" not in body:
                        raise ValueError(
                            'body must be a JSON object with "prompt"'
                        )
                    prompt = str(body["prompt"])
                    # OpenAI's legacy completions API spells it "max_tokens"
                    max_new = body.get(
                        "max_new_tokens", body.get("max_tokens")
                    )
                dl_ms = body.get("deadline_ms")
                if dl_ms is not None:
                    dl_ms = float(dl_ms)
                    if dl_ms <= 0:
                        raise ValueError('"deadline_ms" must be > 0')
                # anchored at request RECEIPT (self._t0) — time already
                # spent reading/parsing counts against the deadline
                deadline = deadline_from(
                    self._t0, dl_ms, default_ms=st.deadline_ms
                )
                kwargs = dict(
                    max_new_tokens=max_new,
                    temperature=body.get("temperature", 0.0),
                    top_k=body.get("top_k", 0),
                    top_p=body.get("top_p", 0.0),
                    seed=body.get("seed", 0),
                    deadline=deadline,
                )
                if body.get("stream"):
                    # validate (and pay the first device call) BEFORE the
                    # 200 status goes out — errors must still be a 400
                    finish: dict = {}
                    cancel = threading.Event()
                    pieces = st.stream(prompt, finish=finish,
                                       cancel=cancel, **kwargs)
                    first = next(pieces, None)
                    TTFT_SECONDS.observe(
                        time.monotonic() - self._t0,
                        exemplar=(self._trace.trace_id
                                  if self._trace.sampled else None),
                    )
                    stream_ctx = (first, pieces, finish, cancel)
                else:
                    result = st.complete(prompt, **kwargs)
        except Overloaded as e:
            # load shed: the client's retry policy absorbs the spike
            return self._json(429, {"error": str(e)}, headers={
                "Retry-After": str(e.retry_after_s)})
        except DeadlineExceeded as e:
            return self._json(504, {"error": str(e)})
        except Draining as e:
            return self._json(503, {"error": str(e)},
                              headers={"Retry-After": "1"})
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            # TypeError covers wrong-typed JSON fields (e.g. top_k: [1])
            # — a malformed request must be a 400, not a dropped socket
            return self._json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — a generation failure
            # (injected or organic) must be a JSON 500 the client can
            # parse, not a dropped socket
            log.warn(f"request failed: {type(e).__name__}: {e}")
            return self._json(500, {
                "error": f"{type(e).__name__}: {e}"})
        if stream_ctx is not None:
            first, pieces, finish, cancel = stream_ctx
            with st._track_busy():
                return self._stream_sse(
                    first, pieces, chat=chat, finish=finish,
                    cancel=cancel,
                )
        if chat:
            return self._json(200, {
                "id": f"chatcmpl-{uuid.uuid4().hex}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": result["model"],
                "choices": [{
                    "index": 0,
                    "message": {
                        "role": "assistant", "content": result["text"],
                    },
                    "finish_reason": result["finish_reason"],
                }],
                "usage": {
                    "prompt_tokens": result["prompt_tokens"],
                    "completion_tokens": result["tokens"],
                    "total_tokens": (
                        result["prompt_tokens"] + result["tokens"]
                    ),
                },
            })
        return self._json(200, result)

    def _stream_sse(self, first: str | None, pieces, chat: bool,
                    finish: dict | None = None,
                    cancel: threading.Event | None = None) -> None:
        """Write text pieces as Server-Sent Events (``data: {json}``
        frames, terminal ``data: [DONE]`` — what OpenAI streaming
        clients parse) WITHOUT coupling the chip to the client: a
        producer thread drains the generator (which holds the generation
        lock) into an unbounded queue at chip speed — total work is
        bounded by max_new_tokens — while this thread writes at whatever
        pace the client reads. A slow or dead reader can never hold the
        generation lock hostage."""
        import queue

        q: queue.Queue = queue.Queue()

        _FAILED = object()   # mid-stream generation error sentinel

        # contextvars do NOT cross thread creation: capture the handler's
        # context (run id + the open request span) so the producer's
        # "decode" span lands in THIS request's trace, not orphaned
        ctx = contextvars.copy_context()

        def produce():
            try:
                with TRACER.phase("decode", quiet=True):
                    for piece in pieces:
                        q.put(piece)
                q.put(None)
            except Exception as e:  # noqa: BLE001 — surfaced via sentinel
                log.warn(f"stream producer failed: {type(e).__name__}: {e}")
                q.put(_FAILED)

        producer = None
        sid = (
            f"chatcmpl-{uuid.uuid4().hex}" if chat
            else f"cmpl-{uuid.uuid4().hex}"
        )
        created = int(time.time())
        try:
            # header writes are INSIDE the disconnect handler: a client
            # gone before the status line still suspends the stream()
            # generator inside the generation lock, and only the finally
            # below releases it deterministically
            self._code = 200
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            failed = False
            if first is not None:
                producer = threading.Thread(
                    target=lambda: ctx.run(produce), daemon=True
                )
                producer.start()
                self._write_sse(first, chat, sid, created)
                while (piece := q.get()) is not None:
                    if piece is _FAILED:
                        failed = True
                        break
                    self._write_sse(piece, chat, sid, created)
            if failed:
                # NO [DONE] and NO terminal chunk: aborting the chunked
                # body is the in-band error signal — a clean EOF would
                # make a truncated completion look like a successful one
                log.warn("aborting stream after mid-generation failure")
                self.close_connection = True
                self.wfile.flush()
            else:
                # the closing frame OpenAI streaming clients expect: an
                # empty delta carrying finish_reason, then [DONE]. The
                # reason comes from the generation loop itself (length =
                # budget truncation, stop = EOS), matching what the same
                # request reports non-streamed.
                reason = (finish or {}).get("reason", "stop")
                final_choice = (
                    {"index": 0, "delta": {}, "finish_reason": reason}
                    if chat else
                    {"index": 0, "text": "", "finish_reason": reason}
                )
                self._write_raw(self._sse_frame(chat, sid, created,
                                                final_choice))
                self._write_raw(b"data: [DONE]\n\n")
                self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            # client went away mid-stream: cancel tells the generation
            # loop to stop paying for tokens nobody reads (the producer
            # drains what's queued and releases the lock on its own)
            if cancel is not None:
                cancel.set()
            CANCELLED_TOTAL.labels("disconnect").inc()
            log.info("server: client disconnected mid-stream — "
                     "generation cancelled")
        finally:
            if producer is not None:
                producer.join()
            else:
                # producer never started (headers failed, or the stream
                # was empty): close the generator so the with-block
                # inside stream() releases the generation lock NOW, not
                # at GC time
                pieces.close()

    def _sse_frame(self, chat: bool, sid: str, created: int,
                   choice: dict) -> bytes:
        """One SSE data: frame in the OpenAI chunk envelope."""
        obj = {
            "id": sid,
            "object": "chat.completion.chunk" if chat else "text_completion",
            "created": created,
            "model": self.state.model_name,
            "choices": [choice],
        }
        return f"data: {json.dumps(obj)}\n\n".encode("utf-8")

    def _write_sse(self, piece: str, chat: bool, sid: str,
                   created: int) -> None:
        if not piece:
            return
        if chat:
            choice = {
                "index": 0, "delta": {"content": piece},
                "finish_reason": None,
            }
        else:
            choice = {"index": 0, "text": piece, "finish_reason": None}
        self._write_raw(self._sse_frame(chat, sid, created, choice))

    def _write_raw(self, data: bytes) -> None:
        """One HTTP/1.1 chunk carrying one SSE frame."""
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()


def make_server(env: dict | None = None) -> ThreadingHTTPServer:
    """Build (but don't run) the server — tests drive it on an ephemeral
    port. The model is loaded and warmed before this returns."""
    env = dict(os.environ if env is None else env)
    state = ServingState(env)
    state.warm()

    handler = type("Handler", (_Handler,), {"state": state})
    from tpu_kubernetes.util.envparse import env_int, env_str

    host = env_str("SERVER_HOST", "127.0.0.1", env=env)
    port = env_int("SERVER_PORT", 8000, env=env)
    server = ThreadingHTTPServer((host, port), handler)
    # the drain worker shuts this listener down once quiesced
    state._http_server = server
    return server


def main(argv: list[str] | None = None) -> int:
    import argparse

    from tpu_kubernetes.parallel import read_env

    # the CLI's verbosity contract applies to the serving path too: one
    # leveled logger (util/log), so -q silences progress and --verbose
    # surfaces per-request access lines, uniformly with tpu-k8s itself
    parser = argparse.ArgumentParser(prog="tpu-kubernetes-server")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="warnings and errors only")
    parser.add_argument("--verbose", action="store_true",
                        help="debug detail (per-request access lines)")
    args = parser.parse_args(argv)
    log.set_verbosity(quiet=args.quiet, verbose=args.verbose)

    denv = read_env()
    if denv.multi_host:
        # request-driven generation would need every process to enter
        # the same compiled call for every request (a broadcast-driven
        # follower loop); the BATCH entrypoint already serves multi-host
        # slices (serve/job.py + serve-llama-v5p32.yaml). Refuse loudly
        # rather than silently serving a mesh over one host's chips.
        raise SystemExit(
            f"the HTTP server is single-host (found JAX_NUM_PROCESSES="
            f"{denv.num_processes}); use `python -m tpu_kubernetes.serve."
            f"job` for multi-host slice serving"
        )
    try:
        server = make_server()
    except ValueError as e:
        # config rejections (lookup × MoE/KV-quant/batch, bad knobs) are
        # one-line diagnostics, not tracebacks — the batch job's stance
        raise SystemExit(f"config error: {e}") from e
    host, port = server.server_address[:2]
    log.info(f"server: listening on {host}:{port}")
    # SIGTERM (what Kubernetes sends ahead of SIGKILL) drains instead of
    # dying mid-generation: admission stops, resident slots finish, the
    # listener closes, serve_forever returns, and the process exits 0
    # inside the terminationGracePeriod
    import signal

    state = server.RequestHandlerClass.state
    signal.signal(
        signal.SIGTERM, lambda signum, frame: state.begin_drain("SIGTERM")
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    if state.drain.is_draining:
        state.drain.wait_drained(timeout=5)
        log.info("server: exiting after drain")
    return 0


if __name__ == "__main__":
    sys.exit(main())
