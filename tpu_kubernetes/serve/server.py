"""HTTP inference server: the live-serving analog of the batch job
(serve/job.py) — what sits behind a Kubernetes Service instead of a Job.

A minimal stdlib server (zero dependencies, air-gap friendly) exposing:

  GET  /healthz            → {"status": "ok", "model": ..., ...}
                             (readiness probe; returns 503 until the
                             first compile has finished warming)
  POST /v1/completions     → {"prompt": str, "max_new_tokens"?: int,
                              "temperature"?: float, "top_k"?: int,
                              "top_p"?: float, "seed"?: int}
                             ⇒ {"text": str, "tokens": int, "model": str}

Model bring-up reuses the batch job's env contract exactly
(``load_serving_stack``: SERVE_MODEL / SERVE_HF_CHECKPOINT /
SERVE_TOKENIZER / SERVE_QUANT), plus SERVE_KV_QUANT for the int8 KV
cache, SERVE_EOS_ID (tokens after it are truncated from responses),
SERVER_HOST/SERVER_PORT, and SERVE_MAX_NEW as the per-request
``max_new_tokens`` cap.

TPU-first serving discipline:

* **Bucketed compiles.** Prompts are right-padded to power-of-two widths
  and served ragged (``prompt_lengths``), so the number of distinct
  compiled programs is O(log max_seq) per sampling configuration — not
  one per prompt length. Programs are cached by their static signature
  (max_new, sampling knobs) in ServingState, one jitted callable each,
  and jax.jit's shape cache handles the width buckets under it.
* **One request on the chip at a time.** A lock serializes generation
  (the chip is the bottleneck; queueing in the server beats queueing in
  PJRT), while the ThreadingHTTPServer keeps health checks responsive
  during long generations.
* Startup warms the default bucket so the readiness probe flips only
  when real traffic would be served at full speed.

The reference provisioner has no inference plane (SURVEY §0); this
completes provision → import weights → quantize → serve-over-HTTP.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def log(*args) -> None:
    print("[server]", *args, file=sys.stderr, flush=True)


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class ServingState:
    """Model + compiled-program cache + the generation lock."""

    def __init__(self, env: dict):
        import jax  # deferred: the server module must import without jax

        from tpu_kubernetes.serve.job import load_serving_stack, truthy_env

        self.env = env
        params, cfg, encode, decode_text = load_serving_stack(env)
        self.params, self.cfg = params, cfg
        self.encode, self.decode_text = encode, decode_text
        self.max_new_cap = int(env.get("SERVE_MAX_NEW", "64"))
        self.kv_quant = truthy_env(env, "SERVE_KV_QUANT")
        eos_env = env.get("SERVE_EOS_ID", "")
        self.eos_id = int(eos_env) if eos_env else None
        self.model_name = env.get("SERVE_HF_CHECKPOINT", "") or env.get(
            "SERVE_MODEL", "llama-test"
        )
        self._lock = threading.Lock()
        self._jax = jax
        # jitted programs keyed by their STATIC arguments — jax.jit's own
        # cache keys on callable identity, so a fresh partial per request
        # would re-trace+compile every time
        self._programs: dict = {}
        self.ready = False

    def warm(self) -> None:
        """Compile the program a DEFAULT request uses (the full
        max_new_tokens cap, greedy, smallest bucket) before going ready,
        so the readiness flip means real traffic runs at full speed."""
        self.complete("")
        self.ready = True
        log("warm: default program compiled, serving")

    def _program(self, max_new: int, temperature: float, top_k: int,
                 top_p: float):
        import functools

        from tpu_kubernetes.models import generate

        key = (max_new, temperature, top_k, top_p)
        fn = self._programs.get(key)
        if fn is None:
            fn = self._jax.jit(functools.partial(
                generate, cfg=self.cfg, max_new_tokens=max_new,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=self.eos_id, kv_quant=self.kv_quant,
            ))
            self._programs[key] = fn
        return fn

    def complete(self, prompt: str, max_new_tokens: int | None = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0) -> dict:
        jax = self._jax
        import jax.numpy as jnp
        import numpy as np

        cfg = self.cfg
        max_new = (
            self.max_new_cap if max_new_tokens is None
            else int(max_new_tokens)   # 0 is a VALUE (and rejected), not unset
        )
        max_new = min(max_new, self.max_new_cap)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        ids = self.encode(prompt) or [0]      # empty prompt → one pad row
        width = _bucket(len(ids))
        if width + max_new > cfg.max_seq:
            raise ValueError(
                f"prompt ({len(ids)} tokens, bucket {width}) + "
                f"max_new_tokens ({max_new}) exceeds max_seq {cfg.max_seq}"
            )
        padded = np.zeros((1, width), np.int32)
        padded[0, :len(ids)] = ids

        fn = self._program(max_new, float(temperature), int(top_k),
                           float(top_p))
        with self._lock:
            out = fn(
                self.params, jnp.asarray(padded),
                rng=jax.random.PRNGKey(int(seed)),
                prompt_lengths=jnp.asarray([len(ids)], jnp.int32),
            )
            tokens = np.asarray(out)[0].tolist()
        if self.eos_id is not None and self.eos_id in tokens:
            tokens = tokens[:tokens.index(self.eos_id)]
        return {
            "text": self.decode_text(tokens),
            "tokens": len(tokens),
            "model": self.model_name,
        }


class _Handler(BaseHTTPRequestHandler):
    state: ServingState  # set by make_server

    def log_message(self, fmt, *args):  # route through our logger
        log(self.address_string(), fmt % args)

    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        if self.path != "/healthz":
            return self._json(404, {"error": "unknown path"})
        st = self.state
        if not st.ready:
            return self._json(503, {"status": "warming"})
        return self._json(200, {
            "status": "ok",
            "model": st.model_name,
            "max_new_tokens_cap": st.max_new_cap,
            "kv_quant": st.kv_quant,
        })

    def do_POST(self):  # noqa: N802
        if self.path != "/v1/completions":
            return self._json(404, {"error": "unknown path"})
        if not self.state.ready:
            return self._json(503, {"error": "warming"})
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body, dict) or "prompt" not in body:
                raise ValueError('body must be a JSON object with "prompt"')
            result = self.state.complete(
                str(body["prompt"]),
                max_new_tokens=body.get("max_new_tokens"),
                temperature=body.get("temperature", 0.0),
                top_k=body.get("top_k", 0),
                top_p=body.get("top_p", 0.0),
                seed=body.get("seed", 0),
            )
        except (ValueError, TypeError, json.JSONDecodeError) as e:
            # TypeError covers wrong-typed JSON fields (e.g. top_k: [1])
            # — a malformed request must be a 400, not a dropped socket
            return self._json(400, {"error": str(e)})
        return self._json(200, result)


def make_server(env: dict | None = None) -> ThreadingHTTPServer:
    """Build (but don't run) the server — tests drive it on an ephemeral
    port. The model is loaded and warmed before this returns."""
    env = dict(os.environ if env is None else env)
    state = ServingState(env)
    state.warm()

    handler = type("Handler", (_Handler,), {"state": state})
    host = env.get("SERVER_HOST", "127.0.0.1")
    port = int(env.get("SERVER_PORT", "8000"))
    return ThreadingHTTPServer((host, port), handler)


def main() -> int:
    server = make_server()
    host, port = server.server_address[:2]
    log(f"listening on {host}:{port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
