"""Batch-inference job entrypoint: prompts file in, completions out.

The serving analog of train/job.py — what a JobSet pod runs on a
provisioned slice (examples/jobs/serve-llama-v5e8.yaml). One compiled program
serves the whole file: prompts are tokenized, right-padded to one static
width (ragged semantics — each row generates from its own last real
token, models/decode.py), batched to a fixed row count, and generated
through the tensor-parallel sharded path (parallel/serving.py). The
whole pipeline is env-driven like the trainer:

  SERVE_MODEL          preset name (default llama-test) — random init,
                       smoke/bring-up mode
  SERVE_HF_CHECKPOINT  LOCAL transformers checkpoint dir (overrides
                       SERVE_MODEL; models/convert_hf.load_hf — llama or
                       mixtral; never downloads)
  SERVE_TOKENIZER      'byte' (default) or 'hf:<local path>'
                       (train/corpus.py's resolver)
  SERVE_PROMPTS        path to a UTF-8 text file, one prompt per line
  SERVE_OUT            output path ('-' = stdout, default); one
                       completion per line, same order (newlines inside a
                       completion are escaped as \\n so line i always
                       pairs with prompt i)
  SERVE_BATCH          rows per compiled call (default 8; the last batch
                       pads with repeats, extras dropped)
  SERVE_MAX_NEW        tokens to generate per prompt (default 64)
  SERVE_MESH           e.g. 'tensor=4' or 'data=2,tensor=2'
                       (default: tensor over all local devices)
  SERVE_QUANT          'int8' → weight-only quantized export
                       (models/quant.py); empty = model dtype
  SERVE_DTYPE          'float32' | 'bfloat16': override the compute
                       dtype (f32 makes greedy responses bitwise-
                       comparable across serving modes/spans — the
                       debugging/eval knob, 2x weight bytes)
  SERVE_KV_QUANT       =1: int8 KV cache (half the cache bytes per
                       decode step; bounded attention rounding —
                       models/decode.KVCache). Composes with SERVE_QUANT;
                       rejected in the BATCH JOB's speculative/
                       prompt-lookup modes (their solo verification
                       loops keep a full-precision cache). The server's
                       slot engine verifies exactly from int8 too, so
                       there speculation composes with SERVE_KV_QUANT.
  SERVE_CACHE_SPAN     pin the KV-cache span (cache size changes XLA's
                       attention reduction order, so pinning it makes
                       runs bitwise-comparable across pipelines;
                       default: fits prompt+max_new exactly)
  SERVE_TEMPERATURE / SERVE_TOP_K / SERVE_TOP_P / SERVE_SEED
  SERVE_EOS_ID         stop rows at this token (emitted tokens after it
                       are dropped from the text)
  SERVE_DRAFT_MODEL /  enable SPECULATIVE decoding: the draft preset /
  SERVE_DRAFT_HF_CHECKPOINT  local transformers dir proposes
                       SERVE_DRAFT_K (default 4) tokens per target pass
                       (models/speculative.py). Greedy only
                       (SERVE_TEMPERATURE must stay 0), batch-1 per
                       prompt (one retrace per distinct prompt length),
                       single-device (SERVE_MESH ignored); output is
                       token-identical to the non-draft greedy path
                       (up to float ties — models/speculative.py).
  SERVE_PROMPT_LOOKUP  =1: speculative decoding WITHOUT a draft model —
                       n-gram (SERVE_NGRAM, default 2) matches in the
                       seen context propose continuations
                       (SERVE_DRAFT_K defaults to 8 here). When a
                       SERVE_DRAFT_* model is also set, the draft model
                       proposes (proposals never change tokens, so the
                       precedence moves acceptance rate, not output);
                       same greedy/batch-1 rules here. The HTTP
                       server's slot engine lifts the batch-1 and
                       single-device limits: with
                       SERVE_CONTINUOUS_BATCHING=1 either proposer
                       drives per-round (slots, draft_k+1) verify
                       windows (docs/guide/serving.md "Speculative
                       continuous batching").
  SERVE_DRAFT_KV_QUANT =1: int8 KV cache for the DRAFT model only —
                       drafts propose, never verify, so this can change
                       the acceptance rate but never the tokens (the
                       target's verification cache stays full
                       precision). Requires SERVE_DRAFT_*.

The live HTTP server (serve/server.py) consumes this same contract via
``load_serving_stack`` and layers its serving-only knobs on top —
SERVER_HOST/SERVER_PORT, SERVER_BATCH/SERVER_BATCH_WINDOW_MS,
SERVE_PREFIX_CACHE_MB (bounded-LRU prefix KV-cache reuse),
SERVE_EARLY_EXIT_STEPS (early-exit decode liveness interval) and
SERVE_CONTINUOUS_BATCHING (persistent slot-engine decode: requests are
admitted into the running batch between segments, SERVER_BATCH doubling
as the slot count) and SERVE_KV_POOL_MB/SERVE_KV_PAGE_SIZE (paged KV
cache for the slot engine: one shared page pool, admission gated on
free pages, warm prefixes pinned zero-copy) — all documented there.
SERVE_MESH composes with the server's slot engine end to end (KV and
the page pool shard over kv heads, MoE decode routes expert-parallel
on an ``expert`` axis — docs/guide/serving.md "Sharded continuous
batching"); the batch job runs one fused program per batch, so
per-request caching/early-exit/slot/page scheduling does not apply
here.

The reference provisioner has no inference plane (SURVEY §0); this
completes the in-tree stack's serving story end to end (provision →
import weights → quantize → shard → serve).
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

from tpu_kubernetes.util.envparse import env_float, env_int


def log(*args) -> None:
    print("[serve]", *args, file=sys.stderr, flush=True)


def truthy_env(env: dict, name: str) -> bool:
    """One falsy-string rule for the whole SERVE_* env contract (shared
    with serve/server.py — diverging copies would make the batch job and
    the HTTP server read the same env differently). The rule itself
    lives in util/envparse.py with the other env chokepoints."""
    from tpu_kubernetes.util.envparse import env_bool

    return env_bool(name, env=env)


def _detokenizer(spec: str):
    """Inverse of train/corpus.py's tokenizers: ids → text. The byte
    decoder never silently drops ids — run_serving refuses up front when
    the model can emit ids the tokenizer cannot render."""
    if spec == "byte":
        return lambda ids: bytes(ids).decode("utf-8", errors="replace")
    if spec.startswith("hf:"):
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(
            spec[3:], local_files_only=True
        )
        return lambda ids: tok.decode(list(ids), skip_special_tokens=True)
    raise ValueError(f"unknown tokenizer {spec!r}")


def load_serving_stack(env: dict):
    """The env-driven model/tokenizer bring-up shared by the batch job
    and the HTTP server (serve/server.py): SERVE_MODEL /
    SERVE_HF_CHECKPOINT / SERVE_TOKENIZER / SERVE_QUANT →
    (params, cfg, encode, decode_text)."""
    import jax

    from tpu_kubernetes.models import CONFIGS, init_params
    from tpu_kubernetes.models.quant import quantize_for_decode
    from tpu_kubernetes.train.corpus import resolve_tokenizer

    tok_spec = env.get("SERVE_TOKENIZER", "byte")
    encode, vocab = resolve_tokenizer(tok_spec)
    decode_text = _detokenizer(tok_spec)

    hf_path = env.get("SERVE_HF_CHECKPOINT", "")
    t0 = time.perf_counter()
    if hf_path:
        from tpu_kubernetes.models import load_hf

        params, cfg = load_hf(hf_path)
        log(f"loaded HF checkpoint {hf_path} in {time.perf_counter()-t0:.1f}s")
    else:
        cfg = CONFIGS[env.get("SERVE_MODEL", "llama-test")]
        params = init_params(jax.random.PRNGKey(0), cfg)
        log(f"random-init {env.get('SERVE_MODEL', 'llama-test')} "
            "(smoke mode — set SERVE_HF_CHECKPOINT for real weights)")
    dtype_env = env.get("SERVE_DTYPE", "")
    if dtype_env:
        # SERVE_DTYPE=float32: serve above the model's storage precision.
        # bf16 argmax can flip on near-tied logits when the cache span or
        # program shape changes (models/speculative.py's caveat); f32
        # makes responses bitwise-comparable across serving modes —
        # the debugging/eval knob, at 2x the weight bytes
        import jax.numpy as jnp
        from dataclasses import replace as _replace

        dtypes = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}
        if dtype_env not in dtypes:
            raise SystemExit(
                f"SERVE_DTYPE must be one of {sorted(dtypes)}, "
                f"got {dtype_env!r}"
            )
        cfg = _replace(cfg, dtype=dtypes[dtype_env])
        params = jax.tree.map(
            lambda p: p.astype(dtypes[dtype_env])
            if p.dtype in (jnp.bfloat16, jnp.float32) else p,
            params,
        )
    if vocab > cfg.vocab_size:
        raise SystemExit(
            f"tokenizer vocab {vocab} exceeds model vocab {cfg.vocab_size}"
        )
    if vocab < cfg.vocab_size:
        # the model can sample ids the tokenizer cannot render — garbled
        # output with no diagnostic; demand a matching tokenizer
        raise SystemExit(
            f"tokenizer vocab {vocab} cannot render model vocab "
            f"{cfg.vocab_size} — pass the model's own tokenizer "
            "(SERVE_TOKENIZER=hf:<path>)"
        )

    if env.get("SERVE_QUANT", "") == "int8":
        params = quantize_for_decode(params, cfg)
        log("int8 weight-only export")
    return params, cfg, encode, decode_text


def run_serving(env: dict | None = None) -> list[str]:
    """The whole pipeline; ``env`` defaults to os.environ (injectable for
    tests). Returns the completions (also written to SERVE_OUT).

    Multi-host: consumes the provisioner's env contract exactly like the
    trainer (train/job.py) — ``initialize()`` assembles the slice over
    DCN before any device use, the mesh spans GLOBAL devices, every
    process runs the same compiled calls (outputs are replicated), and
    only process 0 writes SERVE_OUT/stdout. A v5p-32 slice (4 hosts)
    serves with one tensor-parallel program the same way it trains."""
    env = dict(os.environ if env is None else env)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_kubernetes.models import CONFIGS, init_params
    from tpu_kubernetes.parallel import (
        create_mesh,
        initialize,
        make_sharded_generate,
    )

    denv = initialize(env)
    is_primary = denv.process_id == 0
    if denv.multi_host:
        log(f"process {denv.process_id}/{denv.num_processes} "
            f"accelerator={denv.accelerator_type}")

    prompts_path = env.get("SERVE_PROMPTS", "")
    if not prompts_path:
        raise SystemExit("SERVE_PROMPTS must point at a prompts file")
    prompts = Path(prompts_path).read_text(encoding="utf-8").splitlines()
    if not prompts:
        raise SystemExit(f"{prompts_path} holds no prompts")

    params, cfg, encode, decode_text = load_serving_stack(env)

    mesh_spec = env.get("SERVE_MESH", "")
    if mesh_spec:
        from tpu_kubernetes.topology import parse_mesh_shape

        shape = parse_mesh_shape(mesh_spec)
    else:
        shape = {"tensor": len(jax.devices())}
    from tpu_kubernetes.parallel import device_prefix_for

    # a mesh smaller than the host is a valid ask (e.g. tensor=4 on a
    # v5e-8) — take a device prefix, like the HTTP server. Multi-host
    # keeps the strict all-devices contract: slicing global devices
    # could leave a process with nothing addressable.
    try:
        devices = device_prefix_for(
            shape, jax.devices(), allow_partial=not denv.multi_host,
            label="SERVE_MESH",
        )
    except ValueError as e:
        raise SystemExit(str(e)) from e
    if len(devices) < len(jax.devices()):
        log(f"partial-host mesh: {len(devices)} of "
            f"{len(jax.devices())} devices")
    mesh = create_mesh(shape, devices=devices)
    log(f"mesh={dict(mesh.shape)}")

    max_new = env_int("SERVE_MAX_NEW", 64, env=env)
    batch_rows = env_int("SERVE_BATCH", 8, env=env)
    eos_env = env.get("SERVE_EOS_ID", "")
    eos_id = int(eos_env) if eos_env else None
    pad_id = 0

    token_rows = [encode(p) for p in prompts]
    if any(not r for r in token_rows):
        raise SystemExit("empty prompt line — every line must tokenize "
                         "to at least one token")
    width = max(len(r) for r in token_rows)
    if width + max_new > cfg.max_seq:
        raise SystemExit(
            f"longest prompt ({width}) + SERVE_MAX_NEW ({max_new}) "
            f"exceeds the model's max_seq {cfg.max_seq}"
        )

    def finish(row_ids) -> None:
        ids = list(row_ids)
        if eos_id is not None and eos_id in ids:
            ids = ids[:ids.index(eos_id)]
        nonlocal n_tokens
        n_tokens += len(ids)
        completions.append(decode_text(ids))

    completions: list[str] = []
    n_tokens = 0
    draft_hf = env.get("SERVE_DRAFT_HF_CHECKPOINT", "")
    draft_name = env.get("SERVE_DRAFT_MODEL", "")
    lookup = truthy_env(env, "SERVE_PROMPT_LOOKUP")
    kv_quant = truthy_env(env, "SERVE_KV_QUANT")
    if truthy_env(env, "SERVE_DRAFT_KV_QUANT") and not (draft_hf or draft_name):
        # refuse rather than silently drop the knob (file policy): with
        # no draft model there is no draft cache to quantize
        raise SystemExit(
            "SERVE_DRAFT_KV_QUANT needs a draft model "
            "(SERVE_DRAFT_MODEL / SERVE_DRAFT_HF_CHECKPOINT)"
        )
    if draft_hf or draft_name or lookup:
        if kv_quant:
            # refuse rather than silently drop the knob: the speculative
            # paths are exactness-first and run their own bf16 caches
            raise SystemExit(
                "SERVE_KV_QUANT is not supported in speculative/"
                "prompt-lookup modes (exact verification uses a "
                "full-precision cache) — drop one of the knobs"
            )
        # --- speculative decoding: batch-1, greedy, single-device ------
        # cheap config rejections first — before any checkpoint I/O
        if env_float("SERVE_TEMPERATURE", 0.0, env=env) != 0.0:
            raise SystemExit(
                "speculative decoding is greedy: unset SERVE_TEMPERATURE "
                "or drop the SERVE_DRAFT_*/SERVE_PROMPT_LOOKUP config"
            )
        import functools

        from tpu_kubernetes.models import MoEConfig

        if isinstance(cfg, MoEConfig):
            raise SystemExit(
                "speculative decoding needs a dense TARGET model (MoE "
                "chunk verification is not token-exact); MoE drafts are fine"
            )
        draft_k = env_int("SERVE_DRAFT_K", 8 if lookup else 4, env=env)
        ngram = env_int("SERVE_NGRAM", 2, env=env)
        if draft_k < 1 or ngram < 1:
            raise SystemExit(
                f"SERVE_DRAFT_K ({draft_k}) and SERVE_NGRAM ({ngram}) "
                "must be >= 1"
            )
        if width + max_new + draft_k > cfg.max_seq:
            raise SystemExit(
                f"longest prompt ({width}) + SERVE_MAX_NEW ({max_new}) "
                f"+ SERVE_DRAFT_K ({draft_k}) exceeds the target "
                f"model's max_seq {cfg.max_seq}"
            )

        if lookup and (draft_hf or draft_name):
            # both proposers configured: the draft model wins. Drafts
            # only PROPOSE — the target verifies every token — so the
            # choice moves the acceptance rate, never the output. Same
            # precedence as the HTTP server's slot-engine proposer
            # (serve/server.py spec_source).
            log("draft: SERVE_PROMPT_LOOKUP and SERVE_DRAFT_* both set "
                "— the draft model proposes (lookup ignored)")
            lookup = False
        draft_kv = truthy_env(env, "SERVE_DRAFT_KV_QUANT")
        if lookup:
            from tpu_kubernetes.models import prompt_lookup_generate

            log(f"draft: prompt-lookup (ngram={ngram}, no draft model)")
            spec = jax.jit(functools.partial(
                prompt_lookup_generate, cfg=cfg,
                max_new_tokens=max_new, draft_k=draft_k, ngram=ngram,
            ))

            def run_one(row):
                return spec(params, jnp.asarray([row], jnp.int32))
        else:
            from tpu_kubernetes.models import speculative_generate

            if draft_hf:
                from tpu_kubernetes.models import load_hf

                draft_params, draft_cfg = load_hf(draft_hf)
                log(f"draft: HF checkpoint {draft_hf}")
            else:
                draft_cfg = CONFIGS[draft_name]
                draft_params = init_params(jax.random.PRNGKey(1), draft_cfg)
                log(f"draft: random-init {draft_name} (smoke mode)")
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise SystemExit(
                    f"draft vocab {draft_cfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size} — the models must share a tokenizer"
                )
            if width + max_new + draft_k > draft_cfg.max_seq:
                raise SystemExit(
                    f"longest prompt ({width}) + SERVE_MAX_NEW ({max_new}) "
                    f"+ SERVE_DRAFT_K ({draft_k}) exceeds the draft "
                    f"model's max_seq {draft_cfg.max_seq}"
                )
            if draft_kv:
                log("draft: int8 KV cache (proposals only — exactness "
                    "unaffected)")
            spec = jax.jit(functools.partial(
                speculative_generate, cfg=cfg, draft_cfg=draft_cfg,
                max_new_tokens=max_new, draft_k=draft_k,
                draft_kv_quant=draft_kv,
            ))

            def run_one(row):
                return spec(params, draft_params, jnp.asarray([row], jnp.int32))

        t0 = time.perf_counter()
        drafted = accepted = 0
        for row in token_rows:
            out, stats = run_one(row)
            drafted += int(stats.drafted)
            accepted += int(stats.accepted)
            finish(np.asarray(out)[0].tolist())
        log(f"speculative: k={draft_k}, accepted {accepted}/{drafted} "
            f"({accepted / max(1, drafted):.0%})")
    else:
        # SERVE_CACHE_SPAN: optional KV-cache span override — cache size
        # changes XLA's attention reduction order, so pinning it makes
        # runs bitwise-comparable across pipelines (models/decode.generate)
        span_env = env.get("SERVE_CACHE_SPAN", "")
        fn, p_sh, b_sh = make_sharded_generate(
            cfg, mesh, params, max_new_tokens=max_new,
            temperature=env_float("SERVE_TEMPERATURE", 0.0, env=env),
            top_k=env_int("SERVE_TOP_K", 0, env=env),
            top_p=env_float("SERVE_TOP_P", 0.0, env=env),
            eos_id=eos_id, pad_id=pad_id,
            cache_span=int(span_env) if span_env else None,
            kv_quant=kv_quant,
        )

        def to_global(x, sh):
            """Host data (identical on every process) → a global array:
            device_put cannot target non-addressable devices, so the
            multi-host path assembles shards via make_array_from_callback
            (each process fills exactly its addressable pieces)."""
            if not denv.multi_host:
                return jax.device_put(x, sh)
            x = np.asarray(x)
            return jax.make_array_from_callback(
                x.shape, sh, lambda idx, x=x: x[idx]
            )

        params = jax.tree.map(lambda p, s: to_global(p, s), params, p_sh)
        rng = jax.random.PRNGKey(env_int("SERVE_SEED", 0, env=env))

        t0 = time.perf_counter()
        for start in range(0, len(token_rows), batch_rows):
            rows = token_rows[start:start + batch_rows]
            n_real = len(rows)
            rows = rows + [rows[-1]] * (batch_rows - n_real)  # pad the batch
            lengths = jnp.asarray([len(r) for r in rows], jnp.int32)
            padded = np.zeros((batch_rows, width), np.int32)
            for i, r in enumerate(rows):
                padded[i, :len(r)] = r
            rng, call_rng = jax.random.split(rng)
            out = fn(
                params, to_global(padded, b_sh),
                rng=call_rng, prompt_lengths=lengths,
            )
            # out is replicated (parallel/serving.py out_shardings), so
            # every process can read it and completions stay identical
            for row in np.asarray(out)[:n_real]:
                finish(row.tolist())
    dt = time.perf_counter() - t0
    log(f"{len(prompts)} prompts, {n_tokens} tokens "
        f"in {dt:.1f}s ({n_tokens / dt:.0f} tok/s)")

    out_path = env.get("SERVE_OUT", "-")
    # keep the line↔prompt pairing exact no matter what the model emits
    escaped = [
        c.replace("\\", "\\\\").replace("\n", "\\n").replace("\r", "\\r")
        for c in completions
    ]
    text = "\n".join(escaped) + "\n"
    if is_primary:
        # multi-host: every process computed identical (replicated)
        # completions; exactly one writes them
        if out_path == "-":
            sys.stdout.write(text)
        else:
            Path(out_path).write_text(text, encoding="utf-8")
            log(f"wrote {out_path}")
    return completions


def main() -> int:
    run_serving()
    return 0


if __name__ == "__main__":
    sys.exit(main())
