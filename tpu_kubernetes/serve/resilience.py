"""Serve-path resilience primitives: deadlines, load shedding, drain,
and the self-healing watchdog.

The server (serve/server.py) had exactly one bounded failure mode —
"the request errors" — and three unbounded ones: a slow client could
hold an engine slot forever, a traffic spike could queue unboundedly
behind the generation lock, and a dead scheduler thread hung every
future submitter. This module is the policy layer that bounds them:

* **Deadlines** (:class:`DeadlineExceeded`): every request may carry a
  monotonic deadline (``SERVE_DEADLINE_MS`` default, ``deadline_ms``
  per-request override). Expired entries are failed out of queues and
  retired mid-flight from engine slots — the slot is the scarce
  resource, and a request nobody is waiting for must not spend it.
* **Admission control** (:class:`AdmissionController`,
  :class:`Overloaded`): a bounded queue (``SERVE_MAX_QUEUE``) plus
  estimated-wait gating — a request whose deadline cannot survive the
  current queue is shed NOW with 429 + ``Retry-After`` instead of
  queueing doomed work that will 504 after consuming a slot.
* **Graceful drain** (:class:`DrainController`, :class:`Draining`):
  SIGTERM / ``POST /drain`` stops admission (new work gets 503),
  finishes resident slots, flushes metrics/events, flips ``/healthz``
  to ``draining`` — the contract a Kubernetes preStop hook needs.
* **Self-healing** (:class:`Watchdog`): a dead engine scheduler thread
  is restarted with a cold engine reset, bounded times
  (``SERVE_MAX_ENGINE_RESTARTS``); past the bound the process hard-fails
  ``/healthz`` so the fleet replaces it (the Podracer stance: cheap
  restart IS the recovery primitive).

Everything here is dependency-free and jax-free so the policy is unit-
testable without a model; serve/server.py wires it to the engine.
"""

from __future__ import annotations

import threading
import time

from tpu_kubernetes.obs import REGISTRY
from tpu_kubernetes.util import log

SHED_TOTAL = REGISTRY.counter(
    "tpu_serve_shed_total",
    "requests rejected by admission control with 429 (queue_full = the "
    "bounded queue was at SERVE_MAX_QUEUE; doomed_deadline = the "
    "estimated queue wait already exceeded the request's deadline)",
    labelnames=("reason",),
)
DEADLINE_TOTAL = REGISTRY.counter(
    "tpu_serve_deadline_exceeded_total",
    "requests failed for missing their deadline, by where it expired "
    "(preflight = before generation, queued = waiting for dispatch or "
    "a slot, resident = retired mid-flight from an engine slot)",
    labelnames=("stage",),
)
CANCELLED_TOTAL = REGISTRY.counter(
    "tpu_serve_cancelled_total",
    "requests cancelled before finishing, by cause (disconnect = the "
    "SSE client went away and generation was stopped early)",
    labelnames=("reason",),
)
ENGINE_RESTARTS = REGISTRY.counter(
    "tpu_serve_engine_restarts_total",
    "continuous-engine scheduler threads restarted cold by the "
    "watchdog (bounded by SERVE_MAX_ENGINE_RESTARTS, then /healthz "
    "hard-fails)",
)
FALLBACKS = REGISTRY.counter(
    "tpu_serve_fallback_total",
    "serving-lever fallbacks taken, by reason (each occurrence counts; "
    "the warning logs once per process per reason)",
    labelnames=("reason",),
)

_WARNED: set[str] = set()
_WARNED_LOCK = threading.Lock()


def warn_once(reason: str, message: str) -> None:
    """Count every occurrence in ``tpu_serve_fallback_total{reason}``
    but log the warning once per process per reason — fallbacks taken
    per request must not turn the log into spam when the metric already
    carries the rate."""
    FALLBACKS.labels(reason).inc()
    with _WARNED_LOCK:
        if reason in _WARNED:
            return
        _WARNED.add(reason)
    log.warn(message)


def reset_warned() -> None:
    """Test isolation: forget which reasons already logged."""
    with _WARNED_LOCK:
        _WARNED.clear()


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before (or while) serving it —
    surfaced to HTTP clients as 504."""


class Cancelled(RuntimeError):
    """The client stopped caring (disconnect) — the work was retired."""


class Overloaded(RuntimeError):
    """Admission control shed this request — surfaced as 429 with a
    ``Retry-After`` of :attr:`retry_after_s` seconds."""

    def __init__(self, message: str, retry_after_s: int = 1):
        super().__init__(message)
        self.retry_after_s = max(1, int(retry_after_s))


class Draining(RuntimeError):
    """The server is draining — new work gets 503 and should go to a
    sibling instance."""


def deadline_from(t0: float, deadline_ms: float | None,
                  default_ms: float = 0.0) -> float | None:
    """Request deadline as a monotonic timestamp anchored at ``t0`` (the
    moment the request was received — queue time before parsing counts).
    ``None`` when neither a per-request override nor a positive
    ``SERVE_DEADLINE_MS`` default applies."""
    ms = default_ms if deadline_ms is None else float(deadline_ms)
    if ms is None or ms <= 0:
        return None
    return t0 + ms / 1e3


def expired(deadline: float | None, now: float | None = None) -> bool:
    if deadline is None:
        return False
    return (time.monotonic() if now is None else now) >= deadline


class AdmissionController:
    """Bounded-queue + estimated-wait gating for one serving process.

    ``admit(depth, deadline)`` raises :class:`Overloaded` when the queue
    is at ``max_queue`` (429 beats an unbounded queue: the client's
    retry policy — not this process's memory — absorbs the spike), or
    when the EWMA-estimated wait at this depth already exceeds the
    request's remaining deadline (queueing doomed work costs a slot and
    still 504s). The wait estimate learns from ``observe_service`` —
    the enqueue→dispatch waits the engine/batcher actually measured."""

    def __init__(self, max_queue: int = 0):
        self.max_queue = max(0, int(max_queue))
        self._ewma: float | None = None
        self._lock = threading.Lock()

    def observe_service(self, seconds: float) -> None:
        with self._lock:
            self._ewma = (
                seconds if self._ewma is None
                else 0.8 * self._ewma + 0.2 * seconds
            )

    def estimated_wait(self, depth: int) -> float:
        """Expected queue wait with ``depth`` entries ahead — depth
        times the learned per-entry wait (conservative floor of one
        entry when the queue is empty but admission still costs)."""
        with self._lock:
            per = self._ewma
        return max(1, depth) * (0.05 if per is None else per)

    def admit(self, depth: int, deadline: float | None = None,
              now: float | None = None) -> None:
        if self.max_queue and depth >= self.max_queue:
            wait = self.estimated_wait(depth)
            SHED_TOTAL.labels("queue_full").inc()
            raise Overloaded(
                f"queue full ({depth} >= SERVE_MAX_QUEUE={self.max_queue})"
                " — retry against a less-loaded instance",
                retry_after_s=int(wait) + 1,
            )
        if deadline is not None:
            with self._lock:
                learned = self._ewma is not None
            if not learned:
                return          # never shed on a guess
            now = time.monotonic() if now is None else now
            wait = self.estimated_wait(depth)
            if now + wait >= deadline:
                SHED_TOTAL.labels("doomed_deadline").inc()
                raise Overloaded(
                    f"estimated queue wait {wait:.3f}s exceeds the "
                    "request deadline — shedding instead of queueing "
                    "doomed work",
                    retry_after_s=int(wait) + 1,
                )


class DrainController:
    """The drain state machine: ``serving`` → ``draining`` →
    ``drained``. ``begin`` is idempotent-ish (first caller wins and gets
    True); ``wait_drained`` is what a shutdown hook blocks on."""

    def __init__(self):
        self._lock = threading.Lock()
        self.state = "serving"
        self.reason = ""
        self._drained = threading.Event()

    @property
    def is_draining(self) -> bool:
        return self.state != "serving"

    def begin(self, reason: str = "") -> bool:
        with self._lock:
            if self.state != "serving":
                return False
            self.state = "draining"
            self.reason = reason
        log.info(f"serve: draining ({reason or 'requested'}) — "
                 "admission stopped, finishing resident work")
        return True

    def mark_drained(self) -> None:
        with self._lock:
            self.state = "drained"
        self._drained.set()

    def wait_drained(self, timeout: float | None = None) -> bool:
        return self._drained.wait(timeout)


class Watchdog:
    """Self-healing monitor for one thread-shaped resource: polls
    ``is_alive`` every ``interval_s``; on death calls ``restart`` up to
    ``max_restarts`` times, then ``on_give_up`` (the hard-fail path —
    /healthz flips so the fleet replaces this instance). The restart
    callback owns the actual recovery (the engine fails resident work
    out and starts a fresh scheduler on a cold cache)."""

    def __init__(self, is_alive, restart, max_restarts: int = 3,
                 interval_s: float = 0.5, on_give_up=None,
                 name: str = "engine"):
        self.is_alive = is_alive
        self.restart = restart
        self.max_restarts = max(0, int(max_restarts))
        self.interval_s = interval_s
        self.on_give_up = on_give_up
        self.name = name
        self.restarts = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"watchdog-{name}"
        )

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _give_up(self, why: str) -> None:
        log.warn(f"watchdog[{self.name}]: giving up — {why}")
        if self.on_give_up is not None:
            try:
                self.on_give_up()
            except Exception as e:  # noqa: BLE001 — last resort already
                log.warn(f"watchdog[{self.name}]: give-up hook failed: {e}")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                alive = bool(self.is_alive())
            except Exception:  # noqa: BLE001 — a broken probe reads as dead
                alive = False
            if alive:
                continue
            if self.restarts >= self.max_restarts:
                self._give_up(
                    f"{self.restarts} restarts exhausted "
                    f"(SERVE_MAX_ENGINE_RESTARTS={self.max_restarts})"
                )
                return
            self.restarts += 1
            log.warn(
                f"watchdog[{self.name}]: thread dead — cold restart "
                f"{self.restarts}/{self.max_restarts}"
            )
            try:
                self.restart()
            except Exception as e:  # noqa: BLE001 — restart itself broke
                self._give_up(f"restart failed: {type(e).__name__}: {e}")
                return
