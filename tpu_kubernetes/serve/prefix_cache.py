"""Bounded LRU of KV-cache prompt prefixes (the serve-side prefix
reuse store behind ``SERVE_PREFIX_CACHE_MB``).

A request whose prompt shares a token-id prefix with an earlier request
can skip recomputing that prefix's K/V: causality makes cache slot i a
pure function of tokens ≤ i, so ANY stored segment is reusable up to
the longest common prefix with the new prompt — even a *partial* match
against a longer stored entry is valid (the first q slots of a p-slot
segment are exactly what a fresh prefill of those q tokens computes).

This module is deliberately a plain container: it stores opaque device
arrays (shaped (layers, 1, kv_heads, n_tokens, head_dim) by the server's
convention, plus int8 scales when KV-quantized) and never imports jax —
the server module must import without jax, and so must this one. All
slicing/padding of the arrays happens at the call site.

Sizing is byte-accurate (``arr.size * arr.dtype.itemsize`` summed over
the stored arrays); eviction is least-recently-USED (lookup hits and
covered inserts refresh recency). Subsumption keeps the store minimal:
inserting ids already covered by a stored entry only refreshes that
entry; inserting an extension REPLACES the shorter entry. Thread-safe —
handler threads and the batch dispatcher share one store."""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable


def _arrays_nbytes(arrays: dict[str, Any]) -> int:
    return sum(
        int(a.size) * int(a.dtype.itemsize)
        for a in arrays.values() if a is not None
    )


def _common_prefix_len(a: tuple, b: tuple) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


@dataclass
class PrefixEntry:
    """One cached prefix: ``arrays`` hold exactly ``len(ids)`` filled
    positions (the server trims pad garbage before inserting)."""

    ids: tuple[int, ...]
    arrays: dict[str, Any]
    nbytes: int = field(init=False)

    def __post_init__(self):
        self.nbytes = _arrays_nbytes(self.arrays)


@dataclass
class PagedPrefixEntry:
    """One cached prefix under the PAGED engine: instead of byte-copied
    arrays, ``pages`` are the device pool page ids holding the prefix's
    K/V (whole pages only — ``len(ids)`` is a page_size multiple). The
    store OWNS these pages (PagePool.pin): a warm hit pins them
    read-only into the new slot's table zero-copy, and copy-on-write is
    structural — appends land past the prompt width in fresh pages, so
    a shared page is never written after insert. ``nbytes`` is the
    device bytes the pages occupy (page_bytes x len(pages)), passed in
    because this module never sees the device arrays. Eviction must
    reach the pool: the cache's ``on_evict`` callback is how the engine
    unpins (and wipes) a dropped entry's pages."""

    ids: tuple[int, ...]
    pages: tuple[int, ...]
    nbytes: int


class PrefixCache:
    """Longest-prefix-match LRU over :class:`PrefixEntry`, capped at
    ``max_bytes``. ``sig`` records the model/config signature the
    segments were computed under — one store never serves two models,
    but the signature makes that checkable (``stats()``) instead of
    implicit. ``on_bytes`` (when given) observes the post-op total —
    the server points it at the prefix-cache bytes gauge."""

    def __init__(self, max_bytes: int, sig: tuple = (),
                 on_bytes: Callable[[int], None] | None = None,
                 on_evict: Callable[[Any], None] | None = None):
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be > 0, got {max_bytes}")
        self.max_bytes = int(max_bytes)
        self.sig = tuple(sig)
        self._on_bytes = on_bytes
        # on_evict(entry) fires for every entry the store DROPS — LRU
        # eviction and extension replacement both — after the lock is
        # released (the paged engine's handler takes its own locks to
        # unpin + wipe the entry's pool pages; calling back under ours
        # would order the two locks both ways)
        self._on_evict = on_evict
        self._entries: OrderedDict[tuple, Any] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    # -- internals (caller holds the lock) ---------------------------------

    def _notify(self) -> None:
        if self._on_bytes is not None:
            self._on_bytes(self._bytes)

    def _evict_to_cap(self) -> list:
        dropped = []
        while self._bytes > self.max_bytes and self._entries:
            _, old = self._entries.popitem(last=False)   # least recent
            self._bytes -= old.nbytes
            dropped.append(old)
        return dropped

    def _notify_evicted(self, dropped: list) -> None:
        """Caller must NOT hold the lock (see on_evict above)."""
        if self._on_evict is not None:
            for e in dropped:
                self._on_evict(e)

    # -- API ---------------------------------------------------------------

    def lookup(self, ids: list[int]) -> tuple[int, PrefixEntry | None]:
        """Longest common prefix across the store → (match length, the
        matching entry). A hit refreshes the entry's recency. (0, None)
        when nothing shares even one token."""
        key = tuple(ids)
        with self._lock:
            best_q, best = 0, None
            for entry in self._entries.values():
                q = _common_prefix_len(entry.ids, key)
                if q > best_q:
                    best_q, best = q, entry
            if best is not None:
                self._entries.move_to_end(best.ids)
            return best_q, best

    def insert(self, ids: list[int], arrays: dict[str, Any]) -> bool:
        """Store a segment for ``ids`` (arrays trimmed to len(ids)
        positions). Returns True when stored; False when skipped — ids
        already covered by a stored entry (recency refreshed instead)
        or the segment alone exceeds the cap. Inserting an EXTENSION of
        a stored prefix replaces the shorter entry; eviction then drops
        least-recently-used entries until the total fits the cap."""
        return self._insert_entry(PrefixEntry(ids=tuple(ids),
                                              arrays=arrays))

    def insert_paged(self, ids: list[int], pages: list[int],
                     nbytes: int) -> bool:
        """Store a PAGED prefix (device pool page ids, see
        :class:`PagedPrefixEntry`). Same subsumption/eviction contract
        as :meth:`insert`; the caller pins the pages only on True (a
        skipped insert must not strand a pin)."""
        return self._insert_entry(PagedPrefixEntry(
            ids=tuple(ids), pages=tuple(pages), nbytes=int(nbytes),
        ))

    def _insert_entry(self, entry: Any) -> bool:
        key = entry.ids
        if not key:
            return False
        if entry.nbytes > self.max_bytes:
            return False
        dropped: list = []
        with self._lock:
            for have in list(self._entries.values()):
                q = _common_prefix_len(have.ids, key)
                if q == len(key) and len(have.ids) >= len(key):
                    # covered: the stored entry serves this prefix and
                    # more — storing again would only duplicate bytes
                    self._entries.move_to_end(have.ids)
                    self._notify()
                    return False
                if q == len(have.ids) and len(key) > len(have.ids):
                    # extension: the new segment subsumes the stored one
                    del self._entries[have.ids]
                    self._bytes -= have.nbytes
                    dropped.append(have)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            dropped += self._evict_to_cap()
            self._notify()
        self._notify_evicted(dropped)
        return True

    def clear(self, notify: bool = False) -> int:
        """Drop every entry; returns how many. The paged engine's
        cold-reset path: a reinitialized page pool invalidates stored
        page ids wholesale, so the default drops WITHOUT on_evict (the
        pages no longer exist to unpin); ``notify=True`` routes the
        drops through on_evict for an orderly teardown instead."""
        with self._lock:
            dropped = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
            self._notify()
        if notify:
            self._notify_evicted(dropped)
        return len(dropped)

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        """The /healthz mirror: entry count, bytes vs cap, signature."""
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "sig": list(self.sig),
            }
