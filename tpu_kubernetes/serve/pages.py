"""Host-side KV page accounting for the paged slot engine
(``SERVE_KV_POOL_MB`` > 0): the free list, per-page reference counts,
and the prefix store's pins over the device pool models/decode.py's
:class:`PagedKVCache` provides.

Allocation is HOST-AUTHORITATIVE by design: compiled programs never
touch the free list (static shapes — the engine tops every live row's
page table up *before* each segment), so this module stays jax-free and
single-threaded-simple (the scheduler thread owns it; other threads
only read ``stats()``).

Page states are disjoint and conserved — every usable page is exactly
one of:

* **free**   — on the free list, wiped bitwise-cold on the device;
* **live**   — referenced by at least one resident slot's table and
  not owned by the prefix store;
* **pinned** — owned by the prefix store (a shared read-only prompt
  prefix), whether or not slots currently also reference it.

``free + live + pinned == total`` is the no-leak invariant the chaos
matrix asserts (tests/test_faults.py); :meth:`stats` recomputes the
partition from the ground truth every call, so a page dropped on the
floor breaks the sum instead of hiding. Physical page 0 — the
compiled-program write sink for retired rows — is outside the pool and
outside the arithmetic.

A page is handed back to the free list only when its refcount is zero
AND the store does not own it: a slot retiring decrements its pages
(shared prefix pages survive for the next warm hit), a store eviction
unpins (resident readers keep the page alive until they retire).
``allocate`` is a registered chaos site (``serve.page_alloc``) so fault
injection exercises the admission/preemption paths that consume it.
"""

from __future__ import annotations

from tpu_kubernetes.obs.faults import FAULTS


class PagePool:
    """Free list + refcounts for ``total`` usable device pages,
    numbered 1..total (page 0 is the device-side write sink and never
    leaves this constructor). FIFO reuse keeps recycling pressure even
    across the pool, which also makes leak tests deterministic."""

    def __init__(self, total: int):
        if total < 1:
            raise ValueError(f"page pool needs >= 1 page, got {total}")
        self.total = int(total)
        self._free: list[int] = list(range(1, self.total + 1))
        self._refs: dict[int, int] = {}
        self._pinned: set[int] = set()
        self.stalls = 0          # allocation requests the pool rejected

    # -- slot lifecycle -----------------------------------------------------

    def allocate(self, n: int) -> list[int] | None:
        """Take ``n`` pages off the free list at refcount 1, or None
        when the pool cannot satisfy the request (the caller stalls
        admission or preempts — partial grants would leak on the error
        path). Fires the ``serve.page_alloc`` chaos site."""
        FAULTS.fire("serve.page_alloc")
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            self.stalls += 1
            return None
        got, self._free = self._free[:n], self._free[n:]
        for p in got:
            self._refs[p] = 1
        return got

    def ref(self, pages: list[int]) -> None:
        """A slot starts sharing already-resident pages (a warm-prefix
        hit referencing the store's pinned pages)."""
        for p in pages:
            self._refs[p] = self._refs.get(p, 0) + 1

    def release(self, pages: list[int]) -> list[int]:
        """Drop one reference per page; returns the pages that became
        FREE (refcount 0, not store-owned) — the caller must wipe
        exactly these on the device before reuse."""
        freed = []
        for p in pages:
            left = self._refs.get(p, 0) - 1
            if left < 0:
                raise RuntimeError(f"page {p} released below zero refs")
            if left:
                self._refs[p] = left
                continue
            self._refs.pop(p, None)
            if p not in self._pinned:
                self._free.append(p)
                freed.append(p)
        return freed

    # -- prefix-store lifecycle ---------------------------------------------

    def pin(self, pages: list[int]) -> None:
        """The prefix store takes ownership of resident pages (they
        must currently be referenced — pinning free pages would
        resurrect wiped bytes)."""
        for p in pages:
            if p not in self._refs and p not in self._pinned:
                raise RuntimeError(f"cannot pin non-resident page {p}")
            self._pinned.add(p)

    def unpin(self, pages: list[int]) -> list[int]:
        """Store eviction: release ownership; returns pages now free
        (no slot still reads them) for the caller to wipe."""
        freed = []
        for p in pages:
            self._pinned.discard(p)
            if self._refs.get(p, 0) == 0:
                self._free.append(p)
                freed.append(p)
        return freed

    # -- observability ------------------------------------------------------

    def free_count(self) -> int:
        return len(self._free)

    def stats(self) -> dict:
        """The disjoint free/live/pinned partition (the /healthz and
        gauge feed). Recomputed from the ground truth so
        ``free + live + pinned == total`` failing IS a leak, not a
        bookkeeping echo."""
        free = len(self._free)
        pinned = len(self._pinned)
        live = len([p for p in self._refs if p not in self._pinned])
        return {
            "total": self.total,
            "free": free,
            "live": live,
            "pinned": pinned,
            "stalls": self.stalls,
        }
