"""tpu_kubernetes.serve — the batch-inference entrypoint of the in-tree
stack (``python -m tpu_kubernetes.serve.job``), the serving analog of
tpu_kubernetes.train.job."""

from tpu_kubernetes.serve.job import main, run_serving  # noqa: F401
