"""tpu_kubernetes.serve — the inference entrypoints of the in-tree
stack: batch (``python -m tpu_kubernetes.serve.job``, the serving analog
of tpu_kubernetes.train.job) and live HTTP
(``python -m tpu_kubernetes.serve.server``, what sits behind a
Kubernetes Service)."""

from tpu_kubernetes.serve.job import (  # noqa: F401
    load_serving_stack,
    main,
    run_serving,
)
from tpu_kubernetes.serve.server import make_server  # noqa: F401
