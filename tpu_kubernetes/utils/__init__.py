from tpu_kubernetes.utils.trace import TRACER, Span, Tracer  # noqa: F401
