"""LLaMA-family decoder-only transformer, functional JAX.

The flagship model of the in-tree training stack (the framework's
MaxText-analog example job — BASELINE.json north star trains Llama-7B on a
v5p-32 slice). TPU-first design decisions:

* Pure functional params-pytree + ``lax.scan`` over stacked layer params:
  one trace/compile of the block regardless of depth, the standard recipe
  for fast XLA compiles at 32-80 layers.
* Every parameter leaf carries a **logical axis** annotation (a parallel
  pytree of tuples) which parallel/sharding.py maps onto the device mesh
  (fsdp/tensor/data axes) — sharding lives beside the model but is not
  entangled with it.
* bfloat16 activations/weights with float32 RMSNorm and attention
  accumulation (ops/), f32 master copy optional at the optimizer level.
* GQA: n_kv_heads ≤ n_heads; KV heads are repeated just before the
  attention op (a broadcast XLA folds into the kernel's operand layout).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from tpu_kubernetes.ops import (
    apply_rope,
    flash_attention,
    next_token_nll,
    rms_norm,
    rope_frequencies,
)


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    # attention implementation knobs (forwarded to ops.flash_attention).
    # 512-blocks measured 1.40× faster than 128 end-to-end on v5e (llama-1b,
    # seq 2048); the kernel clamps them to the sequence length
    attn_block_q: int = 512
    attn_block_k: int = 512
    use_pallas: bool | None = None
    remat: bool = True
    # "dots" saves weight-matmul outputs and recomputes the cheap
    # elementwise ops (5% faster than "full" recompute on v5e, small HBM
    # cost); "full" recomputes everything (max memory headroom)
    remat_policy: str = "dots"  # "dots" | "full"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# -- presets (parameter counts approximate the named family members) -------
CONFIGS: dict[str, ModelConfig] = {
    "llama-test": ModelConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=128, remat=False,
    ),
    "llama-125m": ModelConfig(
        vocab_size=32000, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12,
        d_ff=2048, max_seq=2048,
    ),
    "llama-1b": ModelConfig(
        vocab_size=32000, d_model=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        d_ff=5632, max_seq=2048,
    ),
    "llama-7b": ModelConfig(),  # the defaults above are Llama-2-7B
    "llama-70b": ModelConfig(
        d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8, d_ff=28672,
        max_seq=4096,
    ),
}


# -- parameter init + logical axis annotations ------------------------------

def _dense_init(key, shape, dtype, fan_in):
    return (jax.random.normal(key, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(dtype)


def init_params(rng: jax.Array, cfg: ModelConfig) -> dict:
    """Parameter pytree. Layer params are stacked on a leading axis for
    lax.scan (shape (n_layers, ...))."""
    keys = jax.random.split(rng, 9)
    d, h, kv, hd, ff, L = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
        cfg.d_ff, cfg.n_layers,
    )

    def stack_init(key, shape, fan_in):
        ks = jax.random.split(key, L)
        return jnp.stack([_dense_init(k, shape, cfg.dtype, fan_in) for k in ks])

    return {
        "embed": _dense_init(keys[0], (cfg.vocab_size, d), cfg.dtype, 1.0),
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "wq": stack_init(keys[1], (d, h * hd), d),
            "wk": stack_init(keys[2], (d, kv * hd), d),
            "wv": stack_init(keys[3], (d, kv * hd), d),
            "wo": stack_init(keys[4], (h * hd, d), h * hd),
            "mlp_norm": jnp.ones((L, d), cfg.dtype),
            "w_gate": stack_init(keys[5], (d, ff), d),
            "w_up": stack_init(keys[6], (d, ff), d),
            "w_down": stack_init(keys[7], (ff, d), ff),
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        # LLaMA uses a separate (untied) output head
        "lm_head": _dense_init(keys[8], (d, cfg.vocab_size), cfg.dtype, d),
    }


def logical_axes(cfg: ModelConfig) -> dict:
    """Logical sharding axes, one tuple per param leaf (None = replicated
    dim). Mapped to mesh axes by parallel/sharding.py's rules."""
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layer", "embed"),
            "wq": ("layer", "embed", "heads"),
            "wk": ("layer", "embed", "kv"),
            "wv": ("layer", "embed", "kv"),
            "wo": ("layer", "heads", "embed"),
            "mlp_norm": ("layer", "embed"),
            "w_gate": ("layer", "embed", "mlp"),
            "w_up": ("layer", "embed", "mlp"),
            "w_down": ("layer", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def param_count(params: dict) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


# -- forward ----------------------------------------------------------------

def attention_sublayer(cfg: ModelConfig, cos, sin, x, layer):
    """Pre-norm attention + residual. x: (batch, seq, d_model). Shared by
    the dense (this file) and MoE (models/moe.py) block variants."""
    b, s, d = x.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    y = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (y @ layer["wq"]).reshape(b, s, h, hd).transpose(0, 2, 1, 3)
    k = (y @ layer["wk"]).reshape(b, s, kv, hd).transpose(0, 2, 1, 3)
    v = (y @ layer["wv"]).reshape(b, s, kv, hd).transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if kv != h:  # GQA: repeat kv heads
        rep = h // kv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    attn = flash_attention(
        q, k, v, causal=True,
        block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
        use_pallas=cfg.use_pallas,
    )
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
    return x + attn @ layer["wo"]


def _block(cfg: ModelConfig, cos, sin, x, layer):
    """One transformer block. x: (batch, seq, d_model)."""
    x = attention_sublayer(cfg, cos, sin, x, layer)

    # SwiGLU MLP
    y = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gated = jax.nn.silu(y @ layer["w_gate"]) * (y @ layer["w_up"])
    return x + gated @ layer["w_down"]


# checkpoint_name tags the MoE layer places on its routing plan and
# bucketed activations (models/moe.py) — saved under the "moe" policy so
# the backward pass never re-runs the routing machinery (argmax rounds,
# cumsums, the slot scatter, the dispatch gathers)
MOE_SAVED_NAMES = (
    "moe_plan",
    "moe_dispatch",
    "moe_expert_out",
)


def remat_policy_kwargs(cfg: ModelConfig):
    """→ kwargs for jax.checkpoint per cfg.remat_policy."""
    if cfg.remat_policy == "dots":
        return {
            "policy": jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        }
    if cfg.remat_policy == "moe":
        # "dots" + the MoE layer's named intermediates. Without the names,
        # dots_with_no_batch_dims_saveable saves NONE of the MoE machinery
        # (expert einsums are batched over the expert dim; routing is not a
        # dot at all), so the whole routing chain and dispatch gathers run
        # twice per step. The named tensors are the d-sized bucketed
        # activations (~42 MB/layer at bench shapes) and the int/f32 plan
        # (~KBs) — the ff-sized expert intermediates stay unsaved.
        return {
            "policy": jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(
                    *MOE_SAVED_NAMES
                ),
            )
        }
    if cfg.remat_policy == "full":
        return {}
    raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")


def forward(params: dict, tokens: jax.Array, cfg: ModelConfig) -> jax.Array:
    """tokens (batch, seq) int32 → logits (batch, seq, vocab) float32."""
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = params["embed"][tokens]

    block = lambda x, layer: (_block(cfg, cos, sin, x, layer), None)
    if cfg.remat:
        # trade FLOPs for HBM across layers
        block = jax.checkpoint(block, **remat_policy_kwargs(cfg))
    x, _ = jax.lax.scan(block, x, params["layers"])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def loss_fn(params: dict, tokens: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Next-token cross-entropy over (batch, seq) tokens."""
    logits = forward(params, tokens[:, :-1], cfg)
    return next_token_nll(logits, tokens[:, 1:])
