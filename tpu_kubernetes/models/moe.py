"""Mixtral-style sparse Mixture-of-Experts decoder — the expert-parallel
member of the in-tree model family.

The attention path is shared with the dense model
(models/llama.py:attention_sublayer); the SwiGLU MLP is replaced by a
GShard-style top-k routed expert layer. TPU-first design:

* **Static shapes everywhere**: routing is expert-capacity based, so every
  array shape is a function of (batch, seq, n_experts, capacity) only.
* **Indexed dispatch, not dense one-hot**: the default ``gather`` path
  moves tokens to expert buckets with batched row gathers driven by int32
  slot indices — O(b·(E·C + k·s)·d) bytes of HBM traffic — instead of the
  GShard dense dispatch/combine einsums whose (b, s, E, C) one-hot
  operands cost O(b·s·E·C·d) MXU flops that scale with *total* experts.
  Custom VJPs express the backward pass as the complementary gathers, so
  neither direction ever materializes a (b, s, E, C) tensor or scatters
  activation rows. The dense-einsum path survives as
  ``dispatch_mode="einsum"`` and is the numerical oracle in tests.
* **Expert parallelism**: expert weights carry an ``expert`` logical axis
  which parallel/mesh.py maps to the ``expert`` mesh axis. Token
  activations are sharded over the data-like axes (which include
  ``expert`` — GShard's trick of reusing the expert axis for data
  parallelism in the non-MoE path). On the ``einsum`` path XLA inserts the
  all-to-all on the dispatch/combine einsums; on the default ``gather``
  path the row gathers are batch-local (operand, indices and output all
  shard over the token batch) and the cross-device exchange happens where
  the bucketed activations meet the expert-sharded weights in the expert
  matmuls — either way the collective rides ICI.
* ``lax.scan`` over stacked layer params, exactly like the dense model:
  expert weights are stacked (n_layers, n_experts, ...) so one block is
  traced/compiled once.
* Load-balancing auxiliary loss (Switch-style f·P) computed in float32 and
  added by :func:`loss_fn` with coefficient ``router_aux_coef``.

The reference provisioner has no model code at all; this is part of the
framework's in-tree example-job stack (SURVEY.md §2.7 — DP/…/EP are
"delivered via the in-tree example"), giving the ``expert`` mesh axis a
real consumer.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from tpu_kubernetes.models.llama import (
    ModelConfig,
    _dense_init,
    attention_sublayer,
    remat_policy_kwargs,
)
from tpu_kubernetes.ops import next_token_nll, rms_norm, rope_frequencies
from tpu_kubernetes.ops.grouped_matmul import (
    DEFAULT_BLOCK_M,
    _int_zeros,  # symbolic-zero integer cotangent — shared with the kernel VJPs
    grouped_matmul,
)


@dataclass(frozen=True)
class MoEConfig(ModelConfig):
    n_experts: int = 8
    experts_per_token: int = 2
    # per-expert token capacity = ceil(k · seq · capacity_factor / n_experts)
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # "gather": indexed dispatch/combine (row gathers, custom-VJP backward)
    # "einsum": GShard dense one-hot dispatch (oracle; O(b·s·E·C·d) flops)
    # "grouped": DROPLESS — sort token rows by expert and run the Pallas
    #   grouped matmul (ops/grouped_matmul.py); no capacity, no drops
    #   (capacity_factor is ignored). On an expert-parallel mesh the layer
    #   shard_maps itself over the expert axis with an explicit all-to-all
    #   token exchange (models/moe_ep.py) — the train step activates this
    #   automatically; manual jits need moe_ep.expert_parallel_context.
    dispatch_mode: str = "gather"
    # MoE-aware remat: save the routing plan + bucketed activations so the
    # backward never re-runs the routing machinery (llama.py:
    # remat_policy_kwargs "moe" — "dots" alone saves none of it)
    remat_policy: str = "moe"


MOE_CONFIGS: dict[str, MoEConfig] = {
    "moe-test": MoEConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=128, remat=False, n_experts=4, experts_per_token=2,
    ),
    # the grouped twin of moe-test: exercises the dropless grouped path
    # (and, inside an expert-parallel context, the all-to-all EP path in
    # models/moe_ep.py) at test scale — the sharded-serving identity
    # suite decodes this over an expert=2 host mesh
    "moe-test-grouped": MoEConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=128, remat=False, n_experts=4, experts_per_token=2,
        dispatch_mode="grouped",
    ),
    "moe-1b": MoEConfig(
        vocab_size=32000, d_model=1024, n_layers=12, n_heads=16, n_kv_heads=8,
        d_ff=3584, max_seq=2048, n_experts=8, experts_per_token=2,
        # cf 1.0 sizes capacity at the MEAN expert load: zero aggregate
        # padding (cf 1.25 pads 20% of slots; measured 325→291 ms/step on
        # v5e at bench shapes) at the cost of dropping the overflow when
        # routing is imbalanced — a few % of tokens at equilibrium, more
        # early in training until the aux loss balances the router. Both
        # points are standard (Switch ships 1.0–1.25; GShard top-2 used
        # 2.0); this in-tree example trades toward throughput. Raise
        # capacity_factor for quality-critical runs; mixtral-8x7b keeps
        # the conservative default.
        capacity_factor=1.0,
    ),
    "mixtral-8x7b": MoEConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq=4096, rope_theta=1e6, n_experts=8,
        experts_per_token=2,
    ),
}


def expert_capacity(cfg: MoEConfig, seq: int) -> int:
    return max(
        1,
        math.ceil(
            cfg.experts_per_token * seq * cfg.capacity_factor / cfg.n_experts
        ),
    )


# -- params -----------------------------------------------------------------

def init_params(rng: jax.Array, cfg: MoEConfig) -> dict:
    """Parameter pytree; layer params stacked on a leading axis for
    lax.scan, expert weights additionally stacked on an expert axis."""
    keys = jax.random.split(rng, 10)
    d, h, kv, hd, ff, L, E = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
        cfg.d_ff, cfg.n_layers, cfg.n_experts,
    )

    def stack_init(key, shape, fan_in):
        ks = jax.random.split(key, L)
        return jnp.stack([_dense_init(k, shape, cfg.dtype, fan_in) for k in ks])

    return {
        "embed": _dense_init(keys[0], (cfg.vocab_size, d), cfg.dtype, 1.0),
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "wq": stack_init(keys[1], (d, h * hd), d),
            "wk": stack_init(keys[2], (d, kv * hd), d),
            "wv": stack_init(keys[3], (d, kv * hd), d),
            "wo": stack_init(keys[4], (h * hd, d), h * hd),
            "mlp_norm": jnp.ones((L, d), cfg.dtype),
            # router in float32 — routing decisions are precision-sensitive
            "w_router": jnp.stack([
                (jax.random.normal(k, (d, E), jnp.float32) / jnp.sqrt(d))
                for k in jax.random.split(keys[5], L)
            ]),
            "w_gate": stack_init(keys[6], (E, d, ff), d),
            "w_up": stack_init(keys[7], (E, d, ff), d),
            "w_down": stack_init(keys[8], (E, ff, d), ff),
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": _dense_init(keys[9], (d, cfg.vocab_size), cfg.dtype, d),
    }


def logical_axes(cfg: MoEConfig) -> dict:
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layer", "embed"),
            "wq": ("layer", "embed", "heads"),
            "wk": ("layer", "embed", "kv"),
            "wv": ("layer", "embed", "kv"),
            "wo": ("layer", "heads", "embed"),
            "mlp_norm": ("layer", "embed"),
            "w_router": ("layer", "embed", None),
            "w_gate": ("layer", "expert", "embed", "mlp"),
            "w_up": ("layer", "expert", "embed", "mlp"),
            "w_down": ("layer", "expert", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


# -- routed expert layer ----------------------------------------------------

def _topk_selection(gates: jax.Array, k: int):
    """k argmax rounds over (b, s, E) gates → (idxs, masks), each a list of
    k arrays ((b, s) int32 / (b, s, E) f32 one-hot). Shared by both
    dispatch paths so their expert selection can never diverge."""
    remaining = gates
    idxs, masks = [], []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, gates.shape[-1], dtype=jnp.float32)
        idxs.append(idx.astype(jnp.int32))
        masks.append(mask)
        # chosen experts drop to -1 (not 0): even if every unchosen gate
        # underflowed to exactly 0.0, argmax can never re-pick an expert,
        # preserving the distinct-experts invariant slot assignment relies on
        remaining = jnp.where(mask > 0, -1.0, remaining)
    return idxs, masks


def _route(gates: jax.Array, k: int, capacity: int):
    """Top-k expert-capacity routing. gates: (b, s, E) float32 softmax
    probabilities → (dispatch, combine) both (b, s, E, C), plus the
    first-choice mask (b, s, E) for the load-balance loss.

    Two passes, k a small static int. Pass 1 picks the k choice masks
    (argmax, mask out, repeat) — these depend only on each token's own
    gates, and a token never claims the same expert twice. Pass 2 assigns
    capacity slots with a single exclusive cumsum over the sequence, so
    slot assignment — and therefore overflow dropping — is **causal**:
    whether a token is kept depends only on tokens before it, never on
    later ones (plain GShard offsets round-2 slots by whole-batch round-1
    counts and silently leaks future positions into the drop pattern).
    Dropped tokens pass through on the residual; combine weights are
    renormalized over the *selected* experts (Mixtral semantics)."""
    b, s, E = gates.shape
    _, masks = _topk_selection(gates, k)
    first_mask = masks[0]

    # a token's slot in expert e = number of claims on e by strictly
    # earlier tokens (any round; rounds of one token hit distinct experts)
    total = sum(masks)
    pos = jnp.cumsum(total, axis=1) - total               # exclusive cumsum

    dispatch = jnp.zeros((b, s, E, capacity), jnp.float32)
    combine = jnp.zeros((b, s, E, capacity), jnp.float32)
    selected_sum = jnp.zeros((b, s), jnp.float32)
    for mask in masks:
        keep = mask * (pos < capacity)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        sel = keep[..., None] * slot                      # (b, s, E, C)
        gate_i = jnp.sum(gates * mask, axis=-1)           # (b, s)
        dispatch = dispatch + sel
        combine = combine + sel * gate_i[..., None, None]
        selected_sum = selected_sum + gate_i

    combine = combine / jnp.maximum(selected_sum, 1e-9)[..., None, None]
    return dispatch, combine, first_mask


def _topk_plan(gates: jax.Array, k: int):
    """Shared selection + combine-weight computation for the indexed
    dispatch paths (gather and grouped — one place, so the modes can
    never diverge in the gate weighting).

    Returns (expert_idx (k, b, s) int32, masks [k × (b, s, E) one-hot],
    weight (k, b, s) f32). ``weight`` is renormalized over ALL selected
    experts (Mixtral semantics, matching :func:`_route`: on capacity
    paths, dropped selections still count in the denominator); it is the
    router's gradient path."""
    idxs, masks = _topk_selection(gates, k)
    expert_idx = jnp.stack(idxs)                      # (k, b, s)
    gate_r = jnp.stack([
        jnp.sum(gates * m, axis=-1) for m in masks
    ])                                                # (k, b, s) f32
    weight = gate_r / jnp.maximum(jnp.sum(gate_r, axis=0), 1e-9)
    return expert_idx, masks, weight


def _grouped_sort_plan(gates: jax.Array, k: int, E: int):
    """Stable expert-sort plan shared by the single-shard grouped branch
    and the expert-parallel path (models/moe_ep.py) — one implementation,
    so the two dropless paths can never diverge. (token, choice) row
    t·k + r is token t's round-r choice; the stable sort preserves token
    order within each expert.

    Returns (perm, sizes, token_of, inv, weight, first): the sort
    permutation over the b·s·k rows, per-expert row counts (unpadded —
    call sites add their own alignment padding), each sorted row's source
    token, the inverse permutation, the combine weights (k, b, s), and
    the first-choice one-hot for the balance loss."""
    expert_idx, masks, weight = _topk_plan(gates, k)
    _, b, s = expert_idx.shape
    m = b * s * k
    e_flat = expert_idx.transpose(1, 2, 0).reshape(m)
    perm = jnp.argsort(e_flat, stable=True)
    sizes = jnp.bincount(e_flat, length=E).astype(jnp.int32)
    token_of = perm // k
    inv = (
        jnp.zeros((m,), jnp.int32)
        .at[perm]
        .set(jnp.arange(m, dtype=jnp.int32), unique_indices=True)
    )
    return perm, sizes, token_of, inv, weight, masks[0]


def _route_plan(gates: jax.Array, k: int, capacity: int):
    """Indexed form of :func:`_route`: the same k argmax rounds and causal
    slot cumsum, but returned as per-round index/weight arrays instead of
    dense (b, s, E, C) one-hots.

    Returns (dst, keep, weight, first):
      dst    (k, b, s) int32 — flat slot e·C + pos each round targets
      keep   (k, b, s) bool  — slot within capacity (token not dropped)
      weight (k, b, s) f32   — combine weight (see :func:`_topk_plan`)
      first  (b, s, E) f32   — first-choice one-hot for the balance loss
    """
    expert_idx, masks, weight = _topk_plan(gates, k)
    first = masks[0]

    total = sum(masks)
    pos_all = jnp.cumsum(total, axis=1) - total       # (b, s, E) exclusive

    pos = jnp.stack([
        jnp.sum(pos_all * m, axis=-1).astype(jnp.int32) for m in masks
    ])
    keep = pos < capacity
    dst = expert_idx * capacity + pos
    return dst, keep, weight, first


def _slot_sources(dst, keep, n_slots: int):
    """Invert the token→slot map: src[b, j] = sequence index of the token
    claiming flat slot j, valid[b, j] = whether j is claimed. The only
    scatter in the layer — int32 claims, O(b·k·s) values (no feature dim).
    Routing guarantees claimed slots are unique, dropped claims go out of
    bounds and are dropped by XLA."""
    k, b, s = dst.shape
    tok = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (k, b, s))
    # dropped claims go out of bounds (mode="drop" discards them) at
    # DISTINCT indices — unique_indices is a compiler promise that must
    # hold for the dropped writes too
    oob = b * n_slots + jnp.arange(k * b * s, dtype=jnp.int32).reshape(k, b, s)
    flat = jnp.where(
        keep,
        dst + (jnp.arange(b, dtype=jnp.int32) * n_slots)[None, :, None],
        oob,
    )
    claims = (
        jnp.zeros((b * n_slots,), jnp.int32)
        .at[flat.reshape(-1)]
        .set(tok.reshape(-1) + 1, mode="drop", unique_indices=True)
        .reshape(b, n_slots)
    )
    return jnp.maximum(claims - 1, 0), claims > 0


def _take_rows(table, idx):
    """Batched row gather: table (b, n, d), idx (b, m) → (b, m, d)."""
    return jnp.take_along_axis(table, idx[..., None], axis=1)




@jax.custom_vjp
def _dispatch_rows(y, src, valid, dst, keep):
    """Token rows → expert slots: (b, s, d) → (b, E·C, d), unclaimed slots
    zero. Backward is the complementary gather (each token reads the ≤k
    slot cotangents it fed), so neither direction scatters feature rows."""
    return _take_rows(y, src) * valid[..., None].astype(y.dtype)


def _dispatch_rows_fwd(y, src, valid, dst, keep):
    return _dispatch_rows(y, src, valid, dst, keep), (src, valid, dst, keep)


def _dispatch_rows_bwd(res, dxe):
    src, valid, dst, keep = res
    n = dxe.shape[1]
    dy = None
    for r in range(dst.shape[0]):
        rows = _take_rows(dxe, jnp.minimum(dst[r], n - 1))
        rows = rows * keep[r][..., None].astype(dxe.dtype)
        dy = rows if dy is None else dy + rows
    return dy, _int_zeros(src), _int_zeros(valid), _int_zeros(dst), _int_zeros(keep)


_dispatch_rows.defvjp(_dispatch_rows_fwd, _dispatch_rows_bwd)


@jax.custom_vjp
def _combine_rows(out_e, weight, dst, keep, src, valid):
    """Expert-slot rows → tokens: out[b, s] = Σ_r weight_r · out_e[b, dst_r]
    over the token's kept slots. Differentiable in ``weight`` (the router's
    gradient path) and ``out_e``; backward is again gathers only."""
    n = out_e.shape[1]
    acc = None
    for r in range(dst.shape[0]):
        rows = _take_rows(out_e, jnp.minimum(dst[r], n - 1))
        w = weight[r] * keep[r]
        rows = rows * w[..., None].astype(out_e.dtype)
        acc = rows if acc is None else acc + rows
    return acc


def _combine_rows_fwd(out_e, weight, dst, keep, src, valid):
    out = _combine_rows(out_e, weight, dst, keep, src, valid)
    return out, (out_e, weight, dst, keep, src, valid)


def _combine_rows_bwd(res, dout):
    out_e, weight, dst, keep, src, valid = res
    k, _, _ = weight.shape
    n = out_e.shape[1]

    # d·out_e[b, j] = w_slot[b, j] · dout[b, src[b, j]]: recover each slot's
    # combine weight by checking which round of its source token claimed it
    w_slot = None
    for r in range(k):
        w_src = jnp.take_along_axis(weight[r] * keep[r], src, axis=1)
        d_src = jnp.take_along_axis(dst[r], src, axis=1)
        hit = (d_src == jnp.arange(n, dtype=jnp.int32)[None, :])
        term = jnp.where(hit, w_src, 0.0)
        w_slot = term if w_slot is None else w_slot + term
    w_slot = w_slot * valid
    d_out_e = _take_rows(dout, src) * w_slot[..., None].astype(dout.dtype)

    # d·weight[r, b, s] = keep · ⟨dout[b, s], out_e[b, dst_r]⟩
    dws = []
    for r in range(k):
        rows = _take_rows(out_e, jnp.minimum(dst[r], n - 1))
        dw = jnp.sum(rows.astype(jnp.float32) * dout.astype(jnp.float32), -1)
        dws.append(dw * keep[r])
    dweight = jnp.stack(dws)

    return (d_out_e.astype(out_e.dtype), dweight, _int_zeros(dst),
            _int_zeros(keep), _int_zeros(src), _int_zeros(valid))


_combine_rows.defvjp(_combine_rows_fwd, _combine_rows_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _dispatch_sorted(y2, token_of, inv, k):
    """Token rows → expert-sorted rows for the grouped path:
    (b·s, d) → (b·s·k, d), row i = y2[token_of[i]]. The default VJP would
    scatter-add b·s·k feature rows back into b·s; the custom backward is
    the complementary gather instead — each token collects its k sorted
    cotangent rows through ``inv`` and sums them (the same no-row-scatter
    guarantee the gather path's _dispatch_rows makes)."""
    return jnp.take(y2, token_of, axis=0)


def _dispatch_sorted_fwd(y2, token_of, inv, k):
    return jnp.take(y2, token_of, axis=0), (token_of, inv)


def _dispatch_sorted_bwd(k, res, dout):
    token_of, inv = res
    d = dout.shape[-1]
    dy2 = jnp.sum(jnp.take(dout, inv, axis=0).reshape(-1, k, d), axis=1)
    return dy2.astype(dout.dtype), _int_zeros(token_of), _int_zeros(inv)


_dispatch_sorted.defvjp(_dispatch_sorted_fwd, _dispatch_sorted_bwd)


@jax.custom_vjp
def _unsort_rows(rows, inv, perm):
    """Expert-sorted rows → (token, choice) order: row i = rows[inv[i]].
    ``inv``/``perm`` are inverse permutations of each other, so both
    directions — and both cotangents — are pure gathers."""
    return jnp.take(rows, inv, axis=0)


def _unsort_rows_fwd(rows, inv, perm):
    return jnp.take(rows, inv, axis=0), (inv, perm)


def _unsort_rows_bwd(res, dout):
    inv, perm = res
    return (
        jnp.take(dout, perm, axis=0),
        _int_zeros(inv),
        _int_zeros(perm),
    )


_unsort_rows.defvjp(_unsort_rows_fwd, _unsort_rows_bwd)


def _expert_mlp(cfg: MoEConfig, xe, layer):
    """The experts' SwiGLU over bucketed tokens xe (b, E, C, d)."""
    gated = jax.nn.silu(
        jnp.einsum("becd,edf->becf", xe, layer["w_gate"])
    ) * jnp.einsum("becd,edf->becf", xe, layer["w_up"])
    return jnp.einsum("becf,efd->becd", gated, layer["w_down"])


def moe_sublayer(cfg: MoEConfig, x, layer):
    """Pre-norm routed-expert MLP + residual. x: (b, s, d) → (out, aux)."""
    b, s, d = x.shape
    C = expert_capacity(cfg, s)
    y = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)

    logits = jnp.einsum(
        "bsd,de->bse", y.astype(jnp.float32), layer["w_router"]
    )
    gates = jax.nn.softmax(logits, axis=-1)

    if cfg.dispatch_mode == "einsum":
        dispatch, combine, first = _route(gates, cfg.experts_per_token, C)
        # dense one-hot dispatch → per-expert token buckets; three einsums
        # on the MXU but O(b·s·E·C·d) flops of pure data movement
        xe = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(cfg.dtype), y)
        gated = jax.nn.silu(
            jnp.einsum("ebcd,edf->ebcf", xe, layer["w_gate"])
        ) * jnp.einsum("ebcd,edf->ebcf", xe, layer["w_up"])
        out_e = jnp.einsum("ebcf,efd->ebcd", gated, layer["w_down"])
        out = jnp.einsum("ebcd,bsec->bsd", out_e, combine.astype(cfg.dtype))
    elif cfg.dispatch_mode == "gather":
        from jax.ad_checkpoint import checkpoint_name

        dst, keep, weight, first = _route_plan(gates, cfg.experts_per_token, C)
        src, valid = _slot_sources(dst, keep, cfg.n_experts * C)
        # name the plan + bucketed activations so the "moe" remat policy
        # (llama.py:remat_policy_kwargs) saves them: the custom-VJP
        # backwards below consume exactly these residuals, so the whole
        # routing chain (argmax rounds, cumsums, the slot scatter) and the
        # dispatch gather never re-run during the backward pass
        dst = checkpoint_name(dst, "moe_plan")
        keep = checkpoint_name(keep, "moe_plan")
        weight = checkpoint_name(weight, "moe_plan")
        src = checkpoint_name(src, "moe_plan")
        valid = checkpoint_name(valid, "moe_plan")
        xe = checkpoint_name(
            _dispatch_rows(y, src, valid, dst, keep), "moe_dispatch"
        )
        out_e = checkpoint_name(
            _expert_mlp(cfg, xe.reshape(b, cfg.n_experts, C, d), layer),
            "moe_expert_out",
        )
        out = _combine_rows(
            out_e.reshape(b, cfg.n_experts * C, d), weight, dst, keep, src, valid
        )
    elif cfg.dispatch_mode == "grouped":
        from jax.ad_checkpoint import checkpoint_name

        from tpu_kubernetes.models.moe_ep import (
            active_expert_mesh,
            grouped_ep_mlp,
        )

        ep_mesh = active_expert_mesh()
        if ep_mesh is not None:
            # expert-parallel dropless: shard_map over the expert axis
            # with an explicit all-to-all token exchange (moe_ep.py) —
            # the opaque Pallas kernel runs per expert slab. The balance
            # loss uses the GLOBAL gates via the shared tail below.
            out = grouped_ep_mlp(cfg, y, gates, layer, ep_mesh)
            # the same round-0 selection the local plans use, so the aux
            # regularization can never depend on the mesh shape
            first = _topk_selection(gates, 1)[1][0]
        else:
            k, E = cfg.experts_per_token, cfg.n_experts
            m_rows = b * s * k
            m_pad = -(-m_rows // DEFAULT_BLOCK_M) * DEFAULT_BLOCK_M
            perm, sizes, token_of, inv, weight, first = _grouped_sort_plan(
                gates, k, E
            )
            # alignment pad rows ride in the last group; their lhs rows
            # are zero, so their outputs are zero and nothing gathers
            # them back
            sizes = sizes.at[E - 1].add(m_pad - m_rows)
            perm = checkpoint_name(perm, "moe_plan")
            sizes = checkpoint_name(sizes, "moe_plan")
            token_of = checkpoint_name(token_of, "moe_plan")
            inv = checkpoint_name(inv, "moe_plan")
            weight = checkpoint_name(weight, "moe_plan")

            y2 = y.reshape(b * s, d)
            lhs = jnp.pad(
                _dispatch_sorted(y2, token_of, inv, k),
                ((0, m_pad - m_rows), (0, 0)),
            )
            lhs = checkpoint_name(lhs, "moe_dispatch")
            gmm = functools.partial(grouped_matmul, use_pallas=cfg.use_pallas)
            gated = jax.nn.silu(gmm(lhs, layer["w_gate"], sizes)) * gmm(
                lhs, layer["w_up"], sizes
            )
            rows_out = checkpoint_name(
                gmm(gated, layer["w_down"], sizes), "moe_expert_out"
            )
            rows_tok = _unsort_rows(rows_out[:m_rows], inv, perm)
            w_tok = weight.transpose(1, 2, 0).reshape(b, s, k)
            out = jnp.sum(
                rows_tok.reshape(b, s, k, d)
                * w_tok[..., None].astype(rows_tok.dtype),
                axis=2,
            )
    else:
        raise ValueError(f"unknown dispatch_mode {cfg.dispatch_mode!r}")

    # Switch-style load-balance loss: n_experts · Σ_e f_e · P_e, where f_e
    # is the fraction of tokens whose FIRST choice is e, P_e the mean
    # router probability of e
    f = jnp.mean(first, axis=(0, 1))
    p = jnp.mean(gates, axis=(0, 1))
    aux = cfg.n_experts * jnp.sum(f * p)
    return x + out, aux


# -- forward ----------------------------------------------------------------

def _block(cfg: MoEConfig, cos, sin, x, layer):
    x = attention_sublayer(cfg, cos, sin, x, layer)
    return moe_sublayer(cfg, x, layer)


def forward_with_aux(
    params: dict, tokens: jax.Array, cfg: MoEConfig
) -> tuple[jax.Array, jax.Array]:
    """tokens (b, s) int32 → (logits (b, s, vocab) f32, mean aux loss)."""
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = params["embed"][tokens]

    def block(x, layer):
        x, aux = _block(cfg, cos, sin, x, layer)
        return x, aux

    if cfg.remat:
        block = jax.checkpoint(block, **remat_policy_kwargs(cfg))
    x, aux = jax.lax.scan(block, x, params["layers"])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32), jnp.mean(aux)


def forward(params: dict, tokens: jax.Array, cfg: MoEConfig) -> jax.Array:
    return forward_with_aux(params, tokens, cfg)[0]


def loss_fn(params: dict, tokens: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Next-token cross-entropy + router load-balance auxiliary loss."""
    logits, aux = forward_with_aux(params, tokens[:, :-1], cfg)
    return next_token_nll(logits, tokens[:, 1:]) + cfg.router_aux_coef * aux
