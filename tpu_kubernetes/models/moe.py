"""Mixtral-style sparse Mixture-of-Experts decoder — the expert-parallel
member of the in-tree model family.

The attention path is shared with the dense model
(models/llama.py:attention_sublayer); the SwiGLU MLP is replaced by a
GShard-style top-k routed expert layer. TPU-first design:

* **Static shapes everywhere**: routing uses expert-capacity
  dispatch/combine one-hot tensors (no gather/scatter, no dynamic shapes),
  so the whole layer is three einsums XLA maps straight onto the MXU.
* **Expert parallelism**: expert weights carry an ``expert`` logical axis
  which parallel/mesh.py maps to the ``expert`` mesh axis. Token
  activations are sharded over the data-like axes (which include
  ``expert`` — GShard's trick of reusing the expert axis for data
  parallelism in the non-MoE path), so XLA inserts the all-to-all on the
  dispatch/combine einsums and it rides ICI.
* ``lax.scan`` over stacked layer params, exactly like the dense model:
  expert weights are stacked (n_layers, n_experts, ...) so one block is
  traced/compiled once.
* Load-balancing auxiliary loss (Switch-style f·P) computed in float32 and
  added by :func:`loss_fn` with coefficient ``router_aux_coef``.

The reference provisioner has no model code at all; this is part of the
framework's in-tree example-job stack (SURVEY.md §2.7 — DP/…/EP are
"delivered via the in-tree example"), giving the ``expert`` mesh axis a
real consumer.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from tpu_kubernetes.models.llama import (
    ModelConfig,
    _dense_init,
    attention_sublayer,
    remat_policy_kwargs,
)
from tpu_kubernetes.ops import next_token_nll, rms_norm, rope_frequencies


@dataclass(frozen=True)
class MoEConfig(ModelConfig):
    n_experts: int = 8
    experts_per_token: int = 2
    # per-expert token capacity = ceil(k · seq · capacity_factor / n_experts)
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01


MOE_CONFIGS: dict[str, MoEConfig] = {
    "moe-test": MoEConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=128, remat=False, n_experts=4, experts_per_token=2,
    ),
    "moe-1b": MoEConfig(
        vocab_size=32000, d_model=1024, n_layers=12, n_heads=16, n_kv_heads=8,
        d_ff=3584, max_seq=2048, n_experts=8, experts_per_token=2,
    ),
    "mixtral-8x7b": MoEConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq=4096, rope_theta=1e6, n_experts=8,
        experts_per_token=2,
    ),
}


def expert_capacity(cfg: MoEConfig, seq: int) -> int:
    return max(
        1,
        math.ceil(
            cfg.experts_per_token * seq * cfg.capacity_factor / cfg.n_experts
        ),
    )


# -- params -----------------------------------------------------------------

def init_params(rng: jax.Array, cfg: MoEConfig) -> dict:
    """Parameter pytree; layer params stacked on a leading axis for
    lax.scan, expert weights additionally stacked on an expert axis."""
    keys = jax.random.split(rng, 10)
    d, h, kv, hd, ff, L, E = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
        cfg.d_ff, cfg.n_layers, cfg.n_experts,
    )

    def stack_init(key, shape, fan_in):
        ks = jax.random.split(key, L)
        return jnp.stack([_dense_init(k, shape, cfg.dtype, fan_in) for k in ks])

    return {
        "embed": _dense_init(keys[0], (cfg.vocab_size, d), cfg.dtype, 1.0),
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "wq": stack_init(keys[1], (d, h * hd), d),
            "wk": stack_init(keys[2], (d, kv * hd), d),
            "wv": stack_init(keys[3], (d, kv * hd), d),
            "wo": stack_init(keys[4], (h * hd, d), h * hd),
            "mlp_norm": jnp.ones((L, d), cfg.dtype),
            # router in float32 — routing decisions are precision-sensitive
            "w_router": jnp.stack([
                (jax.random.normal(k, (d, E), jnp.float32) / jnp.sqrt(d))
                for k in jax.random.split(keys[5], L)
            ]),
            "w_gate": stack_init(keys[6], (E, d, ff), d),
            "w_up": stack_init(keys[7], (E, d, ff), d),
            "w_down": stack_init(keys[8], (E, ff, d), ff),
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": _dense_init(keys[9], (d, cfg.vocab_size), cfg.dtype, d),
    }


def logical_axes(cfg: MoEConfig) -> dict:
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layer", "embed"),
            "wq": ("layer", "embed", "heads"),
            "wk": ("layer", "embed", "kv"),
            "wv": ("layer", "embed", "kv"),
            "wo": ("layer", "heads", "embed"),
            "mlp_norm": ("layer", "embed"),
            "w_router": ("layer", "embed", None),
            "w_gate": ("layer", "expert", "embed", "mlp"),
            "w_up": ("layer", "expert", "embed", "mlp"),
            "w_down": ("layer", "expert", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


# -- routed expert layer ----------------------------------------------------

def _route(gates: jax.Array, k: int, capacity: int):
    """Top-k expert-capacity routing. gates: (b, s, E) float32 softmax
    probabilities → (dispatch, combine) both (b, s, E, C), plus the
    first-choice mask (b, s, E) for the load-balance loss.

    Two passes, k a small static int. Pass 1 picks the k choice masks
    (argmax, mask out, repeat) — these depend only on each token's own
    gates, and a token never claims the same expert twice. Pass 2 assigns
    capacity slots with a single exclusive cumsum over the sequence, so
    slot assignment — and therefore overflow dropping — is **causal**:
    whether a token is kept depends only on tokens before it, never on
    later ones (plain GShard offsets round-2 slots by whole-batch round-1
    counts and silently leaks future positions into the drop pattern).
    Dropped tokens pass through on the residual; combine weights are
    renormalized over the *selected* experts (Mixtral semantics)."""
    b, s, E = gates.shape
    remaining = gates
    masks = []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)              # (b, s)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # (b, s, E)
        masks.append(mask)
        # chosen experts drop to -1 (not 0): even if every unchosen gate
        # underflowed to exactly 0.0, argmax can never re-pick an expert,
        # preserving the distinct-experts invariant pass 2 relies on
        remaining = jnp.where(mask > 0, -1.0, remaining)
    first_mask = masks[0]

    # a token's slot in expert e = number of claims on e by strictly
    # earlier tokens (any round; rounds of one token hit distinct experts)
    total = sum(masks)
    pos = jnp.cumsum(total, axis=1) - total               # exclusive cumsum

    dispatch = jnp.zeros((b, s, E, capacity), jnp.float32)
    combine = jnp.zeros((b, s, E, capacity), jnp.float32)
    selected_sum = jnp.zeros((b, s), jnp.float32)
    for mask in masks:
        keep = mask * (pos < capacity)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        sel = keep[..., None] * slot                      # (b, s, E, C)
        gate_i = jnp.sum(gates * mask, axis=-1)           # (b, s)
        dispatch = dispatch + sel
        combine = combine + sel * gate_i[..., None, None]
        selected_sum = selected_sum + gate_i

    combine = combine / jnp.maximum(selected_sum, 1e-9)[..., None, None]
    return dispatch, combine, first_mask


def moe_sublayer(cfg: MoEConfig, x, layer):
    """Pre-norm routed-expert MLP + residual. x: (b, s, d) → (out, aux)."""
    b, s, d = x.shape
    C = expert_capacity(cfg, s)
    y = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)

    logits = jnp.einsum(
        "bsd,de->bse", y.astype(jnp.float32), layer["w_router"]
    )
    gates = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, first = _route(gates, cfg.experts_per_token, C)

    # dispatch → per-expert token buckets (all-to-all over the expert axis
    # when sharded); compute in model dtype on the MXU
    xe = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(cfg.dtype), y)
    gated = jax.nn.silu(
        jnp.einsum("ebcd,edf->ebcf", xe, layer["w_gate"])
    ) * jnp.einsum("ebcd,edf->ebcf", xe, layer["w_up"])
    out_e = jnp.einsum("ebcf,efd->ebcd", gated, layer["w_down"])
    out = jnp.einsum("ebcd,bsec->bsd", out_e, combine.astype(cfg.dtype))

    # Switch-style load-balance loss: n_experts · Σ_e f_e · P_e, where f_e
    # is the fraction of tokens whose FIRST choice is e, P_e the mean
    # router probability of e
    f = jnp.mean(first, axis=(0, 1))
    p = jnp.mean(gates, axis=(0, 1))
    aux = cfg.n_experts * jnp.sum(f * p)
    return x + out, aux


# -- forward ----------------------------------------------------------------

def _block(cfg: MoEConfig, cos, sin, x, layer):
    x = attention_sublayer(cfg, cos, sin, x, layer)
    return moe_sublayer(cfg, x, layer)


def forward_with_aux(
    params: dict, tokens: jax.Array, cfg: MoEConfig
) -> tuple[jax.Array, jax.Array]:
    """tokens (b, s) int32 → (logits (b, s, vocab) f32, mean aux loss)."""
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = params["embed"][tokens]

    def block(x, layer):
        x, aux = _block(cfg, cos, sin, x, layer)
        return x, aux

    if cfg.remat:
        block = jax.checkpoint(block, **remat_policy_kwargs(cfg))
    x, aux = jax.lax.scan(block, x, params["layers"])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32), jnp.mean(aux)


def forward(params: dict, tokens: jax.Array, cfg: MoEConfig) -> jax.Array:
    return forward_with_aux(params, tokens, cfg)[0]


def loss_fn(params: dict, tokens: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Next-token cross-entropy + router load-balance auxiliary loss."""
    logits, aux = forward_with_aux(params, tokens[:, :-1], cfg)
    return next_token_nll(logits, tokens[:, 1:]) + cfg.router_aux_coef * aux
