"""Weight-only int8 quantization for the serving path.

KV-cache decode is HBM-bound: every step streams every weight once, so the
per-token floor is ``param_bytes / bandwidth`` (bench.py roofline). Storing
the matmul weights as int8 + per-output-channel f32 scales halves the
bytes streamed — the dequantize (convert + scale multiply) happens in-core
where XLA fuses it into the dot's operand load, so HBM sees only the int8
payload. On v5e (819 GB/s) that moves the llama-1b floor from ~2.2 ms to
~1.1 ms per step.

Scope and choices, TPU-first:

* **Symmetric per-output-channel scales** (absmax / 127 over the input
  dim): one f32 scale per output column keeps the dequant a cheap
  broadcast multiply on the dot's output dimension, and symmetric
  quantization needs no zero-points (no extra add in the hot loop).
* **Matmul weights only** — qkvo, the SwiGLU mlp trio, lm_head. The
  embedding stays bf16 (a lookup reads only ``batch`` rows per step —
  no bandwidth to win, and embeddings are quantization-sensitive);
  norm gains and the MoE router stay in their original dtypes.
* **Same pytree shape**: a quantized leaf becomes ``{"q": int8,
  "s": f32}``, everything else passes through — so the serving entry
  points (models/decode.py) accept raw or quantized params through one
  ``weight()`` accessor and nothing else changes.
* Training is untouched: quantization is an export step
  (``quantize_for_decode``), matching how serving stacks deploy
  (train bf16 → quantize once → serve int8).

The reference provisioner has no inference plane (SURVEY §0); this extends
the in-tree serving stack the rebuild added alongside it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_kubernetes.models.llama import ModelConfig

# leaves under params["layers"] that are plain (L, in, out) matmul weights
_LAYER_MATMUL_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _quantize_leaf(w: jax.Array) -> dict:
    """(…, in, out) float weight → {"q": int8, "s": f32 per-out-channel}.

    The input (contraction) dim is -2; scales are computed over it so each
    output channel dequantizes with one multiply."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "s"}


def weight(leaf, dtype=jnp.bfloat16) -> jax.Array:
    """Accessor the serving path reads every weight through: dequantizes
    an int8 leaf (XLA fuses the convert+multiply into the consuming dot's
    operand load — the bf16 tensor never round-trips HBM), passes a plain
    array straight through."""
    if is_quantized(leaf):
        return (leaf["q"].astype(dtype) * leaf["s"].astype(dtype))
    return leaf


def quantize_for_decode(params: dict, cfg: ModelConfig) -> dict:
    """Export-time quantization of a trained param pytree for serving.

    Returns a pytree with the same keys where the layer matmul stacks and
    lm_head are int8-quantized; embed/norms/router untouched. MoE params
    quantize the expert stacks the same way (the expert dim is a leading
    batch dim, so per-output-channel scales are per-expert too)."""
    del cfg  # both families share the leaf layout quantized here
    layers = dict(params["layers"])
    for name in _LAYER_MATMUL_LEAVES:
        if name in layers:
            layers[name] = _quantize_leaf(layers[name])
    out = dict(params)
    out["layers"] = layers
    out["lm_head"] = _quantize_leaf(params["lm_head"])
    return out


def quantized_param_bytes(params: dict) -> int:
    """Bytes the decode step actually streams per token for this pytree:
    int8 leaves count 1 byte + their scales, everything else its own
    itemsize. The bench decode roofline uses this instead of assuming a
    uniform bf16 parameter size."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total


def max_abs_error(w: jax.Array) -> float:
    """Worst-case absolute dequantization error for one weight tensor —
    bounded by scale/2 per channel; exposed for tests."""
    q = _quantize_leaf(w)
    back = weight(q, jnp.float32)
    return float(jnp.max(jnp.abs(back - w.astype(jnp.float32))))
