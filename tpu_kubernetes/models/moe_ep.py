"""Expert-parallel dropless MoE: the grouped (MegaBlocks-shaped) path
sharded over the ``expert`` mesh axis.

The Pallas grouped matmul (ops/grouped_matmul.py) is opaque to the pjit
partitioner, so ``dispatch_mode="grouped"`` cannot be auto-sharded the way
the einsum/gather paths are — XLA cannot see through the kernel to insert
an all-to-all. This module makes the expert dimension explicit instead:
:func:`grouped_ep_mlp` runs the expert MLP inside a **partial-manual
``jax.shard_map``** over ONLY the ``expert`` axis (every other mesh axis
stays automatic, so data/fsdp batch sharding composes as usual), with the
token exchange written out by hand:

1. each shard sorts its local (token, choice) rows by global expert
   (stable, so per-expert token order is preserved);
2. per-destination-shard row counts are exchanged with one tiny int32
   ``all_gather`` (the (ep, E) count matrix — everything else is derived
   from it);
3. rows travel to their expert's owner through a static-shaped
   ``lax.all_to_all`` over worst-case bins (bin capacity = all of a
   shard's rows; pad rows are zeros and fold into the kernel's tail
   group). ``lax.ragged_all_to_all`` would move only the valid rows —
   the planned upgrade once XLA:CPU supports it, since the hermetic test
   environment (and the driver's dry run) is a virtual CPU mesh; the
   exchange is isolated in this module precisely so that swap is local;
4. each shard re-sorts received rows by its LOCAL expert slab and runs
   ``grouped_matmul`` over them — dropless, no capacity, exactly the
   single-shard grouped path per slab;
5. the same exchange runs in reverse and each token combines its k rows
   with the router weights locally.

Every data movement is a gather with a custom-VJP complementary gather
(the no-row-scatter discipline models/moe.py established); the collective
transposes are the reverse collectives, which JAX derives automatically
for ``all_to_all``/``all_gather``.

Entry is trace-time: :func:`expert_parallel_context` records the mesh
(train/trainer.py wraps the loss in it automatically), and
models/moe.py's grouped branch delegates here whenever the context mesh
has a non-trivial ``expert`` axis. Single-shard meshes keep the plain
grouped path with zero overhead.

Reference: the provisioner (SURVEY §2.7) has no ML code; this completes
the EP column for the dropless path (VERDICT r04 Missing #2).
"""

from __future__ import annotations

import functools
from contextlib import contextmanager

import jax
import jax.numpy as jnp
from jax import lax

from tpu_kubernetes.ops.grouped_matmul import (
    DEFAULT_BLOCK_M,
    _int_zeros,
    grouped_matmul,
)

# -- trace-time context ------------------------------------------------------

_EP_MESH = None


@contextmanager
def expert_parallel_context(mesh):
    """Make ``mesh`` visible to MoE layers traced inside this context, so
    ``dispatch_mode="grouped"`` can shard_map itself over the mesh's
    ``expert`` axis. Applied around the loss by
    train/trainer.py:make_sharded_train_step — user code only needs this
    when jitting a MoE forward by hand over an expert-parallel mesh."""
    global _EP_MESH
    prev = _EP_MESH
    _EP_MESH = mesh
    try:
        yield
    finally:
        _EP_MESH = prev


def active_expert_mesh():
    """The context mesh, if it has a non-trivial expert axis, else None."""
    mesh = _EP_MESH
    if (
        mesh is not None
        and "expert" in mesh.axis_names
        and mesh.shape["expert"] > 1
    ):
        return mesh
    return None


# -- exchange gathers (custom VJP: both directions are gathers) --------------

@jax.custom_vjp
def _fill_bins(ys, idx, valid, dst_of, pos_of):
    """Sorted rows → per-destination bins: out[j, q] = ys[idx[j, q]] where
    valid, else 0. Backward is the complementary gather — every sorted row
    sits in exactly one bin slot (dst_of, pos_of)."""
    take = jnp.take(ys, jnp.minimum(idx, ys.shape[0] - 1), axis=0)
    return jnp.where(valid[..., None], take, jnp.zeros((), ys.dtype))


def _fill_bins_fwd(ys, idx, valid, dst_of, pos_of):
    return _fill_bins(ys, idx, valid, dst_of, pos_of), (
        idx, valid, dst_of, pos_of
    )


def _fill_bins_bwd(res, dout):
    idx, valid, dst_of, pos_of = res
    dys = dout[dst_of, pos_of]
    return (dys, _int_zeros(idx), _int_zeros(valid),
            _int_zeros(dst_of), _int_zeros(pos_of))


_fill_bins.defvjp(_fill_bins_fwd, _fill_bins_bwd)


@jax.custom_vjp
def _read_bins(bins, dst_of, pos_of, idx, valid):
    """Per-destination bins → sorted rows: the exact inverse of
    :func:`_fill_bins` (same metadata), used after the return exchange."""
    return bins[dst_of, pos_of]


def _read_bins_fwd(bins, dst_of, pos_of, idx, valid):
    return _read_bins(bins, dst_of, pos_of, idx, valid), (
        dst_of, pos_of, idx, valid
    )


def _read_bins_bwd(res, dout):
    dst_of, pos_of, idx, valid = res
    take = jnp.take(dout, jnp.minimum(idx, dout.shape[0] - 1), axis=0)
    dbins = jnp.where(valid[..., None], take, jnp.zeros((), dout.dtype))
    return (dbins, _int_zeros(dst_of), _int_zeros(pos_of),
            _int_zeros(idx), _int_zeros(valid))


_read_bins.defvjp(_read_bins_fwd, _read_bins_bwd)


# -- the sharded sublayer ----------------------------------------------------

def grouped_ep_mlp(cfg, y, gates, layer, mesh):
    """Dropless expert MLP over an expert-parallel mesh.

    y (b, s, d) tokens (batch sharded over the data-like axes), gates
    (b, s, E) float32 router probabilities, layer the scanned layer params
    (w_gate/w_up/w_down carry a leading global-expert axis sharded over
    ``expert``) → (b, s, d) combined expert output (no residual).

    Requires n_experts divisible by the expert-axis size. The batch is
    re-split over the expert axis inside (shard_map in_specs); other mesh
    axes stay automatic.
    """
    from tpu_kubernetes.models.moe import (
        _dispatch_sorted,
        _grouped_sort_plan,
        _unsort_rows,
    )
    from jax.ad_checkpoint import checkpoint_name
    from jax.sharding import PartitionSpec as P

    ep = mesh.shape["expert"]
    E = cfg.n_experts
    if E % ep:
        raise ValueError(
            f"n_experts {E} not divisible by expert mesh axis {ep}"
        )
    e_loc = E // ep
    k = cfg.experts_per_token

    def inner(y_l, wg_l, wu_l, wd_l, gates_l):
        p = lax.axis_index("expert")
        b_l, s, d = y_l.shape
        m_l = b_l * s * k

        # -- local routing plan: the SAME sort plan as the single-shard
        # grouped branch (moe.py:_grouped_sort_plan), applied to this
        # shard's local tokens
        perm, sizes, token_of, inv, weight, _ = _grouped_sort_plan(
            gates_l, k, E
        )

        # -- exchange metadata: ONE int32 all_gather of per-expert counts,
        # everything else derived locally --------------------------------
        send = sizes.reshape(ep, e_loc).sum(1)              # rows per dst
        dst_start = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(send)[:-1]]
        )
        counts = lax.all_gather(sizes, "expert")            # (ep, E)
        c_re = counts.reshape(ep, ep, e_loc)[:, p, :]       # (src, E_loc)
        recv = c_re.sum(1)                                  # rows per src

        q = jnp.arange(m_l, dtype=jnp.int32)
        # sorted row i lives in bin (dst_of[i], pos_of[i]); bin slot
        # (j, q) reads sorted row idx[j, q] while q < send[j]
        dst_of = jnp.clip(
            jnp.searchsorted(jnp.cumsum(send), q, side="right"), 0, ep - 1
        ).astype(jnp.int32)
        pos_of = q - dst_start[dst_of]
        idx = dst_start[:, None] + q[None, :]               # (ep, m_l)
        valid_bin = q[None, :] < send[:, None]

        perm = checkpoint_name(perm, "moe_plan")
        inv = checkpoint_name(inv, "moe_plan")
        token_of = checkpoint_name(token_of, "moe_plan")
        weight = checkpoint_name(weight, "moe_plan")

        # -- dispatch: token rows → expert-sorted → bins → all_to_all ----
        ys = _dispatch_sorted(y_l.reshape(b_l * s, d), token_of, inv, k)
        bins = _fill_bins(ys, idx, valid_bin, dst_of, pos_of)
        got = lax.all_to_all(bins, "expert", 0, 0, tiled=False)

        # -- local re-sort by my expert slab -----------------------------
        # chunk from src i: rows for my experts, sorted by expert, with
        # per-expert counts c_re[i]; invalid tail rows are zeros and sort
        # to the end (key e_loc), where the kernel's pad group eats them
        cap = ep * m_l
        csum = jnp.cumsum(c_re, axis=1)                     # (src, E_loc)
        eloc = jax.vmap(
            lambda c, r: jnp.searchsorted(c, r, side="right")
        )(csum, jnp.broadcast_to(q, (ep, m_l)))
        key = jnp.where(q[None, :] < recv[:, None], eloc, e_loc)
        perm2 = jnp.argsort(key.reshape(-1), stable=True).astype(jnp.int32)
        inv2 = (
            jnp.zeros((cap,), jnp.int32)
            .at[perm2]
            .set(jnp.arange(cap, dtype=jnp.int32), unique_indices=True)
        )
        perm2 = checkpoint_name(perm2, "moe_plan")
        inv2 = checkpoint_name(inv2, "moe_plan")
        # _unsort_rows is "permute by known inverse": rows[perm2] with
        # cotangent rows[inv2]
        ys2 = _unsort_rows(got.reshape(cap, d), perm2, inv2)

        sizes_loc = c_re.sum(0)                             # (E_loc,)
        cap_pad = -(-cap // DEFAULT_BLOCK_M) * DEFAULT_BLOCK_M
        total = jnp.sum(sizes_loc)
        # alignment + invalid rows ride in the last group (zero lhs rows
        # → zero outputs), exactly like the single-shard path's m_pad
        sizes_loc = sizes_loc.at[e_loc - 1].add(cap_pad - total)
        lhs = jnp.pad(ys2, ((0, cap_pad - cap), (0, 0)))
        lhs = checkpoint_name(lhs, "moe_dispatch")

        gmm = functools.partial(grouped_matmul, use_pallas=cfg.use_pallas)
        gated = jax.nn.silu(gmm(lhs, wg_l, sizes_loc)) * gmm(
            lhs, wu_l, sizes_loc
        )
        rows_out = checkpoint_name(
            gmm(gated, wd_l, sizes_loc), "moe_expert_out"
        )

        # -- return exchange: unsort to chunk order, all_to_all back,
        # read my rows out of the bins they came back in ------------------
        back = _unsort_rows(rows_out[:cap], inv2, perm2)
        ret = lax.all_to_all(
            back.reshape(ep, m_l, d), "expert", 0, 0, tiled=False
        )
        back_sorted = _read_bins(ret, dst_of, pos_of, idx, valid_bin)

        rows_tok = _unsort_rows(back_sorted, inv, perm)
        w_tok = weight.transpose(1, 2, 0).reshape(b_l, s, k)
        return jnp.sum(
            rows_tok.reshape(b_l, s, k, d)
            * w_tok[..., None].astype(rows_tok.dtype),
            axis=2,
        )

    # function-level import: models ← parallel is the package-level
    # dependency direction (parallel/pipeline imports models)
    from tpu_kubernetes.parallel.compat import shard_map_compat

    return shard_map_compat(
        inner,
        mesh=mesh,
        in_specs=(
            P("expert"), P("expert"), P("expert"), P("expert"), P("expert"),
        ),
        out_specs=P("expert"),
        axis_names={"expert"},
        # the routing plan genuinely varies per expert shard; VMA checking
        # would reject the deliberate divergence
        check_vma=False,
    )(y, layer["w_gate"], layer["w_up"], layer["w_down"], gates)
