"""tpu_kubernetes.models — model family for the in-tree training stack.

Two members share one functional API (init_params / forward / loss_fn /
logical_axes, all taking the config last or as ``cfg=``):

* dense LLaMA decoders (models/llama.py) — the flagship (north-star trains
  Llama-7B on a v5p-32 slice);
* sparse Mixture-of-Experts decoders (models/moe.py) — the
  expert-parallel member.

The top-level functions here dispatch on the config type, so the trainer
and bench are family-agnostic.
"""

from tpu_kubernetes.models import llama as _llama
from tpu_kubernetes.models import moe as _moe
from tpu_kubernetes.models.decode import (  # noqa: F401
    KVCache,
    SlotState,
    cache_clear_row,
    cache_insert_row,
    decode_chunk,
    decode_segment,
    decode_segment_slots,
    decode_step,
    decode_step_slots,
    decode_verify_paged,
    decode_verify_slots,
    generate,
    init_cache,
    init_slot_state,
    prefill,
    prefill_chunked,
    prefill_resume,
)
from tpu_kubernetes.models.speculative import (  # noqa: F401
    SpecStats,
    ngram_propose_host,
    prompt_lookup_generate,
    speculative_generate,
)
from tpu_kubernetes.models.llama import ModelConfig  # noqa: F401
from tpu_kubernetes.models.llama import param_count  # noqa: F401
from tpu_kubernetes.models.moe import MoEConfig, expert_capacity  # noqa: F401
from tpu_kubernetes.models.convert_hf import (  # noqa: F401
    export_hf_llama,
    load_hf,
    load_hf_llama,
)
from tpu_kubernetes.models.quant import (  # noqa: F401
    quantize_for_decode,
    quantized_param_bytes,
)

CONFIGS: dict[str, ModelConfig] = {**_llama.CONFIGS, **_moe.MOE_CONFIGS}


def _family(cfg: ModelConfig):
    return _moe if isinstance(cfg, MoEConfig) else _llama


def init_params(rng, cfg: ModelConfig) -> dict:
    return _family(cfg).init_params(rng, cfg)


def logical_axes(cfg: ModelConfig) -> dict:
    return _family(cfg).logical_axes(cfg)


def forward(params: dict, tokens, cfg: ModelConfig):
    return _family(cfg).forward(params, tokens, cfg)


def loss_fn(params: dict, tokens, cfg: ModelConfig):
    return _family(cfg).loss_fn(params, tokens, cfg)
