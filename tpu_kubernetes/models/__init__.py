"""tpu_kubernetes.models — model family for the in-tree training stack."""

from tpu_kubernetes.models.llama import (  # noqa: F401
    CONFIGS,
    ModelConfig,
    forward,
    init_params,
    logical_axes,
    loss_fn,
    param_count,
)
