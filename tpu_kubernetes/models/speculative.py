"""Speculative decoding: assisted greedy generation, token-exact.

Two drafting strategies over one verification loop:

* :func:`speculative_generate` — a small DRAFT MODEL proposes ``draft_k``
  tokens sequentially.
* :func:`prompt_lookup_generate` — NO draft model: the most recent
  n-gram match in the already-seen context (prompt + generated) proposes
  its historical continuation. Free proposals; strong on extractive /
  code / repetitive text.

Either way the target model scores all proposals in ONE ``decode_chunk``
forward (models/decode.py) and keeps the longest prefix it agrees with,
plus its own correction token — so each target pass emits between 1 and
draft_k+1 tokens. Greedy (temperature 0) acceptance makes the output
**exactly** the target model's own greedy decode, whatever the draft
proposes; that invariant is pinned in tests/test_speculative.py. One
precision caveat: "exactly" means the greedy decode at the SAME KV-cache
span (``generate(..., cache_span=prompt+new+draft_k)``) — cache size
changes XLA's attention reduction order, and differently-sized programs
can round near-tied logits to different argmaxes. A good draft turns the
HBM-bound per-token weight stream into one stream per ~(1+accepted)
tokens.

TPU-first shape discipline:

* The token budget is a static ``(1, max_new_tokens)`` buffer; accepted
  tokens land via masked out-of-bounds-dropping scatters, never a
  data-dependent shape.
* One ``lax.while_loop`` over rounds (each emits ≥ 1 token, so it
  terminates in ≤ max_new rounds); everything inside is fixed-shape:
  proposals, one (k+1)-token target chunk, prefix-match acceptance as a
  cumprod. N-gram search is a static window-stack comparison.
* Cache rollback is O(1): KV caches are allocated once and "rolled back"
  by rewinding ``length`` — stale slots above it are masked out of
  attention and overwritten by the next round's writes.

Batch 1 only (per-row acceptance counts would need per-row cache
cursors); the standard configuration for assisted generation. The
reference provisioner has no inference plane (SURVEY §0); this extends
the serving stack models/decode.py established.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from tpu_kubernetes.models.decode import decode_chunk, decode_step, prefill
from tpu_kubernetes.models.llama import ModelConfig


class SpecStats(NamedTuple):
    """rounds: target chunk passes run; drafted: draft tokens proposed;
    accepted: draft tokens the target agreed with AND that fit the
    remaining token budget — i.e. draft tokens actually emitted (the
    speedup signal: tokens-per-target-pass = emitted / rounds)."""

    rounds: jax.Array
    drafted: jax.Array
    accepted: jax.Array


def _check_target(cfg: ModelConfig, prompt: jax.Array) -> None:
    if prompt.shape[0] != 1:
        raise ValueError(
            f"speculative decoding is batch-1 only, got batch {prompt.shape[0]}"
        )
    from tpu_kubernetes.models.moe import MoEConfig

    if isinstance(cfg, MoEConfig):
        # MoE expert capacity is computed per forward chunk, so a (k+1)-
        # token verification pass can drop tokens sequential decode would
        # keep — silently voiding the exactness guarantee. Refuse loudly.
        # (A MoE *draft* is fine: drafts only propose, never verify.)
        raise ValueError(
            "speculative verification requires a dense target model "
            "(MoE capacity semantics are chunk-size-dependent)"
        )


def _spec_loop(
    params: dict,
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    k: int,
    span: int,
    propose: Callable,   # (last, out, cursor, state) → ((k,) drafts, state)
    rewind: Callable,    # (state, valid_len) → state
    state0: Any,
) -> tuple[jax.Array, SpecStats]:
    """The shared verify/accept/rollback skeleton. Invariant at every
    round boundary: the target cache (and any draft cache inside
    ``state``) holds positions < plen + cursor - 1 — everything before
    ``last``, which sits at position plen + cursor - 1."""
    plen = prompt.shape[1]
    logits, cache_t = prefill(params, prompt, cfg, max_seq=span)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]    # ()
    out = jnp.zeros((max_new_tokens,), jnp.int32).at[0].set(first)

    def cond(carry):
        _, cursor, *_ = carry
        return cursor < max_new_tokens

    def body(carry):
        out, cursor, last, cache_t, state, stats = carry
        drafts, state = propose(last, out, cursor, state)        # (k,)

        chunk = jnp.concatenate([last[None], drafts])            # (k+1,)
        logits_c, cache_t = decode_chunk(params, cache_t, chunk[None], cfg)
        greedy = jnp.argmax(logits_c[0], axis=-1).astype(jnp.int32)  # (k+1,)

        matches = (drafts == greedy[:k]).astype(jnp.int32)
        j = jnp.sum(jnp.cumprod(matches))                        # 0..k
        n_emit = jnp.minimum(j + 1, max_new_tokens - cursor)

        # the emitted tokens ARE the target's greedy choices: for i < j
        # drafts[i] == greedy[i] by definition of the matched prefix, and
        # position j takes the target's correction greedy[j]
        idx = jnp.arange(k + 1, dtype=jnp.int32)
        write_at = jnp.where(idx < n_emit, cursor + idx, max_new_tokens)
        out = out.at[write_at].set(greedy, mode="drop")
        last = greedy[n_emit - 1]
        cursor = cursor + n_emit

        # rewind to "everything before the new last token"; stale higher
        # slots are masked out and overwritten next round
        valid = plen + cursor - 1
        cache_t = cache_t._replace(length=valid)
        state = rewind(state, valid)
        stats = SpecStats(
            rounds=stats.rounds + 1,
            drafted=stats.drafted + k,
            # in the final round n_emit may clip the matched prefix to
            # the remaining budget; count only what was emitted so the
            # logged acceptance rate isn't inflated for short runs.
            # Unclipped: j drafts + 1 correction emitted → j. Clipped
            # (n_emit <= j): every emitted token is a matched draft.
            accepted=stats.accepted + jnp.minimum(j, n_emit),
        )
        return out, cursor, last, cache_t, state, stats

    zero = jnp.zeros((), jnp.int32)
    stats0 = SpecStats(rounds=zero, drafted=zero, accepted=zero)
    out, _, _, _, _, stats = jax.lax.while_loop(
        cond,
        body,
        (out, jnp.asarray(1, jnp.int32), first, cache_t, state0, stats0),
    )
    return out[None, :], stats


def speculative_generate(
    params: dict,
    draft_params: dict,
    prompt: jax.Array,
    cfg: ModelConfig,
    draft_cfg: ModelConfig,
    max_new_tokens: int,
    *,
    draft_k: int = 4,
    draft_kv_quant: bool = False,
) -> tuple[jax.Array, SpecStats]:
    """prompt (1, prompt_len) int32 → ((1, max_new_tokens) int32, stats).

    Draft-model speculative decoding; the emitted tokens are exactly
    ``generate(params, prompt, cfg, max_new_tokens)`` (temperature 0).
    Jittable end to end with static cfg/max_new_tokens/draft_k.

    ``draft_kv_quant`` runs the DRAFT model on an int8 KV cache
    (models/decode.KVCache): drafts only propose, never verify, so
    quantization noise can change the acceptance rate but NEVER the
    output — the exactness-first composition rule that forbids int8 on
    the verification cache does not apply to the draft's. Halves the
    draft's per-step cache traffic; the win grows with context length.
    """
    _check_target(cfg, prompt)
    if draft_k < 1:
        raise ValueError(f"draft_k must be >= 1, got {draft_k}")
    plen = prompt.shape[1]
    # chunk writes can transiently reach plen + max_new - 1 + draft_k
    span = plen + max_new_tokens + draft_k
    for name, c in (("target", cfg), ("draft", draft_cfg)):
        if span > c.max_seq:
            raise ValueError(
                f"prompt {plen} + new {max_new_tokens} + draft_k {draft_k} "
                f"exceeds {name} max_seq {c.max_seq}"
            )

    _, cache_d0 = prefill(
        draft_params, prompt, draft_cfg, max_seq=span,
        kv_quant=draft_kv_quant,
    )
    k = draft_k

    def propose(last, out, cursor, cache_d):
        def dstep(c, _):
            cache_d, tok = c
            lg, cache_d = decode_step(
                draft_params, cache_d, tok[None], draft_cfg
            )
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)[0]
            return (cache_d, nxt), nxt

        (cache_d, _), drafts = jax.lax.scan(
            dstep, (cache_d, last), None, length=k
        )                                                        # (k,)
        # the draft never processed its own last proposal — one step so
        # the full-acceptance case finds d_k's K/V in the cache next round
        _, cache_d = decode_step(
            draft_params, cache_d, drafts[k - 1][None], draft_cfg
        )
        return drafts, cache_d

    return _spec_loop(
        params, prompt, cfg, max_new_tokens, k, span,
        propose, lambda cache_d, valid: cache_d._replace(length=valid),
        cache_d0,
    )


def prompt_lookup_generate(
    params: dict,
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    *,
    draft_k: int = 8,
    ngram: int = 2,
) -> tuple[jax.Array, SpecStats]:
    """Draft-model-FREE speculative decoding (prompt-lookup): propose the
    continuation of the most recent earlier occurrence of the last
    ``ngram`` tokens in the seen context (prompt + generated so far).
    Proposals cost no model forward, so ``draft_k`` defaults higher than
    the draft-model path. Output is exactly the target's greedy decode;
    when no n-gram repeats, each round still emits the target's one
    correction token (plain decode pace, paid as one chunk pass).
    """
    _check_target(cfg, prompt)
    if draft_k < 1 or ngram < 1:
        raise ValueError(f"draft_k ({draft_k}) and ngram ({ngram}) must be >= 1")
    plen = prompt.shape[1]
    # span doubles as the context-buffer length: [prompt | out | k zeros],
    # the zero tail making the continuation slice safe near the valid end
    span = plen + max_new_tokens + draft_k
    if span > cfg.max_seq:
        raise ValueError(
            f"prompt {plen} + new {max_new_tokens} + draft_k {draft_k} "
            f"exceeds max_seq {cfg.max_seq}"
        )
    if ngram > span - 1:
        raise ValueError(
            f"ngram ({ngram}) exceeds the context length ({span})"
        )
    prompt_vec = prompt[0].astype(jnp.int32)

    def propose(last, out, cursor, state):
        ctx = jnp.concatenate(
            [prompt_vec, out, jnp.zeros((draft_k,), jnp.int32)]
        )
        valid = plen + cursor                       # tokens seen so far
        drafts = _ngram_propose(ctx, valid, ngram, draft_k, last)
        return drafts, state

    return _spec_loop(
        params, prompt, cfg, max_new_tokens, draft_k, span,
        propose, lambda state, valid: state, jnp.zeros((), jnp.int32),
    )


def ngram_propose_host(ctx: list, n: int, k: int, last: int) -> list:
    """:func:`_ngram_propose` as plain host python over a real-token
    list — the proposer the continuous-batching slot engine runs per
    slot between verify rounds (serve/server.py), standalone here so
    its edge cases unit-test without a device. Find the LATEST earlier
    occurrence of the last ``n`` tokens of ``ctx`` and return the k
    tokens after it, padded with ``last`` when the historical
    continuation runs out; no match (or a context too short to hold an
    n-gram plus its recurrence) → ``last`` repeated k times.
    Proposals only set the SPEED of the verify loop, never its tokens:
    greedy verification keeps exactly the target's choices, so a bad
    guess costs a round, not correctness."""
    if n < 1 or k < 1:
        raise ValueError(f"ngram ({n}) and draft_k ({k}) must be >= 1")
    if len(ctx) > n:
        tail = list(ctx[-n:])
        for start in range(len(ctx) - n - 1, -1, -1):
            if list(ctx[start:start + n]) == tail:
                cont = list(ctx[start + n:start + n + k])
                return cont + [last] * (k - len(cont))
    return [last] * k


def _ngram_propose(ctx: jax.Array, valid, n: int, k: int, last) -> jax.Array:
    """The prompt-lookup matcher, standalone for direct unit testing:
    find the LATEST occurrence of ``ctx[valid-n : valid]`` (the tail
    n-gram) strictly before the tail itself within ``ctx[:valid]``, and
    return the k tokens following it. No match → ``last`` repeated k
    times (verification rejects at worst; one correction token still
    comes out of the round)."""
    n_windows = ctx.shape[0] - n + 1
    tail = jax.lax.dynamic_slice(ctx, (valid - n,), (n,))
    # windows[i] = ctx[i : i+n], as n static shifted slices
    windows = jnp.stack(
        [ctx[j : j + n_windows] for j in range(n)], axis=1
    )                                               # (n_windows, n)
    pos = jnp.arange(n_windows, dtype=jnp.int32)
    # match strictly BEFORE the tail itself, fully inside the seen ctx
    match = jnp.all(windows == tail[None, :], axis=1) & (pos < valid - n)
    any_match = jnp.any(match)
    latest = jnp.max(jnp.where(match, pos, -1))
    start = jnp.maximum(latest + n, 0)
    drafts = jax.lax.dynamic_slice(ctx, (start,), (k,))
    return jnp.where(any_match, drafts, jnp.full((k,), last))
