"""Autoregressive inference: KV-cache prefill + decode for both model
families (dense LLaMA and MoE).

TPU-first decisions:

* **Static shapes end to end.** The cache is allocated at ``max_seq`` up
  front; the position is a traced scalar and writes are
  ``dynamic_update_slice`` — one compile covers the whole generation.
* **One program.** ``generate`` is a single jittable function: prefill
  (full-sequence forward that also emits the cache via ``lax.scan`` ys)
  followed by a ``lax.scan`` of single-token decode steps with sampling
  folded in. No Python-level token loop, no host round-trips.
* **Cache in KV heads.** GQA caches ``n_kv_heads`` (memory ∝ kv); decode
  attention groups query heads over their KV head in the einsum itself,
  so the cache is never materialized ``h/kv``-times wider.
* **Cache updated in place.** The stacked (L, b, kv, S, d) cache rides the
  layer scan's *carry* and each layer writes exactly one position with
  ``dynamic_update_slice`` — XLA aliases the donated carry buffer, so a
  decode step moves O(params + cache-read + one token) bytes. (Routing the
  cache through scan xs/ys instead — the obvious structure — makes XLA
  restack a fresh full cache every step: ~100 MB/step of pure copy at
  llama-1b bench shapes, measured.)
* Decode attention is plain masked dot-product against the cache (a
  single query token has no O(seq²) problem — flash buys nothing there);
  prefill reuses the training forward path (flash/Pallas on TPU).

Sharding contract: every KV-bearing array this module allocates —
row/slot caches ``(L, b, kv, S, d)``, paged pools
``(L, pages+1, kv, page, d)``, and their int8 scales — keeps the
kv-heads dimension at **axis 2**. parallel/serving.py's sharded-engine
program builders key on that invariant (rank >= 4 ⇒ shard axis 2 on
the ``tensor`` mesh axis; everything else — tokens, page tables,
``SlotState`` — replicates), so the slot/paged primitives here run
unchanged per shard under ``shard_map``. A new cache layout must either
keep kv at axis 2 or teach ``kv_partition_spec`` its shape. The
``jaxcontract`` analyzer pass enforces the pin statically (``kv-axis-pin``,
docs/guide/static-analysis.md), alongside the donation-safety and
jit-purity contracts every program built over these caches relies on.

MoE semantics: the routed layer runs per chunk (the prompt in prefill,
one token per decode step), so expert-capacity dropping — whose threshold
scales with the chunk's length — effectively never fires at decode time
(single-token chunks always fit). That is the standard inference choice:
capacity dropping is a training-time batching artifact, and decode
matches the training forward exactly whenever nothing would be dropped
(see tests/test_decode.py for the precise equivalence statement).

The reference provisioner has no inference plane; this completes the
in-tree model stack (training + serving entry points on the same params).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpu_kubernetes.models.llama import ModelConfig
from tpu_kubernetes.models.moe import MoEConfig, moe_sublayer
from tpu_kubernetes.models.quant import is_quantized, weight as _w
from tpu_kubernetes.ops import (
    apply_rope,
    flash_attention,
    rms_norm,
    rope_frequencies,
)


class KVCache(NamedTuple):
    """Stacked per-layer cache: k/v are (n_layers, batch, kv_heads,
    max_seq, head_dim); length is the number of filled SLOTS (uniform
    across the batch — generated tokens always append at slot ``length``).

    Ragged (right-padded) prompt batches additionally carry
    ``prompt_lengths`` ((batch,) real prompt tokens per row) and
    ``prompt_slots`` (scalar: the padded prompt width where generation
    slots begin). Row i's REAL positions are then slots [0, prompt_lengths
    [i]) ∪ [prompt_slots, length) — decode attention masks everything
    else (the pad slots hold garbage K/V from prefill), and RoPE positions
    for generated tokens are per-row (prompt_lengths[i] + t) so each row's
    relative geometry is gapless even though its cache slots are not.
    Both None = the uniform case (every slot < length is real)."""

    k: jax.Array
    v: jax.Array
    length: jax.Array  # () int32
    prompt_lengths: jax.Array | None = None  # (batch,) int32
    prompt_slots: jax.Array | None = None    # () int32
    # int8 KV quantization (kv_quant=True): k/v hold int8 and these hold
    # the symmetric per-(layer, row, head, position) f32 dequant scales
    # (L, b, kv, S). None = full-precision cache. Halves the cache bytes
    # HBM streams per decode step (+4/head_dim scale overhead) and
    # doubles the max context per HBM byte; attention dequantizes on
    # read, where XLA fuses the multiply into the score einsum.
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None


def init_cache(
    cfg: ModelConfig, batch: int, max_seq: int | None = None,
    kv_quant: bool = False,
) -> KVCache:
    s = max_seq or cfg.max_seq
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, s, cfg.head_dim)
    if kv_quant:
        return KVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            length=jnp.zeros((), jnp.int32),
            k_scale=jnp.ones(shape[:-1], jnp.float32),
            v_scale=jnp.ones(shape[:-1], jnp.float32),
        )
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        length=jnp.zeros((), jnp.int32),
    )


def _quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(…, head_dim) float → (int8 values, f32 per-row scale (…,)).
    Symmetric absmax over the head dim — no zero point, so dequant is
    one broadcast multiply on the attention read path."""
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(x32 / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def _mlp(cfg: ModelConfig, x: jax.Array, layer: dict) -> jax.Array:
    """The post-attention sublayer for either family (residual included).
    Weights are read through the quant accessor, so int8-exported params
    (models/quant.py) serve through the same code path."""
    if isinstance(cfg, MoEConfig):
        if any(is_quantized(layer[k]) for k in ("w_gate", "w_up", "w_down")):
            layer = {**layer, **{
                k: _w(layer[k], cfg.dtype) for k in ("w_gate", "w_up", "w_down")
            }}
        out, _ = moe_sublayer(cfg, x, layer)
        return out
    y = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gated = jax.nn.silu(y @ _w(layer["w_gate"], cfg.dtype)) * (
        y @ _w(layer["w_up"], cfg.dtype)
    )
    return x + gated @ _w(layer["w_down"], cfg.dtype)


def _attend_cache(cfg, q, k_cache, v_cache, limits,
                  prompt_lengths=None, prompt_slots=None,
                  k_scale=None, v_scale=None):
    """Decode-side attention only: q (b, h, c, d) against the cache
    (b, kv, S, d); chunk row i attends slots < limits[i] (``limits``
    (c,) shared across the batch, or (b, c) per-row) — causal within a
    multi-token chunk, full against the history. For ragged prompt
    batches the pad slots between a row's real prompt and the uniform
    generation region are masked too (see KVCache). GQA: query heads are
    grouped over their KV head inside the einsum (no repeated cache).
    Prefill goes through the training flash kernel instead."""
    h, kv = cfg.n_heads, cfg.n_kv_heads
    b, _, c, hd = q.shape
    rep = h // kv
    qg = q.reshape(b, kv, rep, c, hd).astype(jnp.float32)
    k32 = k_cache.astype(jnp.float32)
    if k_scale is not None:              # int8 cache → dequantize on read
        k32 = k32 * k_scale[..., None]
    s = jnp.einsum(
        "bkrcd,bksd->bkrcs", qg, k32
    ) * (1.0 / (cfg.head_dim ** 0.5))
    slots = jnp.arange(k_cache.shape[2])
    mask = slots < limits[..., None]                # (c, S) | (b, c, S)
    if prompt_lengths is not None:
        # ragged chunks: limits (b, c), mask (b, c, S) — c=1 is the
        # classic decode step, c>1 is chunk verification.
        # prompt_slots is a scalar for uniform batches (all rows'
        # generation region starts at one padded width) or (b,) for
        # slot-mixed batches (decode_step_slots), where every row keeps
        # its own prompt width; reshape(-1, 1) broadcasts both.
        ps = jnp.asarray(prompt_slots).reshape(-1, 1)
        real = (
            (slots[None, :] < prompt_lengths[:, None])
            | (slots[None, :] >= ps)
        )                                           # (b, S)
        mask = mask & real[:, None, :]
    if mask.ndim == 2:                              # shared across batch
        s = jnp.where(mask[None, None, None], s, -1e30)
    else:                                           # per-row
        s = jnp.where(mask[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    v32 = v_cache.astype(jnp.float32)
    if v_scale is not None:
        v32 = v32 * v_scale[..., None]
    out = jnp.einsum("bkrcs,bksd->bkrcd", p, v32)
    return out.reshape(b, h, c, hd).astype(q.dtype)


def _decode_block(cfg, cos, sin, pos, li, x, layer, kv_state,
                  prompt_lengths=None, prompt_slots=None):
    """One layer, one chunk of c tokens at slots ``pos .. pos+c-1``.
    x: (b, c, d); ``kv_state`` = (k_all, v_all, ks_all, vs_all) — the
    FULL stacked cache (L, b, kv, S, d) plus the int8 dequant scales (or
    None, None for a full-precision cache) — threaded through with layer
    ``li``'s slice updated in place (one c-position dynamic_update_slice
    on the scan carry — see module docstring). c == 1 is the classic
    decode step; c > 1 is chunk verification — uniform or ragged (the
    chunk occupies uniform slots past the ragged prompt region, so each
    row's positions stay gapless). → (x, kv_state)."""
    b, c, _ = x.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    y = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (y @ _w(layer["wq"], cfg.dtype)).reshape(b, c, h, hd).transpose(0, 2, 1, 3)
    k = (y @ _w(layer["wk"], cfg.dtype)).reshape(b, c, kv, hd).transpose(0, 2, 1, 3)
    v = (y @ _w(layer["wv"], cfg.dtype)).reshape(b, c, kv, hd).transpose(0, 2, 1, 3)
    off = jnp.arange(c, dtype=jnp.int32)
    if prompt_lengths is not None:
        # ragged rows: the token in SLOT pos+j is row i's LOGICAL position
        # prompt_lengths[i] + (pos - prompt_slots) + j — gapless per row
        positions = (
            prompt_lengths[:, None] + (pos - prompt_slots) + off[None, :]
        )                                                    # (b, c)
        # chunk row j sees the history plus chunk rows ≤ j
        limits = jnp.broadcast_to((pos + 1 + off)[None, :], (b, c))
    else:
        positions = pos + off                                # (c,)
        limits = positions + 1
    q = apply_rope(q, cos, sin, positions=positions)
    k = apply_rope(k, cos, sin, positions=positions)

    k_all, v_all, ks_all, vs_all = kv_state
    if ks_all is not None:               # int8 cache: quantize on write
        k, k_sc = _quantize_kv(k)
        v, v_sc = _quantize_kv(v)
        ks_all = jax.lax.dynamic_update_slice(
            ks_all, k_sc[None], (li, 0, 0, pos)
        )
        vs_all = jax.lax.dynamic_update_slice(
            vs_all, v_sc[None], (li, 0, 0, pos)
        )
    k_all = jax.lax.dynamic_update_slice(k_all, k[None], (li, 0, 0, pos, 0))
    v_all = jax.lax.dynamic_update_slice(v_all, v[None], (li, 0, 0, pos, 0))
    k_cache = jax.lax.dynamic_index_in_dim(k_all, li, 0, keepdims=False)
    v_cache = jax.lax.dynamic_index_in_dim(v_all, li, 0, keepdims=False)
    k_scale = v_scale = None
    if ks_all is not None:
        k_scale = jax.lax.dynamic_index_in_dim(ks_all, li, 0, keepdims=False)
        v_scale = jax.lax.dynamic_index_in_dim(vs_all, li, 0, keepdims=False)

    attn = _attend_cache(cfg, q, k_cache, v_cache, limits,
                         prompt_lengths, prompt_slots,
                         k_scale=k_scale, v_scale=v_scale)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, c, h * hd)
    x = x + attn @ _w(layer["wo"], cfg.dtype)
    return _mlp(cfg, x, layer), (k_all, v_all, ks_all, vs_all)


def prefill(
    params: dict, tokens: jax.Array, cfg: ModelConfig,
    max_seq: int | None = None, lengths: jax.Array | None = None,
    kv_quant: bool = False,
) -> tuple[jax.Array, KVCache]:
    """Process the whole prompt at once. tokens: (batch, prompt_len) →
    (last-position logits (batch, vocab) f32, filled cache).

    ``lengths`` ((batch,) int32) marks a RIGHT-padded ragged batch: the
    returned logits are each row's last REAL position (lengths[i]-1, not
    prompt_len-1) and the cache records the per-row lengths so decode
    masks the pad slots. Causality already keeps real tokens blind to the
    trailing pads; the garbage K/V the pad positions produce is dealt
    with at decode time (see KVCache).

    ``kv_quant`` stores the cache as int8 + per-position scales (see
    KVCache): prefill's own attention still runs full-precision — only
    what later decode steps READ is quantized."""
    b, plen = tokens.shape
    S = max_seq or cfg.max_seq
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    x = params["embed"][tokens]

    def block(x, layer):
        y = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (y @ _w(layer["wq"], cfg.dtype)).reshape(b, plen, h, hd).transpose(0, 2, 1, 3)
        k = (y @ _w(layer["wk"], cfg.dtype)).reshape(b, plen, kv, hd).transpose(0, 2, 1, 3)
        v = (y @ _w(layer["wv"], cfg.dtype)).reshape(b, plen, kv, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # cache this layer's K/V padded out to S
        pad = [(0, 0), (0, 0), (0, S - plen), (0, 0)]
        k_full = jnp.pad(k, pad)
        v_full = jnp.pad(v, pad)
        kq = k
        vq = v
        if kv != h:
            rep = h // kv
            kq = jnp.repeat(kq, rep, axis=1)
            vq = jnp.repeat(vq, rep, axis=1)
        # the same flash kernel as training (Pallas on TPU, XLA reference
        # elsewhere) — prefill is a full-sequence causal forward
        attn = flash_attention(
            q, kq, vq, causal=True,
            block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
            use_pallas=cfg.use_pallas,
        )
        attn = attn.transpose(0, 2, 1, 3).reshape(b, plen, h * hd)
        x = x + attn @ _w(layer["wo"], cfg.dtype)
        if kv_quant:
            # quantize the plen real positions, THEN pad: identical cache
            # (pad q=0, scale=1) without absmax/round work over S - plen
            # all-zero rows
            kq8, k_sc = _quantize_kv(k)
            vq8, v_sc = _quantize_kv(v)
            sc_pad = [(0, 0), (0, 0), (0, S - plen)]
            return _mlp(cfg, x, layer), (
                jnp.pad(kq8, pad), jnp.pad(vq8, pad),
                jnp.pad(k_sc, sc_pad, constant_values=1.0),
                jnp.pad(v_sc, sc_pad, constant_values=1.0),
            )
        return _mlp(cfg, x, layer), (k_full, v_full)

    if kv_quant:
        x, (k_cache, v_cache, k_sc, v_sc) = jax.lax.scan(
            block, x, params["layers"]
        )
        scales = {"k_scale": k_sc, "v_scale": v_sc}
    else:
        x, (k_cache, v_cache) = jax.lax.scan(block, x, params["layers"])
        scales = {}

    if lengths is None:
        x_last = x[:, -1]
        cache = KVCache(
            k=k_cache, v=v_cache, length=jnp.asarray(plen, jnp.int32),
            **scales,
        )
    else:
        x_last = jnp.take_along_axis(
            x, (lengths - 1)[:, None, None], axis=1
        )[:, 0]
        cache = KVCache(
            k=k_cache, v=v_cache, length=jnp.asarray(plen, jnp.int32),
            prompt_lengths=lengths.astype(jnp.int32),
            prompt_slots=jnp.asarray(plen, jnp.int32),
            **scales,
        )
    x_last = rms_norm(x_last, params["final_norm"], cfg.norm_eps)
    logits = (x_last @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    return logits, cache


def prefill_chunked(
    params: dict, tokens: jax.Array, cfg: ModelConfig,
    max_seq: int, chunk: int = 256, kv_quant: bool = False,
) -> tuple[jax.Array, KVCache]:
    """Prefill a (batch, prompt_len) prompt in fixed ``chunk``-token
    pieces: a ``lax.scan`` over the headless decode-chunk body. Same contract
    as :func:`prefill` (last-position logits + filled cache), but peak
    activation memory and the compiled attention shape are bounded by
    ``chunk`` instead of the full prompt — the long-context serving
    entry. prompt_len must divide by ``chunk`` (right-size or pad-free
    slice upstream; ragged batches unsupported). The O(vocab) head runs
    ONCE on the final position, not per chunk. Logits match
    :func:`prefill` up to float reduction-order differences (the chunk
    path scores against the growing cache instead of one fused
    attention) — EXCEPT under ``kv_quant``, where each chunk attends the
    already-quantized history (exactly what later decode steps will see,
    but unlike whole-prompt ``prefill``, whose own attention stays full
    precision), so int8 rounding accumulates across chunks and long
    prompts can diverge beyond reduction-order ties."""
    b, plen = tokens.shape
    if plen % chunk:
        raise ValueError(f"prompt_len {plen} must divide by chunk {chunk}")
    if plen > max_seq:
        # dynamic_update_slice would CLAMP out-of-range chunk writes and
        # silently corrupt the cache tail — fail like prefill does
        raise ValueError(f"prompt_len {plen} exceeds cache max_seq {max_seq}")
    if plen > cfg.max_seq:
        # positions past the RoPE table would silently clip to its last
        # rotation (gather semantics) — wrong logits, no error
        raise ValueError(
            f"prompt_len {plen} exceeds model max_seq {cfg.max_seq}"
        )
    cache = init_cache(cfg, b, max_seq, kv_quant=kv_quant)

    def step(cache, piece):
        hidden, cache = _decode_chunk_hidden(params, cache, piece, cfg)
        return cache, hidden[:, -1]

    pieces = tokens.reshape(b, plen // chunk, chunk).swapaxes(0, 1)
    cache, last_hidden = jax.lax.scan(step, cache, pieces)
    x = rms_norm(last_hidden[-1], params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    return logits, cache


def prefill_resume(
    params: dict, tokens: jax.Array, cfg: ModelConfig, cache: KVCache,
    lengths: jax.Array | None = None,
) -> tuple[jax.Array, KVCache]:
    """Warm-prefix prefill: continue a UNIFORM cache already holding
    ``p = cache.length`` prompt positions with ``tokens`` (batch, c) —
    the prompt SUFFIX — in one chunked forward. Returns the same
    contract as :func:`prefill` (last-real-position logits (batch,
    vocab) f32, filled cache): resuming at p with suffix width c yields
    a cache metadata-identical to a cold ragged prefill of the whole
    (p + c)-wide prompt — ``prompt_slots = p + c``, ``prompt_lengths =
    p + lengths`` — so every downstream decode program is shared with
    the cold path.

    ``lengths`` ((batch,) int32) marks a RIGHT-padded ragged suffix:
    row i's real suffix is tokens[i, :lengths[i]]; the pad slots in
    [p + lengths[i], p + c) hold garbage K/V exactly like cold ragged
    prefill's pads, masked by the returned metadata. Causality makes
    the reused prefix slots valid for ANY continuation: K/V at position
    i depend only on tokens ≤ i, so a cached segment truncated to the
    shared prefix is bitwise what a fresh prefill of those positions
    computes (float reduction order aside — the suffix scores against
    the cache instead of one fused flash attention, the
    :func:`prefill_chunked` caveat, including its kv_quant divergence:
    the suffix attends the already-quantized prefix)."""
    if cache.prompt_lengths is not None:
        raise ValueError(
            "prefill_resume needs a uniform cache (prompt_lengths=None): "
            "a cached prefix is whole real positions [0, length)"
        )
    c = tokens.shape[1]
    if c < 1:
        raise ValueError("prefill_resume needs at least one suffix token")
    if c > cache.k.shape[3]:
        raise ValueError(
            f"suffix width {c} exceeds cache max_seq {cache.k.shape[3]}"
        )
    hidden, new_cache = _decode_chunk_hidden(params, cache, tokens, cfg)
    if lengths is None:
        x_last = hidden[:, -1]
    else:
        x_last = jnp.take_along_axis(
            hidden, (lengths - 1)[:, None, None], axis=1
        )[:, 0]
        new_cache = new_cache._replace(
            prompt_lengths=(cache.length + lengths).astype(jnp.int32),
            prompt_slots=new_cache.length,
        )
    x_last = rms_norm(x_last, params["final_norm"], cfg.norm_eps)
    logits = (x_last @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    return logits, new_cache


def decode_step(
    params: dict, cache: KVCache, token: jax.Array, cfg: ModelConfig
) -> tuple[jax.Array, KVCache]:
    """One token for the whole batch. token: (batch,) int32 at position
    ``cache.length`` → (logits (batch, vocab) f32, cache advanced by 1)."""
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    pos = cache.length
    x = params["embed"][token][:, None, :]                   # (b, 1, d)

    def block(carry, xs):
        x, kv_state = carry
        layer, li = xs
        x, kv_state = _decode_block(
            cfg, cos, sin, pos, li, x, layer, kv_state,
            cache.prompt_lengths, cache.prompt_slots,
        )
        return (x, kv_state), None

    n_layers = cache.k.shape[0]
    (x, (k_new, v_new, ks_new, vs_new)), _ = jax.lax.scan(
        block,
        (x, (cache.k, cache.v, cache.k_scale, cache.v_scale)),
        (params["layers"], jnp.arange(n_layers, dtype=jnp.int32)),
    )

    x = rms_norm(x[:, 0], params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    return logits, KVCache(
        k=k_new, v=v_new, length=pos + 1,
        prompt_lengths=cache.prompt_lengths, prompt_slots=cache.prompt_slots,
        k_scale=ks_new, v_scale=vs_new,
    )


def decode_chunk(
    params: dict, cache: KVCache, tokens: jax.Array, cfg: ModelConfig
) -> tuple[jax.Array, KVCache]:
    """Process ``c`` tokens at positions ``cache.length .. +c-1`` in ONE
    forward: tokens (b, c) int32 → (logits (b, c, vocab) f32 — one set
    per chunk position — cache advanced by c).

    The multi-token generalization of :func:`decode_step` (c=1 is the
    same computation, through the same ``_decode_block``): chunk K/V are
    written into the cache first, then each chunk row attends all cache
    slots below its own position — causal within the chunk, full against
    the history. This is the verification primitive for speculative
    decoding (models/speculative.py), where the target model scores k
    draft tokens in one pass instead of k sequential steps. Ragged
    (right-padded) prompt batches are supported the same way as
    :func:`decode_step`: the chunk lands in the uniform generation
    region and the pad slots stay masked."""
    x, cache = _decode_chunk_hidden(params, cache, tokens, cfg)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    return logits, cache


def _decode_chunk_hidden(
    params: dict, cache: KVCache, tokens: jax.Array, cfg: ModelConfig
) -> tuple[jax.Array, KVCache]:
    """decode_chunk minus the head: (b, c) tokens → (final hidden states
    (b, c, d) pre-norm, advanced cache). Chunked prefill scans this so
    the O(c·vocab) logits matmul runs once at the end, not per chunk."""
    c = tokens.shape[1]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    pos = cache.length
    x = params["embed"][tokens]                                 # (b, c, d)

    def block(carry, xs):
        x, kv_state = carry
        layer, li = xs
        x, kv_state = _decode_block(
            cfg, cos, sin, pos, li, x, layer, kv_state,
            cache.prompt_lengths, cache.prompt_slots,
        )
        return (x, kv_state), None

    n_layers = cache.k.shape[0]
    (x, (k_new, v_new, ks_new, vs_new)), _ = jax.lax.scan(
        block,
        (x, (cache.k, cache.v, cache.k_scale, cache.v_scale)),
        (params["layers"], jnp.arange(n_layers, dtype=jnp.int32)),
    )
    return x, KVCache(
        k=k_new, v=v_new, length=pos + c,
        prompt_lengths=cache.prompt_lengths, prompt_slots=cache.prompt_slots,
        k_scale=ks_new, v_scale=vs_new,
    )


def _sample(logits: jax.Array, rng: jax.Array, temperature: float,
            top_k: int, top_p: float = 0.0):
    """(batch, vocab) f32 → (batch,) int32. temperature 0 = greedy.
    top_k and top_p (nucleus) filters compose — both static, one sort
    each, no data-dependent shapes (the nucleus is a mask, not a
    gather)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p > 0.0:
        # keep the smallest prefix of descending-probability tokens whose
        # mass reaches top_p; the highest-probability token always stays
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_p          # exclusive prefix mass
        # threshold = smallest kept logit, mapped back to the unsorted order
        cutoff = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1
        )[:, None]
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(
    params: dict,
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    rng: jax.Array | None = None,
    prompt_lengths: jax.Array | None = None,
    eos_id: int | None = None,
    pad_id: int = 0,
    cache_span: int | None = None,
    kv_quant: bool = False,
) -> jax.Array:
    """prompt (batch, prompt_len) int32 → (batch, max_new_tokens) int32.

    ``cache_span`` overrides the KV-cache allocation (default
    prompt_len + max_new_tokens). The cache size changes XLA's attention
    reduction order, which can flip greedy argmax on near-tied logits —
    pass the other program's span when comparing outputs bitwise (e.g.
    speculative decoding allocates prompt + new + draft_k).

    ``kv_quant`` serves from an int8 KV cache (see KVCache) — half the
    cache bytes per decode step at a small, bounded rounding error in
    attention (≤ 1/127 of each head-row's absmax per element).
    Jittable end to end (prefill + lax.scan of decode steps with sampling
    folded in); wrap in jax.jit with static cfg/max_new_tokens for a
    single compiled serving program.

    ``prompt_lengths`` ((batch,) int32) serves a RIGHT-padded ragged
    batch: row i's prompt is prompt[i, :prompt_lengths[i]], the rest pad.
    Each row generates from its own last real token with gapless RoPE
    positions and pad slots masked out of attention — for dense models,
    token-identical to generating each row unpadded (see KVCache). For
    MoE configs the identity is weaker: expert capacity is computed at
    the padded width, so a real token the unpadded run would capacity-
    drop can survive here (right-pad causality still guarantees pads
    never displace real tokens' slots, and rows never affect each other —
    capacity buckets are per row).

    ``eos_id`` stops a row once it samples that token: later positions
    emit ``pad_id``. Static shapes (the scan always runs max_new_tokens
    steps — finished rows just stop contributing tokens)."""
    if rng is None:
        rng = jax.random.PRNGKey(0)
    plen = prompt.shape[1]
    if plen + max_new_tokens > cfg.max_seq:
        raise ValueError(
            f"prompt {plen} + new {max_new_tokens} exceeds max_seq {cfg.max_seq}"
        )

    rng, first_rng = jax.random.split(rng)
    # right-size the cache: decode attends over plen+max_new positions,
    # not cfg.max_seq (static per compile, same as max_new_tokens)
    logits, cache = prefill(
        params, prompt, cfg, max_seq=cache_span or (plen + max_new_tokens),
        lengths=prompt_lengths, kv_quant=kv_quant,
    )
    first = _sample(logits, first_rng, temperature, top_k, top_p)
    done = jnp.zeros(prompt.shape[0], bool)
    if eos_id is not None:
        done = first == eos_id

    def step(carry, step_rng):
        cache, token, done = carry
        logits, cache = decode_step(params, cache, token, cfg)
        nxt = _sample(logits, step_rng, temperature, top_k, top_p)
        if eos_id is not None:
            emitted = jnp.where(done, pad_id, nxt)
            done = done | (nxt == eos_id)
        else:
            emitted = nxt
        return (cache, nxt, done), emitted

    rngs = jax.random.split(rng, max_new_tokens - 1)
    _, rest = jax.lax.scan(step, (cache, first, done), rngs)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def decode_segment(
    params: dict, cache: KVCache, token: jax.Array, done: jax.Array,
    cfg: ModelConfig, steps: int, *, eos_id: int | None = None,
    pad_id: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """``steps`` GREEDY decode steps with per-row done-masking — the
    early-exit building block: a host loop runs one segment per K
    steps, reads back ``done``, and stops once every row is finished,
    so a batch's wall time scales with its longest LIVE row instead of
    the batch max ``max_new_tokens``.

    token: (batch,) — each row's previously sampled token (the raw
    sample even for EOS'd rows, matching :func:`generate`'s carry, so
    the cache evolves identically and emitted tokens are token-identical
    to the fused scan). done: (batch,) bool — rows whose EOS already
    appeared; their emitted slots are ``pad_id``, exactly generate's
    masking. → (emitted (batch, steps) int32, next token (batch,),
    done (batch,), cache advanced by ``steps``). Budget-based liveness
    (a row that reached its OWN max_new) is the caller's host-side
    bookkeeping — budgets never change what a row emits, only when the
    loop may stop."""

    def step(carry, _):
        cache, tok, done = carry
        logits, cache = decode_step(params, cache, tok, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if eos_id is not None:
            emitted = jnp.where(done, pad_id, nxt)
            done = done | (nxt == eos_id)
        else:
            emitted = nxt
        return (cache, nxt, done), emitted

    (cache, token, done), toks = jax.lax.scan(
        step, (cache, token, done), None, length=steps
    )
    return toks.T, token, done, cache


# ---------------------------------------------------------------------------
# continuous batching: per-slot decode over a mixed batch
# ---------------------------------------------------------------------------


class SlotState(NamedTuple):
    """Per-slot decode carry for CONTINUOUS batching: every row of the
    batch cache is an independent request at its own position, admitted
    and retired without stopping the others. All fields are (batch,)
    int32; a slot is LIVE iff ``remaining > 0`` (EOS and budget
    exhaustion both zero it, folding done/live/budget into one field —
    the host reads one array between segments to drain finished slots).

    * ``tok``        — the row's previously emitted token (the decode
                       step's input, mirroring :func:`decode_segment`'s
                       raw-token carry);
    * ``pos``        — the cache SLOT the next K/V write lands in (its
                       logical RoPE position is ``prompt_lengths +
                       (pos - prompt_slots)`` — gapless per row, the
                       same rule ragged batches use);
    * ``remaining``  — tokens this row may still emit (its budget);
    * ``prompt_lengths`` / ``prompt_slots`` — the per-ROW ragged prompt
      metadata (unlike :class:`KVCache`, where ``prompt_slots`` is a
      batch-wide scalar: slot rows were prefilled at different widths).

    Retired slots keep decoding (static shapes): a dead row re-writes
    its own unused slot ``pos`` (never advanced once ``remaining`` hits
    0; always in range — the last live write was at ``pos - 1`` and
    admission required ``prompt_slots + budget <= max_seq``) and its
    attention reads only its own row, so garbage never crosses rows —
    the same independence argument ragged batching rests on (dense
    models only; MoE capacity is batch-shaped)."""

    tok: jax.Array
    pos: jax.Array
    remaining: jax.Array
    prompt_lengths: jax.Array
    prompt_slots: jax.Array


def init_slot_state(batch: int) -> SlotState:
    """All slots free: remaining=0 everywhere. Empty slots attend only
    their own zero-initialized row (pos=0 → one masked-in slot), so
    they are numerically inert until an insert claims them."""
    z = jnp.zeros((batch,), jnp.int32)
    return SlotState(tok=z, pos=z, remaining=z,
                     prompt_lengths=z, prompt_slots=z)


def cache_insert_row(cache: KVCache, row: KVCache, slot) -> KVCache:
    """Graft a freshly prefilled SINGLE-row cache segment into row
    ``slot`` of a fixed (max_batch, max_seq) batch cache — the admission
    primitive of continuous batching. ``row`` is what :func:`prefill`
    (or :func:`prefill_resume`) returns for one request at its own
    bucketed width; its k/v are padded out to the engine's max_seq with
    init_cache values (zeros; scale 1.0) so an occupied slot differs
    from a cold one only in its real positions. ``slot`` may be traced
    — jit once per row width, donate the batch cache (argnum 0) and XLA
    updates it in place."""
    S = cache.k.shape[3]
    s_row = row.k.shape[3]
    if s_row > S:
        raise ValueError(
            f"row cache width {s_row} exceeds engine max_seq {S}"
        )
    if row.k.shape[1] != 1:
        raise ValueError(f"row cache must be batch-1, got {row.k.shape[1]}")
    if (cache.k_scale is None) != (row.k_scale is None):
        raise ValueError(
            "cache/row kv-quant mismatch (one has int8 scales)"
        )

    def graft(dst, src, fill):
        pad = [(0, 0)] * src.ndim
        pad[3] = (0, S - s_row)
        src = jnp.pad(src, pad, constant_values=fill).astype(dst.dtype)
        start = (0, slot) + (0,) * (src.ndim - 2)
        return jax.lax.dynamic_update_slice(dst, src, start)

    return cache._replace(
        k=graft(cache.k, row.k, 0),
        v=graft(cache.v, row.v, 0),
        k_scale=(None if cache.k_scale is None
                 else graft(cache.k_scale, row.k_scale, 1.0)),
        v_scale=(None if cache.v_scale is None
                 else graft(cache.v_scale, row.v_scale, 1.0)),
    )


def cache_clear_row(cache: KVCache, slot) -> KVCache:
    """Reset row ``slot`` to init_cache values (zeros; scale 1.0) —
    slot recycling after a request drains. Not needed for correctness
    (a retired row's garbage is masked and rows are independent) but
    keeps freed slots bitwise equal to a cold cache, so an engine
    restart and a long-running engine see identical state. Same
    jit/donate discipline as :func:`cache_insert_row`."""
    def wipe(dst, fill):
        shape = dst.shape[:1] + (1,) + dst.shape[2:]
        src = jnp.full(shape, fill, dst.dtype)
        start = (0, slot) + (0,) * (dst.ndim - 2)
        return jax.lax.dynamic_update_slice(dst, src, start)

    return cache._replace(
        k=wipe(cache.k, 0),
        v=wipe(cache.v, 0),
        k_scale=(None if cache.k_scale is None
                 else wipe(cache.k_scale, 1.0)),
        v_scale=(None if cache.v_scale is None
                 else wipe(cache.v_scale, 1.0)),
    )


def decode_step_slots(
    params: dict, cache: KVCache, st: SlotState, cfg: ModelConfig
) -> tuple[jax.Array, KVCache]:
    """One greedy-decode forward where EVERY row sits at its own
    position ``st.pos`` — the mixed-batch analog of :func:`decode_step`
    (which advances the whole batch at one shared ``cache.length``).
    K/V writes are per-row scatters at (row, st.pos[row]); attention
    masks each row to its own real span (prompt ∪ generated, the ragged
    rule with per-row ``prompt_slots``); RoPE positions are gapless per
    row. → (logits (b, vocab) f32, cache with every row's slot
    written). The cache's scalar ``length``/batch-wide metadata are
    ignored — SlotState IS the position authority here."""
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    b = st.tok.shape[0]
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = (
        st.prompt_lengths + (st.pos - st.prompt_slots)
    )[:, None]                                               # (b, 1)
    limits = (st.pos + 1)[:, None]                           # (b, 1)
    b_idx = jnp.arange(b)[:, None]
    kv_idx = jnp.arange(kv)[None, :]
    x = params["embed"][st.tok][:, None, :]                  # (b, 1, d)

    def block(carry, xs):
        x, (k_all, v_all, ks_all, vs_all) = carry
        layer, li = xs
        y = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (y @ _w(layer["wq"], cfg.dtype)).reshape(b, 1, h, hd)
        q = q.transpose(0, 2, 1, 3)
        k = (y @ _w(layer["wk"], cfg.dtype)).reshape(b, 1, kv, hd)
        k = k.transpose(0, 2, 1, 3)
        v = (y @ _w(layer["wv"], cfg.dtype)).reshape(b, 1, kv, hd)
        v = v.transpose(0, 2, 1, 3)
        q = apply_rope(q, cos, sin, positions=positions)
        k = apply_rope(k, cos, sin, positions=positions)
        k1, v1 = k[:, :, 0, :], v[:, :, 0, :]                # (b, kv, hd)
        if ks_all is not None:
            k1, k_sc = _quantize_kv(k1)
            v1, v_sc = _quantize_kv(v1)
            ks_all = ks_all.at[li, b_idx, kv_idx, st.pos[:, None]].set(k_sc)
            vs_all = vs_all.at[li, b_idx, kv_idx, st.pos[:, None]].set(v_sc)
        k1 = k1.astype(k_all.dtype)
        v1 = v1.astype(v_all.dtype)
        k_all = k_all.at[li, b_idx, kv_idx, st.pos[:, None]].set(k1)
        v_all = v_all.at[li, b_idx, kv_idx, st.pos[:, None]].set(v1)
        k_cache = jax.lax.dynamic_index_in_dim(k_all, li, 0, keepdims=False)
        v_cache = jax.lax.dynamic_index_in_dim(v_all, li, 0, keepdims=False)
        k_scale = v_scale = None
        if ks_all is not None:
            k_scale = jax.lax.dynamic_index_in_dim(
                ks_all, li, 0, keepdims=False)
            v_scale = jax.lax.dynamic_index_in_dim(
                vs_all, li, 0, keepdims=False)
        attn = _attend_cache(cfg, q, k_cache, v_cache, limits,
                             st.prompt_lengths, st.prompt_slots,
                             k_scale=k_scale, v_scale=v_scale)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, 1, h * hd)
        x = x + attn @ _w(layer["wo"], cfg.dtype)
        return (_mlp(cfg, x, layer), (k_all, v_all, ks_all, vs_all)), None

    n_layers = cache.k.shape[0]
    (x, (k_new, v_new, ks_new, vs_new)), _ = jax.lax.scan(
        block,
        (x, (cache.k, cache.v, cache.k_scale, cache.v_scale)),
        (params["layers"], jnp.arange(n_layers, dtype=jnp.int32)),
    )
    x = rms_norm(x[:, 0], params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    return logits, cache._replace(
        k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new,
    )


def decode_segment_slots(
    params: dict, cache: KVCache, st: SlotState, cfg: ModelConfig,
    steps: int, *, eos_id: int | None = None, pad_id: int = 0,
) -> tuple[jax.Array, SlotState, KVCache]:
    """``steps`` greedy :func:`decode_step_slots` steps — the
    continuous-batching analog of :func:`decode_segment`: the host runs
    one segment per K steps, reads back ``remaining``, drains finished
    slots and inserts queued requests between segments. A live row
    (remaining > 0) emits its greedy token, advances ``pos``, and burns
    one unit of budget (EOS zeroes the rest); a dead row emits
    ``pad_id`` and freezes. Token emission matches
    :func:`decode_segment` exactly — liveness decides when a row STOPS,
    never what it emits. → (emitted (batch, steps) int32, state,
    cache)."""

    def step(carry, _):
        cache, st = carry
        live = st.remaining > 0
        logits, cache = decode_step_slots(params, cache, st, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        emitted = jnp.where(live, nxt, pad_id)
        rem = jnp.where(live, st.remaining - 1, 0)
        if eos_id is not None:
            rem = jnp.where(live & (nxt == eos_id), 0, rem)
        st = st._replace(
            tok=jnp.where(live, nxt, st.tok),
            pos=jnp.where(live, st.pos + 1, st.pos),
            remaining=rem,
        )
        return (cache, st), emitted

    (cache, st), toks = jax.lax.scan(
        step, (cache, st), None, length=steps
    )
    return toks.T, st, cache


def _verify_accept(
    greedy: jax.Array, drafts: jax.Array, st: SlotState,
    *, eos_id: int | None, pad_id: int,
) -> tuple[jax.Array, SlotState]:
    """Ragged per-row draft acceptance — the batched form of the
    ``_spec_loop`` rule (models/speculative.py): row i keeps the longest
    prefix of ``drafts[i]`` the target's ``greedy[i]`` agrees with, plus
    one correction token, clipped to the row's remaining budget and to
    the first emitted EOS. The emitted tokens are BY CONSTRUCTION the
    target's own greedy choices at valid context (matched prefix ⇒ the
    window context equals the sequential context), so acceptance decides
    how many tokens come out of a round, never which ones. Dead rows
    (remaining == 0) emit ``pad_id`` and freeze — the segment liveness
    rule, unchanged. → (emitted (b, k+1) int32, advanced state); the
    cache "rollback" is the position arithmetic itself: ``pos`` advances
    by the accepted length only, and the rejected window slots above it
    are masked until the next round's window rewrites them."""
    b, c = greedy.shape
    k = c - 1
    off = jnp.arange(c, dtype=jnp.int32)
    matches = (drafts == greedy[:, :k]).astype(jnp.int32)
    j = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)        # (b,) 0..k
    n_acc = jnp.minimum(j + 1, st.remaining)                 # budget clip
    if eos_id is not None:
        is_eos = greedy == eos_id
        eos_idx = jnp.min(
            jnp.where(is_eos, off[None, :], c), axis=1
        )                                                    # (b,) c = none
        n_emit = jnp.minimum(n_acc, eos_idx + 1)
        hit_eos = eos_idx < n_acc          # EOS inside the emitted prefix
        rem = jnp.where(hit_eos, 0, st.remaining - n_emit)
    else:
        n_emit = n_acc
        rem = st.remaining - n_emit
    emitted = jnp.where(off[None, :] < n_emit[:, None], greedy, pad_id)
    last = jnp.take_along_axis(
        greedy, jnp.clip(n_emit - 1, 0, k)[:, None], axis=1
    )[:, 0]
    return emitted, st._replace(
        tok=jnp.where(n_emit > 0, last, st.tok),
        pos=st.pos + n_emit,
        remaining=rem,
    )


def decode_verify_slots(
    params: dict, cache: KVCache, st: SlotState, drafts: jax.Array,
    cfg: ModelConfig, *, eos_id: int | None = None, pad_id: int = 0,
) -> tuple[jax.Array, SlotState, KVCache]:
    """ONE speculative verify round over the slot engine's mixed batch:
    every row runs the target once over its ``draft_k+1``-token window
    ``[st.tok[i], drafts[i]]`` at slots ``pos..pos+k`` (per-row gapless
    RoPE positions, per-row causal limits — :func:`decode_step_slots`
    widened from c=1 to c=k+1), then accepts per :func:`_verify_accept`.
    K/V for the whole window land in the cache before attention reads
    them, so window row i attends exactly the sequential context when
    rows 0..i-1 matched; rejected slots above the accepted position stay
    masked (``limits``) and the NEXT round's window — which always
    starts at or below them — rewrites them before they can ever be
    attended. The shape is fixed at (slots, draft_k+1) whatever the
    acceptance pattern, so the retrace sentinel sees one program per
    (engine, draft_k) signature. → (emitted (b, k+1) int32 padded with
    ``pad_id``, state advanced by each row's accepted length, cache);
    the host reads each row's emission count off the ``pos`` delta,
    exactly the segment contract."""
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    b, k = drafts.shape
    c = k + 1
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    off = jnp.arange(c, dtype=jnp.int32)
    positions = (
        st.prompt_lengths + (st.pos - st.prompt_slots)
    )[:, None] + off[None, :]                                # (b, c)
    limits = (st.pos + 1)[:, None] + off[None, :]            # (b, c)
    b_idx = jnp.arange(b)[:, None, None]
    kv_idx = jnp.arange(kv)[None, :, None]
    # (b, 1, c): broadcasts with b_idx/kv_idx to one (b, kv, c) scatter;
    # a drained row's frozen window may poke past max_seq — those
    # scatters drop (the dense analog of dead rows writing the sink)
    slot_idx = (st.pos[:, None] + off[None, :])[:, None, :]
    chunk = jnp.concatenate([st.tok[:, None], drafts], axis=1)
    x = params["embed"][chunk]                               # (b, c, d)

    def block(carry, xs):
        x, (k_all, v_all, ks_all, vs_all) = carry
        layer, li = xs
        y = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (y @ _w(layer["wq"], cfg.dtype)).reshape(b, c, h, hd)
        q = q.transpose(0, 2, 1, 3)
        kc = (y @ _w(layer["wk"], cfg.dtype)).reshape(b, c, kv, hd)
        kc = kc.transpose(0, 2, 1, 3)
        vc = (y @ _w(layer["wv"], cfg.dtype)).reshape(b, c, kv, hd)
        vc = vc.transpose(0, 2, 1, 3)                        # (b, kv, c, hd)
        q = apply_rope(q, cos, sin, positions=positions)
        kc = apply_rope(kc, cos, sin, positions=positions)
        if ks_all is not None:
            kc, k_sc = _quantize_kv(kc)
            vc, v_sc = _quantize_kv(vc)
            ks_all = ks_all.at[li, b_idx, kv_idx, slot_idx].set(
                k_sc, mode="drop")
            vs_all = vs_all.at[li, b_idx, kv_idx, slot_idx].set(
                v_sc, mode="drop")
        kc = kc.astype(k_all.dtype)
        vc = vc.astype(v_all.dtype)
        k_all = k_all.at[li, b_idx, kv_idx, slot_idx].set(kc, mode="drop")
        v_all = v_all.at[li, b_idx, kv_idx, slot_idx].set(vc, mode="drop")
        k_cache = jax.lax.dynamic_index_in_dim(k_all, li, 0, keepdims=False)
        v_cache = jax.lax.dynamic_index_in_dim(v_all, li, 0, keepdims=False)
        k_scale = v_scale = None
        if ks_all is not None:
            k_scale = jax.lax.dynamic_index_in_dim(
                ks_all, li, 0, keepdims=False)
            v_scale = jax.lax.dynamic_index_in_dim(
                vs_all, li, 0, keepdims=False)
        attn = _attend_cache(cfg, q, k_cache, v_cache, limits,
                             st.prompt_lengths, st.prompt_slots,
                             k_scale=k_scale, v_scale=v_scale)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, c, h * hd)
        x = x + attn @ _w(layer["wo"], cfg.dtype)
        return (_mlp(cfg, x, layer), (k_all, v_all, ks_all, vs_all)), None

    n_layers = cache.k.shape[0]
    (x, (k_new, v_new, ks_new, vs_new)), _ = jax.lax.scan(
        block,
        (x, (cache.k, cache.v, cache.k_scale, cache.v_scale)),
        (params["layers"], jnp.arange(n_layers, dtype=jnp.int32)),
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # (b, c)
    emitted, st = _verify_accept(
        greedy, drafts, st, eos_id=eos_id, pad_id=pad_id,
    )
    return emitted, st, cache._replace(
        k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new,
    )


# ---------------------------------------------------------------------------
# paged KV cache: a fixed page pool + per-slot page tables
# ---------------------------------------------------------------------------


class PagedKVCache(NamedTuple):
    """A fixed POOL of KV pages shared by every slot: k/v are
    (n_layers, n_pages + 1, kv_heads, page_size, head_dim) — one
    *logical* page spans all layers and covers ``page_size`` contiguous
    cache positions of ONE row. Physical page 0 is a reserved write
    SINK: retired rows keep scattering into it (static shapes — the
    paged analog of a dead dense row re-writing its own frozen slot)
    and unallocated page-table entries point at it, so allocation never
    happens inside a compiled program. Usable pages are 1..n_pages; a
    host-side free list (serve/pages.py) owns which of those are live.

    Unlike :class:`KVCache` there is no position metadata here — the
    pool is pure storage. WHERE a row's positions live is the page
    table, a (slots, max_pages) int32 array the HOST owns and passes
    into every paged program (position p of row i lives at page
    table[i, p // page_size], offset p % page_size); WHEN a row stops
    is :class:`SlotState`, unchanged. Attention gathers each row's
    pages back into a (max_pages * page_size)-wide virtual dense row
    and runs the exact ``_attend_cache`` the dense engine runs — with
    ``max_pages * page_size == max_seq`` the compiled attention is the
    same shape, same reduction order, so paged greedy decode is
    token-identical to the dense slot engine (masked slots read
    finite garbage at weight exactly 0.0, the ragged-batch argument).

    int8 variant (kv_quant): k/v hold int8 and k_scale/v_scale
    (n_layers, n_pages + 1, kv_heads, page_size) hold the symmetric
    per-position f32 dequant scales, exactly the dense layout paged."""

    k: jax.Array
    v: jax.Array
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None


def page_bytes(cfg: ModelConfig, page_size: int,
               kv_quant: bool = False) -> int:
    """Device bytes of ONE logical page (all layers, k+v, scales
    included) — the host-side pool-sizing unit behind
    ``SERVE_KV_POOL_MB`` (pool_bytes // page_bytes = usable pages)."""
    per_pos = cfg.n_layers * cfg.n_kv_heads * cfg.head_dim
    if kv_quant:
        scale = cfg.n_layers * cfg.n_kv_heads * 4       # f32 per position
        return page_size * 2 * (per_pos + scale)        # int8 k/v
    itemsize = jnp.dtype(cfg.dtype).itemsize
    return page_size * 2 * per_pos * itemsize


def init_paged_pool(
    cfg: ModelConfig, num_pages: int, page_size: int,
    kv_quant: bool = False,
) -> PagedKVCache:
    """Allocate a cold pool of ``num_pages`` usable pages (+ the page-0
    sink). Cold pages are bitwise what :func:`init_cache` rows are
    (zeros; scale 1.0), and the free path wipes pages back to this, so
    a recycled page is indistinguishable from a fresh pool's."""
    if num_pages < 1:
        raise ValueError(f"num_pages must be >= 1, got {num_pages}")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    shape = (cfg.n_layers, num_pages + 1, cfg.n_kv_heads, page_size,
             cfg.head_dim)
    if kv_quant:
        return PagedKVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.ones(shape[:-1], jnp.float32),
            v_scale=jnp.ones(shape[:-1], jnp.float32),
        )
    return PagedKVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
    )


def _to_pages(a: jax.Array, skip_pages: int, ps: int) -> jax.Array:
    """A batch-1 dense row segment → page-major: (L, 1, kv, n*ps[, hd])
    → (L, n - skip_pages, kv, ps[, hd]), dropping the first
    ``skip_pages`` pages (positions already resident in the pool)."""
    L, _, kv = a.shape[:3]
    n = a.shape[3] // ps
    a = a[:, 0]                                     # (L, kv, n*ps[, hd])
    a = a.reshape((L, kv, n, ps) + a.shape[3:])
    if a.ndim == 5:
        a = a.transpose(0, 2, 1, 3, 4)
    else:
        a = a.transpose(0, 2, 1, 3)
    return a[:, skip_pages:]


def paged_insert_row(
    pool: PagedKVCache, row: KVCache, pages: jax.Array, skip: int = 0,
) -> PagedKVCache:
    """The paged admission primitive (:func:`cache_insert_row`'s
    analog): scatter a freshly prefilled batch-1 row cache of width
    ``n * page_size`` into pool pages ``pages`` ((n,) int32, page i
    receiving positions [i*page_size, (i+1)*page_size)). ``skip``
    (STATIC, page-aligned) marks a warm-prefix admission: the first
    ``skip`` positions already live in shared pinned pages — their
    table entries point at the store's pages and nothing is written,
    which is what makes a warm hit zero-copy (the dense path
    byte-copies the prefix through the resume base instead). ``pages``
    may be traced: jit once per (width, skip) pair, donate the pool."""
    ps = pool.k.shape[3]
    s_row = row.k.shape[3]
    n = pages.shape[0]
    if row.k.shape[1] != 1:
        raise ValueError(f"row cache must be batch-1, got {row.k.shape[1]}")
    if s_row != n * ps:
        raise ValueError(
            f"row width {s_row} != {n} pages x page_size {ps}"
        )
    if skip % ps or not 0 <= skip < s_row:
        raise ValueError(
            f"skip {skip} must be page-aligned in [0, {s_row})"
        )
    if (pool.k_scale is None) != (row.k_scale is None):
        raise ValueError(
            "pool/row kv-quant mismatch (one has int8 scales)"
        )
    sp = skip // ps
    dst = pages[sp:]

    def scatter(pool_a, row_a):
        if pool_a is None:
            return None
        src = _to_pages(row_a, sp, ps).astype(pool_a.dtype)
        return pool_a.at[:, dst].set(src)

    return PagedKVCache(
        k=scatter(pool.k, row.k),
        v=scatter(pool.v, row.v),
        k_scale=scatter(pool.k_scale, row.k_scale),
        v_scale=scatter(pool.v_scale, row.v_scale),
    )


def paged_clear_pages(pool: PagedKVCache, pages: jax.Array) -> PagedKVCache:
    """Wipe freed pages back to init values (zeros; scale 1.0) — the
    paged :func:`cache_clear_row`. ``pages`` is (n,) int32 and may be
    PADDED with any out-of-range sentinel (>= n_pages + 1): padded
    entries are dropped by the scatter, so the host jits ONE program at
    a fixed n and clears any smaller set through it."""
    n = pages.shape[0]

    def wipe(pool_a, fill):
        if pool_a is None:
            return None
        shape = (pool_a.shape[0], n) + pool_a.shape[2:]
        return pool_a.at[:, pages].set(
            jnp.full(shape, fill, pool_a.dtype), mode="drop",
        )

    return PagedKVCache(
        k=wipe(pool.k, 0),
        v=wipe(pool.v, 0),
        k_scale=wipe(pool.k_scale, 1.0),
        v_scale=wipe(pool.v_scale, 1.0),
    )


def gather_pages(pool: PagedKVCache, pages: jax.Array) -> KVCache:
    """Pages → a contiguous batch-1 dense row cache of width
    ``n * page_size`` (uniform: every slot < length is real). The warm-
    prefix bridge: the engine gathers a stored prefix's pinned pages
    into the resume base :func:`prefill_resume` consumes — the bytes
    are exactly what :func:`paged_insert_row` scattered, so the resume
    computation matches the dense byte-copy path bitwise."""
    ps = pool.k.shape[3]
    n = pages.shape[0]

    def dense(pool_a):
        if pool_a is None:
            return None
        a = pool_a[:, pages]                 # (L, n, kv, ps[, hd])
        if a.ndim == 5:
            a = a.transpose(0, 2, 1, 3, 4)
        else:
            a = a.transpose(0, 2, 1, 3)
        a = a.reshape(a.shape[:2] + (n * ps,) + a.shape[4:])
        return a[:, None]                    # (L, 1, kv, n*ps[, hd])

    return KVCache(
        k=dense(pool.k), v=dense(pool.v),
        length=jnp.asarray(n * ps, jnp.int32),
        k_scale=dense(pool.k_scale), v_scale=dense(pool.v_scale),
    )


def decode_step_paged(
    params: dict, pool: PagedKVCache, table: jax.Array, st: SlotState,
    cfg: ModelConfig,
) -> tuple[jax.Array, PagedKVCache]:
    """:func:`decode_step_slots` through a page table: every row writes
    this step's K/V at (table[row, pos // ps], pos % ps) and attends
    its pages gathered back into a (max_pages * ps)-wide virtual dense
    row — same masks, same ragged metadata, same einsums, so with
    ``max_pages * ps == max_seq`` the logits are bitwise the dense
    engine's. The host guarantees every LIVE row's table covers slot
    ``pos`` before calling (pre-segment top-up — no in-program
    allocation); retired rows write the page-0 sink, whose garbage no
    live row's gather can weight above exactly 0.0. → (logits
    (b, vocab) f32, pool with every row's position written)."""
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    b, mp = table.shape
    ps = pool.k.shape[3]
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = (
        st.prompt_lengths + (st.pos - st.prompt_slots)
    )[:, None]                                               # (b, 1)
    limits = (st.pos + 1)[:, None]                           # (b, 1)
    page = jnp.take_along_axis(table, (st.pos // ps)[:, None], axis=1)
    off = (st.pos % ps)[:, None]                             # (b, 1)
    kv_idx = jnp.arange(kv)[None, :]
    x = params["embed"][st.tok][:, None, :]                  # (b, 1, d)

    def virtual(pool_l):
        """One layer's pages → the (b, kv, mp*ps[, hd]) virtual dense
        cache every row's attention reads."""
        a = pool_l[table]                    # (b, mp, kv, ps[, hd])
        if a.ndim == 5:
            a = a.transpose(0, 2, 1, 3, 4)
        else:
            a = a.transpose(0, 2, 1, 3)
        return a.reshape(a.shape[:2] + (mp * ps,) + a.shape[4:])

    def block(carry, xs):
        x, (k_all, v_all, ks_all, vs_all) = carry
        layer, li = xs
        y = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (y @ _w(layer["wq"], cfg.dtype)).reshape(b, 1, h, hd)
        q = q.transpose(0, 2, 1, 3)
        k = (y @ _w(layer["wk"], cfg.dtype)).reshape(b, 1, kv, hd)
        k = k.transpose(0, 2, 1, 3)
        v = (y @ _w(layer["wv"], cfg.dtype)).reshape(b, 1, kv, hd)
        v = v.transpose(0, 2, 1, 3)
        q = apply_rope(q, cos, sin, positions=positions)
        k = apply_rope(k, cos, sin, positions=positions)
        k1, v1 = k[:, :, 0, :], v[:, :, 0, :]                # (b, kv, hd)
        if ks_all is not None:
            k1, k_sc = _quantize_kv(k1)
            v1, v_sc = _quantize_kv(v1)
            ks_all = ks_all.at[li, page, kv_idx, off].set(k_sc)
            vs_all = vs_all.at[li, page, kv_idx, off].set(v_sc)
        k1 = k1.astype(k_all.dtype)
        v1 = v1.astype(v_all.dtype)
        # live rows own their pages exclusively, so scatter indices
        # collide only on the sink (dead rows) — never-read, any winner
        k_all = k_all.at[li, page, kv_idx, off].set(k1)
        v_all = v_all.at[li, page, kv_idx, off].set(v1)
        k_l = jax.lax.dynamic_index_in_dim(k_all, li, 0, keepdims=False)
        v_l = jax.lax.dynamic_index_in_dim(v_all, li, 0, keepdims=False)
        k_scale = v_scale = None
        if ks_all is not None:
            k_scale = virtual(jax.lax.dynamic_index_in_dim(
                ks_all, li, 0, keepdims=False))
            v_scale = virtual(jax.lax.dynamic_index_in_dim(
                vs_all, li, 0, keepdims=False))
        attn = _attend_cache(cfg, q, virtual(k_l), virtual(v_l), limits,
                             st.prompt_lengths, st.prompt_slots,
                             k_scale=k_scale, v_scale=v_scale)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, 1, h * hd)
        x = x + attn @ _w(layer["wo"], cfg.dtype)
        return (_mlp(cfg, x, layer), (k_all, v_all, ks_all, vs_all)), None

    n_layers = pool.k.shape[0]
    (x, (k_new, v_new, ks_new, vs_new)), _ = jax.lax.scan(
        block,
        (x, (pool.k, pool.v, pool.k_scale, pool.v_scale)),
        (params["layers"], jnp.arange(n_layers, dtype=jnp.int32)),
    )
    x = rms_norm(x[:, 0], params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    return logits, PagedKVCache(
        k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new,
    )


def decode_segment_paged(
    params: dict, pool: PagedKVCache, table: jax.Array, st: SlotState,
    cfg: ModelConfig, steps: int, *, eos_id: int | None = None,
    pad_id: int = 0,
) -> tuple[jax.Array, SlotState, PagedKVCache]:
    """``steps`` greedy :func:`decode_step_paged` steps — the paged
    :func:`decode_segment_slots`, with IDENTICAL emission logic (live
    rows emit and advance, dead rows emit ``pad_id`` and freeze, EOS
    zeroes the budget), so the two engines are token-identical step for
    step. The table is constant across the segment: the host tops every
    live row's table up to cover ``pos + min(steps, remaining)`` before
    calling (and preempts a row when the pool cannot). → (emitted
    (batch, steps) int32, state, pool)."""

    def step(carry, _):
        pool, st = carry
        live = st.remaining > 0
        logits, pool = decode_step_paged(params, pool, table, st, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        emitted = jnp.where(live, nxt, pad_id)
        rem = jnp.where(live, st.remaining - 1, 0)
        if eos_id is not None:
            rem = jnp.where(live & (nxt == eos_id), 0, rem)
        st = st._replace(
            tok=jnp.where(live, nxt, st.tok),
            pos=jnp.where(live, st.pos + 1, st.pos),
            remaining=rem,
        )
        return (pool, st), emitted

    (pool, st), toks = jax.lax.scan(
        step, (pool, st), None, length=steps
    )
    return toks.T, st, pool


def decode_verify_paged(
    params: dict, pool: PagedKVCache, table: jax.Array, st: SlotState,
    drafts: jax.Array, cfg: ModelConfig, *, eos_id: int | None = None,
    pad_id: int = 0,
) -> tuple[jax.Array, SlotState, PagedKVCache]:
    """:func:`decode_verify_slots` through a page table: each row's
    ``draft_k+1`` window positions ``pos..pos+k`` scatter at
    (table[row, p // ps], p % ps) and attention reads the row's pages
    gathered into the (max_pages * ps)-wide virtual dense row — same
    masks, same acceptance, so paged speculative decode stays bitwise
    the dense engine's. The host guarantees every live row's table
    covers ``pos + min(k+1, remaining) - 1`` before calling (the
    emittable extent — the pre-round top-up); window positions past a
    row's budget may fall on unallocated entries and land in the page-0
    sink, which is harmless: acceptance can never emit past
    ``remaining``, so a greedy argmax polluted by a sink read is always
    clipped out of both the emission and the EOS/match tests. The
    host-side rollback is a page-table TRUNCATE: after readback the
    engine returns pages past each row's accepted position to the pool
    (serve/pages.py refcount discipline) — the paged analog of the
    dense position rewind. → (emitted (b, k+1), state, pool)."""
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    b, mp = table.shape
    ps = pool.k.shape[3]
    k = drafts.shape[1]
    c = k + 1
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    off = jnp.arange(c, dtype=jnp.int32)
    positions = (
        st.prompt_lengths + (st.pos - st.prompt_slots)
    )[:, None] + off[None, :]                                # (b, c)
    limits = (st.pos + 1)[:, None] + off[None, :]            # (b, c)
    p = st.pos[:, None] + off[None, :]                       # (b, c)
    # gather clamps a frozen row's out-of-range page index; its write
    # then collides on whatever page that is only with itself or the
    # sink — never-read, any winner (the dead-row rule)
    page_idx = jnp.take_along_axis(table, p // ps, axis=1)[:, None, :]
    off_idx = (p % ps)[:, None, :]                           # (b, 1, c)
    kv_idx = jnp.arange(kv)[None, :, None]
    chunk = jnp.concatenate([st.tok[:, None], drafts], axis=1)
    x = params["embed"][chunk]                               # (b, c, d)

    def virtual(pool_l):
        a = pool_l[table]                    # (b, mp, kv, ps[, hd])
        if a.ndim == 5:
            a = a.transpose(0, 2, 1, 3, 4)
        else:
            a = a.transpose(0, 2, 1, 3)
        return a.reshape(a.shape[:2] + (mp * ps,) + a.shape[4:])

    def block(carry, xs):
        x, (k_all, v_all, ks_all, vs_all) = carry
        layer, li = xs
        y = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (y @ _w(layer["wq"], cfg.dtype)).reshape(b, c, h, hd)
        q = q.transpose(0, 2, 1, 3)
        kc = (y @ _w(layer["wk"], cfg.dtype)).reshape(b, c, kv, hd)
        kc = kc.transpose(0, 2, 1, 3)
        vc = (y @ _w(layer["wv"], cfg.dtype)).reshape(b, c, kv, hd)
        vc = vc.transpose(0, 2, 1, 3)                        # (b, kv, c, hd)
        q = apply_rope(q, cos, sin, positions=positions)
        kc = apply_rope(kc, cos, sin, positions=positions)
        if ks_all is not None:
            kc, k_sc = _quantize_kv(kc)
            vc, v_sc = _quantize_kv(vc)
            ks_all = ks_all.at[li, page_idx, kv_idx, off_idx].set(k_sc)
            vs_all = vs_all.at[li, page_idx, kv_idx, off_idx].set(v_sc)
        kc = kc.astype(k_all.dtype)
        vc = vc.astype(v_all.dtype)
        k_all = k_all.at[li, page_idx, kv_idx, off_idx].set(kc)
        v_all = v_all.at[li, page_idx, kv_idx, off_idx].set(vc)
        k_l = jax.lax.dynamic_index_in_dim(k_all, li, 0, keepdims=False)
        v_l = jax.lax.dynamic_index_in_dim(v_all, li, 0, keepdims=False)
        k_scale = v_scale = None
        if ks_all is not None:
            k_scale = virtual(jax.lax.dynamic_index_in_dim(
                ks_all, li, 0, keepdims=False))
            v_scale = virtual(jax.lax.dynamic_index_in_dim(
                vs_all, li, 0, keepdims=False))
        attn = _attend_cache(cfg, q, virtual(k_l), virtual(v_l), limits,
                             st.prompt_lengths, st.prompt_slots,
                             k_scale=k_scale, v_scale=v_scale)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, c, h * hd)
        x = x + attn @ _w(layer["wo"], cfg.dtype)
        return (_mlp(cfg, x, layer), (k_all, v_all, ks_all, vs_all)), None

    n_layers = pool.k.shape[0]
    (x, (k_new, v_new, ks_new, vs_new)), _ = jax.lax.scan(
        block,
        (x, (pool.k, pool.v, pool.k_scale, pool.v_scale)),
        (params["layers"], jnp.arange(n_layers, dtype=jnp.int32)),
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # (b, c)
    emitted, st = _verify_accept(
        greedy, drafts, st, eos_id=eos_id, pad_id=pad_id,
    )
    return emitted, st, PagedKVCache(
        k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new,
    )
