"""Hugging Face checkpoint → this framework's param pytrees.

Makes the serving/training stack consumable with real pretrained weights:
``transformers`` **Llama** (dense) and **Mixtral** (MoE) checkpoints —
the de-facto interchange formats — map 1:1 onto models/llama.py's and
models/moe.py's pytrees. HF ``nn.Linear`` stores ``(out_features,
in_features)``, ours are ``(in, out)``, so every matmul weight
transposes; per-layer tensors stack on a leading axis for the
``lax.scan`` block; Mixtral expert weights additionally stack on an
expert axis (HF's w1/w3/w2 are gate/up/down). RoPE conventions agree
(rotate-half; HF duplicates the (seq, head_dim/2) table across both
halves, ops/norms.py applies the halves directly). Anything the in-tree
models cannot represent — rope_scaling, attention bias, sliding-window
attention — is refused loudly: silently wrong logits are worse than a
failed load. Verified logit-for-logit against the HF reference forwards
(tests/test_convert_hf.py).

Loading never touches the network: pass a live ``transformers`` model, a
state dict, or a LOCAL checkpoint directory (``local_files_only=True`` —
the TPU images are air-gapped by design, weights are pre-staged the same
way the packer images pre-stage container images).

The reference provisioner has no model zoo (SURVEY §0); this belongs to
the in-tree stack's interop surface.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import jax.numpy as jnp
import numpy as np

from tpu_kubernetes.models.llama import ModelConfig
from tpu_kubernetes.models.moe import MoEConfig


class ConvertError(Exception):
    pass


def _shared_config_fields(hf_config: Any, dtype: Any) -> dict:
    """The transformer-backbone fields both families share, with the
    representability guards applied once (silently wrong logits are worse
    than a loud failure)."""
    if getattr(hf_config, "rope_scaling", None):
        raise ConvertError(
            "rope_scaling is set (Llama 3.1+ style NTK/linear scaling); "
            "the in-tree models implement plain RoPE only"
        )
    if getattr(hf_config, "attention_bias", False):
        raise ConvertError(
            "attention_bias=True checkpoints carry q/k/v/o bias tensors "
            "the in-tree models have no slot for"
        )
    window = getattr(hf_config, "sliding_window", None)
    if window and window < hf_config.max_position_embeddings:
        raise ConvertError(
            f"sliding_window={window} < max_position_embeddings="
            f"{hf_config.max_position_embeddings}: the in-tree models are "
            "full-causal, logits would silently diverge past the window"
        )
    return dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(
            hf_config, "num_key_value_heads", hf_config.num_attention_heads
        ),
        d_ff=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        norm_eps=hf_config.rms_norm_eps,
        dtype=dtype,
    )


def config_from_hf(hf_config: Any, dtype: Any = jnp.bfloat16) -> ModelConfig:
    """transformers LlamaConfig → ModelConfig (shape fields only)."""
    return ModelConfig(**_shared_config_fields(hf_config, dtype))


def moe_config_from_hf(hf_config: Any, dtype: Any = jnp.bfloat16) -> MoEConfig:
    """transformers MixtralConfig → MoEConfig. Combine-weight semantics
    agree exactly (HF softmaxes the top-k logits; this model softmaxes all
    and renormalizes over the selected — the common denominator cancels).
    HF Mixtral has no capacity concept, so the converted config is
    DROPLESS (capacity_factor = n_experts) — the regime in which logits
    match the HF reference exactly; training users wanting capacity
    batching lower it explicitly."""
    shared = _shared_config_fields(hf_config, dtype)
    return MoEConfig(
        **shared,
        n_experts=hf_config.num_local_experts,
        experts_per_token=hf_config.num_experts_per_tok,
        capacity_factor=float(hf_config.num_local_experts),
    )


def _np(t) -> np.ndarray:
    """torch tensor | ndarray → float32 ndarray (host)."""
    if hasattr(t, "detach"):
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def _getter(state_dict: Mapping[str, Any]) -> Callable[[str], np.ndarray]:
    def get(key: str) -> np.ndarray:
        if key not in state_dict:
            raise ConvertError(f"checkpoint is missing {key!r}")
        return _np(state_dict[key])

    return get


def _backbone_params(
    state_dict: Mapping[str, Any], cfg: ModelConfig
) -> tuple[dict, dict, Callable]:
    """The attention/norm/embedding weights both families share →
    (top-level params, layer dict to extend, the getter). Handles the
    tied-embedding fallback (no lm_head.weight → embed.T)."""
    get = _getter(state_dict)

    def stack(fmt: str, transpose: bool) -> jnp.ndarray:
        rows = [get(fmt.format(i)) for i in range(cfg.n_layers)]
        if transpose:
            rows = [r.T for r in rows]  # (out, in) → (in, out)
        return jnp.asarray(np.stack(rows), cfg.dtype)

    embed = get("model.embed_tokens.weight")
    lm_head = (
        get("lm_head.weight").T if "lm_head.weight" in state_dict
        else embed.T  # tie_word_embeddings
    )
    layers = {
        "attn_norm": stack(
            "model.layers.{}.input_layernorm.weight", transpose=False
        ),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight", True),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight", True),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight", True),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight", True),
        "mlp_norm": stack(
            "model.layers.{}.post_attention_layernorm.weight", False
        ),
    }
    top = {
        "embed": jnp.asarray(embed, cfg.dtype),
        "final_norm": jnp.asarray(get("model.norm.weight"), cfg.dtype),
        "lm_head": jnp.asarray(lm_head, cfg.dtype),
    }
    return top, layers, get


def params_from_hf_state_dict(
    state_dict: Mapping[str, Any], cfg: ModelConfig
) -> dict:
    """HF Llama ``state_dict`` → models/llama.py param pytree in
    ``cfg.dtype``. Raises ConvertError on missing keys (a truncated or
    non-Llama checkpoint)."""
    top, layers, get = _backbone_params(state_dict, cfg)

    def stack(fmt: str) -> jnp.ndarray:
        return jnp.asarray(np.stack([
            get(fmt.format(i)).T for i in range(cfg.n_layers)
        ]), cfg.dtype)

    layers.update({
        "w_gate": stack("model.layers.{}.mlp.gate_proj.weight"),
        "w_up": stack("model.layers.{}.mlp.up_proj.weight"),
        "w_down": stack("model.layers.{}.mlp.down_proj.weight"),
    })
    return {**top, "layers": layers}


def params_from_hf_mixtral_state_dict(
    state_dict: Mapping[str, Any], cfg: MoEConfig
) -> dict:
    """HF Mixtral ``state_dict`` → models/moe.py param pytree. Expert
    weights stack (layer, expert, …); HF's w1/w3/w2 are gate/up/down."""
    top, layers, get = _backbone_params(state_dict, cfg)

    def stack_experts(which: str) -> jnp.ndarray:
        per_layer = [
            np.stack([
                get(
                    f"model.layers.{i}.block_sparse_moe.experts.{e}."
                    f"{which}.weight"
                ).T
                for e in range(cfg.n_experts)
            ])
            for i in range(cfg.n_layers)
        ]
        return jnp.asarray(np.stack(per_layer), cfg.dtype)

    layers.update({
        # router stays float32 (models/moe.py: routing is
        # precision-sensitive)
        "w_router": jnp.asarray(np.stack([
            get(f"model.layers.{i}.block_sparse_moe.gate.weight").T
            for i in range(cfg.n_layers)
        ]), jnp.float32),
        "w_gate": stack_experts("w1"),
        "w_up": stack_experts("w3"),
        "w_down": stack_experts("w2"),
    })
    return {**top, "layers": layers}


def _resolve_model(model_or_path: Any):
    """Path-or-model → live transformers model (local files only)."""
    if isinstance(model_or_path, (str, bytes)) or hasattr(
        model_or_path, "__fspath__"
    ):
        import torch  # noqa: F401 — transformers needs it for weights
        from transformers import AutoModelForCausalLM

        return AutoModelForCausalLM.from_pretrained(
            model_or_path, local_files_only=True
        )
    return model_or_path


def load_hf(
    model_or_path: Any, dtype: Any = jnp.bfloat16
) -> tuple[dict, ModelConfig]:
    """One-call interop: a live ``transformers`` model OR a local
    checkpoint path → (params, cfg), dispatched on the config's
    ``model_type`` (llama → dense, mixtral → MoE). Network access is
    never attempted."""
    model = _resolve_model(model_or_path)
    kind = getattr(model.config, "model_type", "llama")
    if kind == "mixtral":
        cfg = moe_config_from_hf(model.config, dtype=dtype)
        return params_from_hf_mixtral_state_dict(model.state_dict(), cfg), cfg
    if kind == "llama":
        cfg = config_from_hf(model.config, dtype=dtype)
        return params_from_hf_state_dict(model.state_dict(), cfg), cfg
    raise ConvertError(f"unsupported model_type {kind!r} (llama | mixtral)")


def load_hf_llama(
    model_or_path: Any, dtype: Any = jnp.bfloat16
) -> tuple[dict, ModelConfig]:
    """Dense-only variant of :func:`load_hf` for callers that want the
    type guarantee — rejects MoE checkpoints BEFORE converting any
    weights (a real Mixtral state dict is tens of GB)."""
    model = _resolve_model(model_or_path)
    if getattr(model.config, "model_type", "llama") != "llama":
        raise ConvertError("checkpoint is not a dense Llama — use load_hf")
    return load_hf(model, dtype=dtype)


def export_hf_llama(
    params: dict, cfg: ModelConfig, path: Any = None, torch_dtype: Any = None
):
    """The inverse interop: a DENSE param pytree → a transformers Llama
    model (saved to ``path`` when given) — so anything this framework
    trains or finetunes (e.g. a merged LoRA, train/lora.merge_lora) can
    ride the rest of the ecosystem. Exact inverse of the import mapping;
    round-trip parity is pinned in tests.

    ``torch_dtype`` defaults to the pytree's own weight dtype (a
    bf16-trained model exports bf16 — half the bytes of f32 and the
    ecosystem convention); pass ``torch.float32`` to up-cast."""
    if isinstance(cfg, MoEConfig):
        raise ConvertError("export supports the dense family only")
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    if torch_dtype is None:
        torch_dtype = (
            torch.bfloat16
            if jnp.dtype(params["embed"].dtype) == jnp.bfloat16
            else torch.float32
        )
    hf_config = LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.d_model,
        intermediate_size=cfg.d_ff,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        max_position_embeddings=cfg.max_seq,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.norm_eps,
        attention_bias=False,
        tie_word_embeddings=False,
        torch_dtype=torch_dtype,
    )

    # one bulk device→host transfer per stacked weight, then numpy slicing
    # (not one transfer per layer); f32 is the lossless interchange for
    # both bf16 and f32 sources, cast to torch_dtype at tensor creation
    def host(arr) -> np.ndarray:
        return np.asarray(arr, np.float32)

    def t(arr: np.ndarray) -> Any:
        return torch.tensor(arr).to(torch_dtype)

    layers = {k: host(v) for k, v in params["layers"].items()}
    sd = {
        "model.embed_tokens.weight": t(host(params["embed"])),
        "model.norm.weight": t(host(params["final_norm"])),
        "lm_head.weight": t(host(params["lm_head"]).T.copy()),
    }
    names = {
        "wq": "self_attn.q_proj", "wk": "self_attn.k_proj",
        "wv": "self_attn.v_proj", "wo": "self_attn.o_proj",
        "w_gate": "mlp.gate_proj", "w_up": "mlp.up_proj",
        "w_down": "mlp.down_proj",
    }
    for i in range(cfg.n_layers):
        sd[f"model.layers.{i}.input_layernorm.weight"] = t(
            layers["attn_norm"][i]
        )
        sd[f"model.layers.{i}.post_attention_layernorm.weight"] = t(
            layers["mlp_norm"][i]
        )
        for ours, theirs in names.items():
            sd[f"model.layers.{i}.{theirs}.weight"] = t(
                layers[ours][i].T.copy()
            )

    # build on the meta device so torch never allocates (or random-inits)
    # a throwaway copy of the weights; assign=True adopts our tensors
    try:
        with torch.device("meta"):
            model = LlamaForCausalLM(hf_config)
        model.load_state_dict(sd, assign=True)
        # non-persistent buffers (the rotary inv_freq) are not in the
        # state dict and stayed on meta — rebuild that module for real
        model.model.rotary_emb = type(model.model.rotary_emb)(
            config=hf_config
        )
        if any(b.is_meta for b in model.buffers()):
            raise RuntimeError("meta buffers remain after export")
    except (AttributeError, NotImplementedError, RuntimeError, TypeError):
        # older torch/transformers layouts: pay the full init once
        model = LlamaForCausalLM(hf_config)
        model.load_state_dict(sd)
    model.eval()
    if path is not None:
        model.save_pretrained(str(path))
    return model