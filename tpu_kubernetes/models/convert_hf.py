"""Hugging Face Llama checkpoint → this framework's param pytree.

Makes the serving/training stack consumable with real pretrained weights:
``transformers`` Llama checkpoints (the de-facto interchange format,
plain-RoPE/no-bias variants — anything else is refused loudly) map
1:1 onto models/llama.py's pytree — HF ``nn.Linear`` stores
``(out_features, in_features)``, ours are ``(in, out)``, so every matmul
weight transposes; per-layer tensors stack on a leading axis for the
``lax.scan`` block. RoPE conventions agree (rotate-half; HF duplicates
the (seq, head_dim/2) table across both halves, ops/norms.py applies the
halves directly), verified by the logit-parity test against the HF
reference forward (tests/test_convert_hf.py).

Loading never touches the network: pass a live ``transformers`` model, a
state dict, or a LOCAL checkpoint directory (``local_files_only=True`` —
the TPU images are air-gapped by design, weights are pre-staged the same
way the packer images pre-stage container images).

The reference provisioner has no model zoo (SURVEY §0); this belongs to
the in-tree stack's interop surface.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from tpu_kubernetes.models.llama import ModelConfig


class ConvertError(Exception):
    pass


def config_from_hf(hf_config: Any, dtype: Any = jnp.bfloat16) -> ModelConfig:
    """transformers LlamaConfig → ModelConfig (shape fields only).

    Refuses configs the in-tree model cannot represent — silently wrong
    logits are worse than a loud failure."""
    if getattr(hf_config, "rope_scaling", None):
        raise ConvertError(
            "rope_scaling is set (Llama 3.1+ style NTK/linear scaling); "
            "the in-tree model implements plain RoPE only"
        )
    if getattr(hf_config, "attention_bias", False):
        raise ConvertError(
            "attention_bias=True checkpoints carry q/k/v/o bias tensors "
            "the in-tree model has no slot for"
        )
    return ModelConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(
            hf_config, "num_key_value_heads", hf_config.num_attention_heads
        ),
        d_ff=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        norm_eps=hf_config.rms_norm_eps,
        dtype=dtype,
    )


def _np(t) -> np.ndarray:
    """torch tensor | ndarray → float32 ndarray (host)."""
    if hasattr(t, "detach"):
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def params_from_hf_state_dict(
    state_dict: Mapping[str, Any], cfg: ModelConfig
) -> dict:
    """HF Llama ``state_dict`` → models/llama.py param pytree in
    ``cfg.dtype``. Raises ConvertError on missing keys (a truncated or
    non-Llama checkpoint) — silently wrong weights are worse than a
    loud failure."""
    def get(key: str) -> np.ndarray:
        if key not in state_dict:
            raise ConvertError(f"checkpoint is missing {key!r}")
        return _np(state_dict[key])

    def linear(key: str) -> np.ndarray:
        return get(key).T  # (out, in) → (in, out)

    def stack(fmt: str, transpose: bool) -> jnp.ndarray:
        rows = [
            (linear if transpose else get)(fmt.format(i))
            for i in range(cfg.n_layers)
        ]
        return jnp.asarray(np.stack(rows), cfg.dtype)

    embed = get("model.embed_tokens.weight")
    if "lm_head.weight" in state_dict:
        lm_head = linear("lm_head.weight")
    else:
        lm_head = embed.T  # tie_word_embeddings
    params = {
        "embed": jnp.asarray(embed, cfg.dtype),
        "layers": {
            "attn_norm": stack(
                "model.layers.{}.input_layernorm.weight", transpose=False
            ),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight", True),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight", True),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight", True),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight", True),
            "mlp_norm": stack(
                "model.layers.{}.post_attention_layernorm.weight", False
            ),
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight", True),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight", True),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight", True),
        },
        "final_norm": jnp.asarray(get("model.norm.weight"), cfg.dtype),
        "lm_head": jnp.asarray(lm_head, cfg.dtype),
    }
    return params


def load_hf_llama(
    model_or_path: Any, dtype: Any = jnp.bfloat16
) -> tuple[dict, ModelConfig]:
    """One-call interop: a live ``transformers`` Llama model OR a local
    checkpoint path → (params, cfg). Network access is never attempted."""
    if isinstance(model_or_path, (str, bytes)) or hasattr(
        model_or_path, "__fspath__"
    ):
        import torch  # noqa: F401 — transformers needs it for weights
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            model_or_path, local_files_only=True
        )
    else:
        model = model_or_path
    cfg = config_from_hf(model.config, dtype=dtype)
    return params_from_hf_state_dict(model.state_dict(), cfg), cfg
