"""The in-tree training job (MaxText analog) — what ``kubectl apply`` runs
on a TPU slice the framework provisioned.

Flow (BASELINE.json north star): ``create cluster -p gcp-tpu`` stands up the
slice and bakes the jax.distributed env onto every host
(install_tpu_agent.sh.tpl); this job consumes that contract
(parallel/distributed.py), builds a mesh over every chip in the slice, and
trains the configured Llama over ICI. Step timing is logged so the driver
can measure create→first-train-step latency (the north-star metric).

Env knobs: JOB_MODEL (default llama-7b), JOB_BATCH (global), JOB_SEQ,
JOB_STEPS, JOB_MESH ("data=1,fsdp=16,tensor=1"), JOB_DCN_MESH (multislice:
cross-slice axes, e.g. "data=2" — JOB_MESH then describes the intra-slice
ICI axes), JOB_DATA_PATH (token shards; synthetic data when unset),
JOB_CHECKPOINT_DIR, JOB_CHECKPOINT_EVERY, JOB_EVAL_DATA_PATH +
JOB_EVAL_EVERY/JOB_EVAL_BATCHES (held-out loss/perplexity),
JOB_ACCUM_STEPS (gradient accumulation: microbatches per optimizer step),
JOB_OPTIMIZER ("adamw" | "adafactor" — factored second moment for
HBM-constrained runs, trainer.TrainConfig.optimizer).
"""

from __future__ import annotations

import os
import sys
import time


def log(*args):
    print("[job]", *args, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    # honor an explicit JAX_PLATFORMS even where a sitecustomize re-forces
    # a tunneled TPU platform at import (same stance as bench.py — local
    # CPU smoke runs of the job entrypoint must be possible)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from tpu_kubernetes.parallel import (
        enable_persistent_compile_cache,
        initialize,
    )

    t_start = time.time()
    cache = enable_persistent_compile_cache()
    if cache:
        log(f"compile cache: {cache}")
    denv = initialize()
    log(f"process {denv.process_id}/{denv.num_processes} "
        f"accelerator={denv.accelerator_type} topology={denv.slice_topology}")

    from tpu_kubernetes.models import CONFIGS, param_count
    from tpu_kubernetes.parallel import (
        create_hybrid_mesh,
        create_mesh,
        mesh_shape_for_devices,
    )
    from tpu_kubernetes.train import (
        TrainConfig,
        init_state,
        input_pipeline,
        make_eval_step,
        make_sharded_train_step,
        prefetch,
        synthetic_batches,
    )
    from tpu_kubernetes.train.checkpoint import CheckpointError, latest_step, restore, save

    n = len(jax.devices())
    model = os.environ.get("JOB_MODEL", "llama-7b")
    cfg = CONFIGS[model]
    batch = int(os.environ.get("JOB_BATCH", str(max(4, n))))
    seq = int(os.environ.get("JOB_SEQ", str(cfg.max_seq)))
    steps = int(os.environ.get("JOB_STEPS", "100"))
    mesh_spec = os.environ.get("JOB_MESH", "")
    dcn_spec = os.environ.get("JOB_DCN_MESH", "")
    data_path = os.environ.get("JOB_DATA_PATH", "")
    ckpt_dir = os.environ.get("JOB_CHECKPOINT_DIR", "")
    ckpt_every = int(os.environ.get("JOB_CHECKPOINT_EVERY", "50"))
    eval_path = os.environ.get("JOB_EVAL_DATA_PATH", "")
    eval_every = int(os.environ.get("JOB_EVAL_EVERY", "50"))
    eval_batches = int(os.environ.get("JOB_EVAL_BATCHES", "8"))

    from tpu_kubernetes.topology import parse_mesh_shape

    if dcn_spec:
        # multislice: DCN axes cross slices, JOB_MESH covers one slice.
        # The slice count comes from the dcn shape the operator supplied —
        # authoritative even where MEGASCALE_* env is absent (local runs)
        dcn_shape = parse_mesh_shape(dcn_spec)
        n_slices = 1
        for v in dcn_shape.values():
            n_slices *= v
        ici = (
            parse_mesh_shape(mesh_spec) if mesh_spec
            else mesh_shape_for_devices(n // n_slices)
        )
        mesh = create_hybrid_mesh(ici, dcn_shape)
    else:
        shape = parse_mesh_shape(mesh_spec) if mesh_spec else mesh_shape_for_devices(n)
        mesh = create_mesh(shape)
    log(f"devices={n} mesh={dict(mesh.shape)} model={model} "
        f"batch={batch} seq={seq}")

    tc = TrainConfig(
        accum_steps=int(os.environ.get("JOB_ACCUM_STEPS", "1")),
        optimizer=os.environ.get("JOB_OPTIMIZER", "adamw"),
    )
    if tc.accum_steps < 1 or batch % tc.accum_steps:
        # fail in seconds, not after a 7B init on the pod — train_step
        # would reject this anyway, but only at first trace
        raise SystemExit(
            f"JOB_BATCH={batch} not divisible by "
            f"JOB_ACCUM_STEPS={tc.accum_steps} (or accum < 1)"
        )
    state = init_state(jax.random.PRNGKey(0), cfg, tc)
    log(f"params={param_count(state['params'])/1e9:.2f}B")
    step_fn, shardings, b_sharding = make_sharded_train_step(cfg, tc, mesh, state)
    state = jax.device_put(state, shardings)

    start_step = 0
    if ckpt_dir:
        try:
            found = latest_step(ckpt_dir)
            if found is not None:
                state = restore(ckpt_dir, like=state)
                start_step = int(state["step"])
                log(f"resumed from step {start_step}")
        except CheckpointError as e:
            log(f"no resume: {e}")

    if data_path:
        # start_step skips already-consumed batches on checkpoint resume
        batches = input_pipeline(
            data_path, batch, seq, cfg.vocab_size, b_sharding,
            start_step=start_step,
        )
        log(f"data: {data_path} (from step {start_step})")
    else:
        batches = prefetch(
            jax.device_put(b, b_sharding)
            for b in synthetic_batches(cfg.vocab_size, batch, seq)
        )
        log("data: synthetic")
    eval_step = None
    eval_data: list = []
    if eval_path:
        # Built ONCE before the loop: a fresh jit closure per eval would be
        # a full XLA recompile every JOB_EVAL_EVERY steps, and the pipeline
        # would re-open memmaps + a prefetch thread each time. The fixed
        # seed-pinned prefix is materialized so every eval sees the same
        # batches (already device_put against the eval sharding).
        eval_step, eb_sharding = make_eval_step(cfg, mesh, state)
        eval_it = input_pipeline(
            eval_path, batch, seq, cfg.vocab_size, eb_sharding,
            seed=1, prefetch_depth=1,
        )
        eval_data = [next(eval_it) for _ in range(eval_batches)]
        eval_it.close()  # release the prefetch thread + memmaps

    def run_eval(at_step: int) -> None:
        """Mean held-out loss over the fixed eval prefix."""
        import math

        total = 0.0
        for eb in eval_data:
            total += float(eval_step(state["params"], eb))
        mean = total / eval_batches
        log(f"eval step={at_step} loss={mean:.4f} ppl={math.exp(min(mean, 30)):.2f}")

    from tpu_kubernetes.obs.profile import device_memory_stats
    from tpu_kubernetes.train.trainer import (
        FIRST_STEP_SECONDS,
        observe_first_step,
        observe_steps,
    )

    first_step_done = False
    t_last = time.time()
    for i in range(start_step, steps):
        t_call = 0.0 if first_step_done else time.time()
        state, loss = step_fn(state, next(batches))
        if not first_step_done:
            jax.block_until_ready(loss)
            first_step_s = time.time() - t_start
            FIRST_STEP_SECONDS.set(first_step_s)
            # the step call alone (trace+compile+run) — the compile-mode
            # phase the execute windows below compare against
            observe_first_step(time.time() - t_call)
            hbm = device_memory_stats()
            if hbm and "peak_bytes_in_use" in hbm:
                log(f"hbm peak={hbm['peak_bytes_in_use'] / 2**30:.2f}GiB"
                    + (f" limit={hbm['bytes_limit'] / 2**30:.2f}GiB"
                       if "bytes_limit" in hbm else ""))
            log(f"FIRST TRAIN STEP at +{first_step_s:.1f}s "
                f"loss={float(loss):.4f}")   # the north-star latency marker
            first_step_done = True
        if (i + 1) % 10 == 0:
            jax.block_until_ready(loss)
            now = time.time()
            tps = 10 * batch * seq / (now - t_last)
            observe_steps(now - t_last, 10, 10 * batch * seq,
                          loss=float(loss))
            log(f"step={i + 1} loss={float(loss):.4f} tokens/s={tps:.0f}")
            t_last = now
        if eval_path and (i + 1) % eval_every == 0:
            run_eval(i + 1)
        if ckpt_dir and (i + 1) % ckpt_every == 0:
            # orbax save of cross-host sharded arrays is a collective —
            # EVERY process must enter it (matching the restore path above)
            save(ckpt_dir, state, step=i + 1)
            if denv.process_id == 0:
                log(f"checkpointed step {i + 1}")

    log("done")


if __name__ == "__main__":
    main()
