"""Training step + state for the in-tree example job (MaxText analog).

TPU-first: one jitted train step with donated state, sharded via logical
axis rules over an arbitrary mesh (parallel/mesh.py); gradients reduce with
whatever collectives XLA inserts for the mesh (psum over data/fsdp riding
ICI). AdamW with global-norm clipping; loss in float32.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpu_kubernetes.models import ModelConfig, init_params, logical_axes, loss_fn
from tpu_kubernetes.obs import REGISTRY
from tpu_kubernetes.obs.profile import PhaseProfiler
from tpu_kubernetes.parallel import batch_sharding, param_shardings

# -- training telemetry (obs/metrics.py) -------------------------------------
# The Podracer lesson (arxiv 2104.06272): per-phase timing and throughput
# accounting are what let TPU fleets be tuned — step time and tokens/sec are
# THE signals the Gemma-on-TPU comparison (arxiv 2605.25645) leans on.
STEP_SECONDS = REGISTRY.histogram(
    "tpu_train_step_seconds",
    "optimizer step wall time (averaged over the logging window)",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0),
)
TOKENS_PER_SECOND = REGISTRY.gauge(
    "tpu_train_tokens_per_second",
    "training throughput over the most recent logging window",
)
TRAIN_STEPS = REGISTRY.counter(
    "tpu_train_steps_total", "optimizer steps completed",
)
TRAIN_LOSS = REGISTRY.gauge(
    "tpu_train_loss", "most recently logged training loss",
)
FIRST_STEP_SECONDS = REGISTRY.gauge(
    "tpu_train_first_step_seconds",
    "job start to first completed train step (the north-star latency)",
)
# compile-vs-execute attribution (obs/profile.py): the first step call
# pays jit trace + XLA compile; steady-state step time flows in window-
# grained through observe_steps — the split is what turns "step time
# moved" into "the compile got slower" vs "the step got slower"
PROFILER = PhaseProfiler(
    metric="tpu_train_phase_seconds",
    help="device-synced training phase seconds (mode=compile is the "
         "first step including trace+compile; mode=execute is steady "
         "state)",
)
COMPILE_OVERHEAD_SECONDS = REGISTRY.gauge(
    "tpu_train_compile_overhead_seconds",
    "what the first step paid beyond a steady-state step (trace + XLA "
    "compile); updated once steady-state windows exist",
)


def observe_first_step(seconds: float) -> None:
    """The first step CALL, device-synced by the caller — the compile-
    mode phase. (FIRST_STEP_SECONDS is create→first-step, a superset
    that also counts init/mesh/data; this is the step itself.)"""
    PROFILER.observe("step", seconds, mode="compile")


def observe_steps(window_seconds: float, n_steps: int, tokens: int,
                  loss: float | None = None) -> None:
    """Fold one logging window into the registry: ``n_steps`` optimizer
    steps took ``window_seconds`` and consumed ``tokens`` tokens. Kept
    window-grained on purpose — per-step observation would force a device
    sync every step (jax dispatch is async; only block_until_ready gives
    an honest per-step time)."""
    if n_steps < 1 or window_seconds <= 0:
        return
    per_step = window_seconds / n_steps
    for _ in range(n_steps):
        STEP_SECONDS.observe(per_step)
    TRAIN_STEPS.inc(n_steps)
    TOKENS_PER_SECOND.set(tokens / window_seconds)
    if loss is not None:
        TRAIN_LOSS.set(float(loss))
    PROFILER.observe("step", window_seconds, mode="execute", calls=n_steps)
    comp = PROFILER.stat("step", "compile")
    if comp:
        COMPILE_OVERHEAD_SECONDS.set(
            max(0.0, comp["last_seconds"] - per_step)
        )


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 100
    # gradient accumulation: the (global_batch, seq+1) step batch is split
    # into accum_steps microbatches scanned sequentially, gradients
    # accumulated in float32, ONE optimizer update — global batches larger
    # than HBM allows, numerically the full-batch step (equal micro means)
    accum_steps: int = 1
    # "adamw" (default) or "adafactor". Adafactor (the T5 recipe: factored
    # second moment, bf16 first moment) exists for HBM: AdamW streams f32
    # m and v over EVERY parameter each step — ~22 bytes/param of optimizer
    # traffic and 8 bytes/param of resident state, which for sparse MoE
    # scales with TOTAL experts while compute scales with active ones.
    # Factoring v to row/col statistics and keeping m in bf16 cuts the
    # update traffic to ~8 bytes/param and the resident moments 4×, at
    # Adafactor's (long-validated) approximation of the second moment.
    optimizer: str = "adamw"


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.linear_schedule(0.0, tc.learning_rate, tc.warmup_steps)
    if tc.optimizer == "adafactor":
        return optax.chain(
            optax.clip_by_global_norm(tc.grad_clip),
            optax.adafactor(
                schedule,
                momentum=tc.beta1,
                dtype_momentum=jnp.bfloat16,
                # decay_rate keeps Adafactor's own 1 - t^-0.8 schedule
                # (beta2 is an AdamW-family constant, not this knob)
                weight_decay_rate=tc.weight_decay or None,
                # the schedule already ramps the absolute rate; parameter
                # scaling would additionally multiply by RMS(p) and shrink
                # early updates of small-init layers
                multiply_by_parameter_scale=False,
            ),
        )
    if tc.optimizer != "adamw":
        raise ValueError(
            f"unknown optimizer {tc.optimizer!r} (adamw | adafactor)"
        )
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(
            schedule, b1=tc.beta1, b2=tc.beta2, weight_decay=tc.weight_decay
        ),
    )


def init_state(
    rng: jax.Array, cfg: ModelConfig, tc: TrainConfig
) -> dict[str, Any]:
    params = init_params(rng, cfg)
    opt_state = make_optimizer(tc).init(params)
    return {"params": params, "opt_state": opt_state, "step": jnp.zeros((), jnp.int32)}


def train_step(
    state: dict[str, Any], batch: jax.Array, cfg: ModelConfig, tc: TrainConfig,
    loss: Callable | None = None,
) -> tuple[dict[str, Any], jax.Array]:
    """One optimizer step. batch: (per-global-batch, seq+1) int32 tokens.
    ``loss`` defaults to the model family's loss_fn; the pipelined step
    passes pipeline_loss_fn here — the optimizer/update logic is shared.

    tc.accum_steps > 1 scans that many microbatches (batch rows must
    divide evenly), accumulating f32 gradients and applying ONE update —
    the returned loss is the microbatch mean. The token batch is small
    (int32), so any cross-device resharding of the (accum, micro, seq+1)
    reshape is noise next to a step's compute."""
    lossf = loss or loss_fn
    if tc.accum_steps < 1:
        # a typo'd JOB_ACCUM_STEPS=0 must not silently disable the
        # accumulation it was set to provide
        raise ValueError(f"accum_steps must be >= 1, got {tc.accum_steps}")
    if tc.accum_steps > 1:
        rows = batch.shape[0]
        if rows % tc.accum_steps:
            raise ValueError(
                f"batch rows {rows} not divisible by accum_steps "
                f"{tc.accum_steps}"
            )
        micro = batch.reshape(tc.accum_steps, rows // tc.accum_steps, -1)

        def one(carry, mb):
            gsum, lsum = carry
            lv, g = jax.value_and_grad(lossf)(state["params"], mb, cfg)
            gsum = jax.tree.map(
                lambda a, b: a + b.astype(jnp.float32), gsum, g
            )
            return (gsum, lsum + lv), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state["params"]
        )
        (gsum, lsum), _ = jax.lax.scan(one, (zeros, jnp.float32(0.0)), micro)
        scale = 1.0 / tc.accum_steps
        grads = jax.tree.map(
            lambda g, p: (g * scale).astype(p.dtype), gsum, state["params"]
        )
        loss_value = lsum * scale
    else:
        loss_value, grads = jax.value_and_grad(lossf)(
            state["params"], batch, cfg
        )
    updates, new_opt = make_optimizer(tc).update(
        grads, state["opt_state"], state["params"]
    )
    new_params = optax.apply_updates(state["params"], updates)
    return (
        {"params": new_params, "opt_state": new_opt, "step": state["step"] + 1},
        loss_value,
    )


def state_shardings(
    state: dict[str, Any], cfg: ModelConfig, mesh: Mesh, p_shardings: Any = None
) -> Any:
    """Shardings for the whole train state: params by logical axes (or the
    given pytree, e.g. pipeline shardings); optimizer moments follow their
    parameters; scalars replicated."""
    if p_shardings is None:
        p_shardings = param_shardings(logical_axes(cfg), mesh)
    replicated = NamedSharding(mesh, PartitionSpec())
    return {
        "params": p_shardings,
        "opt_state": opt_state_shardings(
            state["opt_state"], state["params"], p_shardings, replicated
        ),
        "step": replicated,
    }


def opt_state_shardings(opt_state, params_like, target_shardings, replicated):
    """Optimizer-state pytree → shardings: subtrees structured like
    ``params_like`` get ``target_shardings``, everything else replicates.
    Shared by the full-training and LoRA sharded steps (optimizer moments
    always mirror whatever pytree is being optimized).

    Leaves whose shape does NOT match their parameter replicate even
    inside a params-shaped subtree: Adafactor's factored second-moment
    trees mirror the params STRUCTURE but hold row/col reductions (and
    (1,) placeholders), where a full-rank PartitionSpec would be
    malformed — and at O(d + ff) per matrix they are cheap to replicate."""
    template_treedef = jax.tree.structure(params_like)

    def rec(node):
        if jax.tree.structure(node) == template_treedef:
            return jax.tree.map(
                lambda leaf, p, s: (
                    s if getattr(leaf, "shape", None) == p.shape else replicated
                ),
                node, params_like, target_shardings,
            )
        if hasattr(node, "_fields"):  # NamedTuple (optax states) — must
            return type(node)(*(rec(x) for x in node))  # precede tuple
        if isinstance(node, tuple):
            return tuple(rec(x) for x in node)
        if isinstance(node, list):
            return [rec(x) for x in node]
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return replicated

    return rec(opt_state)


def _with_expert_parallel(loss: Callable | None, mesh: Mesh) -> Callable | None:
    """Wrap a loss so MoE layers see the mesh at trace time
    (models/moe_ep.py): dispatch_mode="grouped" then shard_maps itself
    over the expert axis instead of hitting the opaque-kernel wall. A
    no-op for meshes without a non-trivial expert axis."""
    if "expert" not in mesh.axis_names or mesh.shape["expert"] <= 1:
        return loss
    from tpu_kubernetes.models.moe_ep import expert_parallel_context

    lossf = loss or loss_fn

    @functools.wraps(lossf)
    def wrapped(params, batch, cfg):
        with expert_parallel_context(mesh):
            return lossf(params, batch, cfg)

    return wrapped


def make_sharded_train_step(
    cfg: ModelConfig, tc: TrainConfig, mesh: Mesh, state: dict[str, Any],
    loss: Callable | None = None, p_shardings: Any = None,
) -> tuple[Callable, Any, NamedSharding]:
    """→ (jitted step, state shardings, batch sharding). The returned step
    donates the state buffer (in-place update on device)."""
    shardings = state_shardings(state, cfg, mesh, p_shardings=p_shardings)
    b_sharding = batch_sharding(mesh)
    step = jax.jit(
        functools.partial(
            train_step, cfg=cfg, tc=tc, loss=_with_expert_parallel(loss, mesh)
        ),
        in_shardings=(shardings, b_sharding),
        out_shardings=(shardings, NamedSharding(mesh, PartitionSpec())),
        donate_argnums=(0,),
    )
    return step, shardings, b_sharding


def make_pipeline_train_step(
    cfg: ModelConfig, tc: TrainConfig, mesh: Mesh, state: dict[str, Any],
    n_microbatches: int,
) -> tuple[Callable, Any, NamedSharding]:
    """→ (jitted pipelined step, state shardings, batch sharding). Layer
    params live sharded over the ``stage`` mesh axis; the batch shards
    over the data axes (PP × DP in one program). A thin specialization of
    make_sharded_train_step: same optimizer/update path, pipelined loss."""
    from tpu_kubernetes.parallel.pipeline import (
        pipeline_loss_fn,
        pipeline_param_shardings,
    )

    return make_sharded_train_step(
        cfg, tc, mesh, state,
        loss=functools.partial(
            pipeline_loss_fn, mesh=mesh, n_microbatches=n_microbatches
        ),
        p_shardings=pipeline_param_shardings(logical_axes(cfg), mesh),
    )


def make_eval_step(
    cfg: ModelConfig, mesh: Mesh, state: dict[str, Any]
) -> tuple[Callable, NamedSharding]:
    """→ (jitted eval step, batch sharding): loss-only forward over the
    same mesh/shardings as training, nothing donated (params survive)."""
    shardings = state_shardings(state, cfg, mesh)
    b_sharding = batch_sharding(mesh)
    lossf = _with_expert_parallel(None, mesh) or loss_fn

    def eval_step(params, batch):
        return lossf(params, batch, cfg)

    step = jax.jit(
        eval_step,
        in_shardings=(shardings["params"], b_sharding),
        out_shardings=NamedSharding(mesh, PartitionSpec()),
    )
    return step, b_sharding


def synthetic_batches(
    vocab_size: int, batch: int, seq: int, seed: int = 0
) -> Iterator[jax.Array]:
    """Deterministic synthetic token stream; batches are (batch, seq+1) so
    the next-token loss sees exactly ``seq`` positions (keeps attention
    sequence lengths block-aligned for the pallas kernel)."""
    rng = jax.random.PRNGKey(seed)
    while True:
        rng, sub = jax.random.split(rng)
        yield jax.random.randint(sub, (batch, seq + 1), 0, vocab_size, jnp.int32)
