"""Corpus preparation: raw text → the flat token shards train/data.py
memory-maps.

Closes the last gap in the data path: ``create cluster`` stands up the
slice, ``train.job`` consumes ``JOB_DATA_PATH`` shards, and this module
produces them from text. One shard = one flat little-endian array of
token ids (uint16 for vocab < 65536 else uint32 — data.py's contract).

Tokenizers, TPU-first pragmatics:

* **byte** (default): UTF-8 bytes as token ids (vocab 256). Zero
  dependencies, zero downloads, deterministic — the right default for an
  air-gapped TPU pod (this image has no network egress) and for smoke
  runs; byte-level LMs are a respectable baseline (e.g. ByT5-style).
* **hf:<name-or-path>**: any Hugging Face tokenizer already present
  locally (the transformers package is baked into the TPU image;
  checkpoints must be pre-staged — the framework never downloads at
  train time, same philosophy as the airgapped packer images).

CLI::

    python -m tpu_kubernetes.train.corpus --out shards/ file1.txt file2.txt
    python -m tpu_kubernetes.train.corpus --tokenizer hf:/path/to/tok ...

Reference anchor: none — the reference provisioner has no data plane
(SURVEY §5.4); this belongs to the in-tree training stack.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Any, Callable


def byte_tokenizer() -> tuple[Callable[[str], list[int]], int]:
    """→ (encode, vocab_size): UTF-8 byte-level tokenization."""
    return (lambda text: list(text.encode("utf-8"))), 256


def hf_tokenizer(name_or_path: str) -> tuple[Callable[[str], list[int]], int]:
    """→ (encode, vocab_size) from a LOCALLY available HF tokenizer."""
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(name_or_path, local_files_only=True)
    return (lambda text: tok.encode(text, add_special_tokens=False)), len(tok)


def resolve_tokenizer(spec: str) -> tuple[Callable[[str], list[int]], int]:
    if spec == "byte":
        return byte_tokenizer()
    if spec.startswith("hf:"):
        return hf_tokenizer(spec[3:])
    raise ValueError(f"unknown tokenizer {spec!r} (use 'byte' or 'hf:<path>')")


def token_dtype(vocab_size: int):
    """data.py's width contract: uint16 while it fits, else uint32."""
    import numpy as np

    return np.uint16 if vocab_size < 65536 else np.uint32


def build_shards(
    inputs: list[Path], out_dir: Path, tokenizer: str = "byte",
    shard_tokens: int = 64 * 1024 * 1024, eot_id: int | None = None,
) -> list[Path]:
    """Tokenize ``inputs`` (text files, read in order) into
    ``out_dir/shard_{i:05d}.bin`` files of at most ``shard_tokens`` tokens.
    ``eot_id`` (document separator) is appended after each input file when
    given and must be in-vocab. Refuses an out_dir that already holds
    shards: TokenDataset globs ``*.bin``, so stale shards from a previous
    run would silently mix into training data. Returns the paths written.

    Tokens buffer as numpy arrays in the shard dtype (a 64M-token shard
    is ~128 MB, not the gigabytes a Python int list would cost)."""
    import numpy as np

    encode, vocab = resolve_tokenizer(tokenizer)
    if eot_id is not None and not 0 <= eot_id < vocab:
        raise ValueError(
            f"eot_id {eot_id} out of range for vocab {vocab} — training "
            "would silently clip it into a real token"
        )
    stale = sorted(out_dir.glob("*.bin")) if out_dir.is_dir() else []
    if stale:
        raise ValueError(
            f"{out_dir} already holds {len(stale)} .bin shard(s) — "
            "remove them or pick a fresh directory (the data pipeline "
            "would silently read them as training data)"
        )
    dtype = token_dtype(vocab)
    paths: list[Path] = []
    chunks: list[Any] = []
    buffered = 0

    def flush(arr) -> None:
        p = out_dir / f"shard_{len(paths):05d}.bin"
        p.parent.mkdir(parents=True, exist_ok=True)
        arr.tofile(p)
        paths.append(p)

    for src in inputs:
        ids = encode(src.read_text(encoding="utf-8"))
        if eot_id is not None:
            ids = list(ids) + [eot_id]
        chunks.append(np.asarray(ids, dtype))
        buffered += chunks[-1].size
        while buffered >= shard_tokens:
            flat = np.concatenate(chunks)
            flush(flat[:shard_tokens])
            chunks = [flat[shard_tokens:]]
            buffered = chunks[0].size
    if buffered:
        flush(np.concatenate(chunks))
    return paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_kubernetes.train.corpus",
        description="Tokenize text files into flat token shards "
                    "(train/data.py's input format).",
    )
    ap.add_argument("inputs", nargs="+", type=Path, help="text files")
    ap.add_argument("--out", type=Path, required=True, help="shard directory")
    ap.add_argument("--tokenizer", default="byte",
                    help="'byte' (default) or 'hf:<local name-or-path>'")
    ap.add_argument("--shard-tokens", type=int, default=64 * 1024 * 1024,
                    help="max tokens per shard (default 64M)")
    ap.add_argument("--eot-id", type=int, default=None,
                    help="optional end-of-text token appended per input file")
    args = ap.parse_args(argv)

    missing = [p for p in args.inputs if not p.is_file()]
    if missing:
        print(f"error: missing input file(s): "
              f"{', '.join(map(str, missing))}", file=sys.stderr)
        return 1
    try:
        paths = build_shards(
            args.inputs, args.out, tokenizer=args.tokenizer,
            shard_tokens=args.shard_tokens, eot_id=args.eot_id,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    total = sum(p.stat().st_size for p in paths)
    print(f"wrote {len(paths)} shard(s), {total} bytes → {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
