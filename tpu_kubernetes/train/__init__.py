"""tpu_kubernetes.train — training loop, optimizer, input pipeline, and
checkpointing for the in-tree example job."""

from tpu_kubernetes.train.data import (  # noqa: F401
    TokenDataset,
    global_batches,
    input_pipeline,
    local_batches,
    prefetch,
)
from tpu_kubernetes.train.trainer import (  # noqa: F401
    TrainConfig,
    init_state,
    make_eval_step,
    make_optimizer,
    make_pipeline_train_step,
    make_sharded_train_step,
    state_shardings,
    synthetic_batches,
    train_step,
)
from tpu_kubernetes.train.lora import (  # noqa: F401
    LoraConfig,
    init_lora_state,
    lora_train_step,
    make_sharded_lora_step,
    merge_lora,
)
