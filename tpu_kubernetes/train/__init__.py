"""tpu_kubernetes.train — training loop, optimizer, and checkpointing for
the in-tree example job."""

from tpu_kubernetes.train.trainer import (  # noqa: F401
    TrainConfig,
    init_state,
    make_optimizer,
    make_pipeline_train_step,
    make_sharded_train_step,
    state_shardings,
    synthetic_batches,
    train_step,
)
