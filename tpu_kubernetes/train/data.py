"""Input pipeline: memory-mapped token shards → per-host loading → global
device arrays, with background prefetch.

The reference provisioner has no data plane of its own (its job ends when
the cluster registers); this is part of the in-tree training stack. The
TPU-first shape of an input pipeline:

* **Each process reads only its stripe.** A multi-host slice runs one
  process per host; tokens are striped across processes by sequence index
  (process p takes sequences p, p+P, p+2P, …), so no host reads or
  materializes the global batch.
* **Global arrays from local shards** via
  ``jax.make_array_from_process_local_data`` — the per-host arrays become
  one logically-global batch laid out to match the train step's batch
  sharding, no cross-host shuffle.
* **Prefetch.** A background thread stages the next batches host→device
  while the current step runs, hiding transfer latency behind compute
  (the host↔device transfer is the classic input-bound stall).

Token files are flat little-endian arrays (uint16 for vocab < 65536 else
uint32), memory-mapped — the OS page cache does the buffering, nothing is
ever fully loaded.
"""

from __future__ import annotations

import queue
import threading
from pathlib import Path
from typing import Any, Iterator

import jax
import numpy as np


class DataError(Exception):
    pass


class TokenDataset:
    """Flat token file → (seq+1)-length training sequences.

    ``path`` may be a single file or a directory of ``*.bin`` shards
    (concatenated in sorted order). dtype is inferred from ``vocab_size``.
    Sequences are non-overlapping windows; the trailing remainder is
    dropped (static shapes)."""

    def __init__(self, path: str | Path, seq: int, vocab_size: int):
        p = Path(path)
        files = sorted(p.glob("*.bin")) if p.is_dir() else [p]
        if not files or not all(f.is_file() for f in files):
            raise DataError(f"no token shards at {path}")
        dtype = np.uint16 if vocab_size <= 0xFFFF else np.uint32
        self._maps = [np.memmap(f, dtype=dtype, mode="r") for f in files]
        self._sizes = [m.shape[0] for m in self._maps]
        self.seq = seq
        self.window = seq + 1  # next-token loss consumes seq+1 tokens
        self.n_sequences = sum(s // self.window for s in self._sizes)
        if self.n_sequences == 0:
            raise DataError(
                f"{path}: {sum(self._sizes)} tokens < one window of {self.window}"
            )

    def __len__(self) -> int:
        return self.n_sequences

    def sequence(self, index: int) -> np.ndarray:
        """The index-th window as int32 (window,)."""
        if index < 0 or index >= self.n_sequences:
            raise IndexError(index)
        for m, size in zip(self._maps, self._sizes):
            n = size // self.window
            if index < n:
                start = index * self.window
                return np.asarray(
                    m[start:start + self.window], dtype=np.int32
                )
            index -= n
        raise IndexError(index)  # unreachable


def local_batches(
    dataset: TokenDataset,
    global_batch: int,
    *,
    process_index: int | None = None,
    process_count: int | None = None,
    seed: int = 0,
    epochs: int | None = None,
    start_step: int = 0,
) -> Iterator[np.ndarray]:
    """This host's stripe of each global batch: (global_batch / P, seq+1)
    int32, striped by sequence index and reshuffled each epoch with a
    seeded permutation (identical on every host — only the stripe
    differs). ``start_step`` skips the first N global batches without
    loading them (checkpoint resume: the permutation sequence is
    deterministic in ``seed``, so step s yields the same batch in every
    run)."""
    p = process_index if process_index is not None else jax.process_index()
    P = process_count if process_count is not None else jax.process_count()
    if global_batch % P:
        raise DataError(f"global batch {global_batch} not divisible by {P} hosts")
    local = global_batch // P
    if dataset.n_sequences < global_batch:
        raise DataError(
            f"dataset has {dataset.n_sequences} sequences < one global "
            f"batch of {global_batch}"
        )
    steps_per_epoch = dataset.n_sequences // global_batch
    rng = np.random.default_rng(seed)
    # fast-forward whole epochs: burn their permutations, not their batches
    epoch = start_step // steps_per_epoch
    skip = start_step % steps_per_epoch
    for _ in range(epoch):
        rng.permutation(dataset.n_sequences)
    while epochs is None or epoch < epochs:
        order = rng.permutation(dataset.n_sequences)
        for s in range(skip, steps_per_epoch):
            base = s * global_batch
            idx = order[base + p:base + global_batch:P]
            yield np.stack([dataset.sequence(int(i)) for i in idx[:local]])
        skip = 0
        epoch += 1


def global_batches(
    local_iter: Iterator[np.ndarray], sharding: Any
) -> Iterator[jax.Array]:
    """Assemble per-host local batches into logically-global sharded
    arrays matching the train step's batch sharding."""
    for local in local_iter:
        yield jax.make_array_from_process_local_data(sharding, local)


def prefetch(
    it: Iterator[Any], depth: int = 2
) -> Iterator[Any]:
    """Stage up to ``depth`` items ahead on a background thread so host
    work (file reads, device transfer dispatch) overlaps the running
    step. Exceptions re-raise at the consumption point. When the consumer
    stops early (generator close / GeneratorExit), the worker is released
    — it would otherwise block forever on a full queue, pinning staged
    batches for the process lifetime."""
    if depth < 1:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def _put(item) -> bool:
        """Bounded put that gives up once the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not _put(item):
                    return
            _put(_END)
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            _put(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def input_pipeline(
    path: str | Path, global_batch: int, seq: int, vocab_size: int,
    sharding: Any, *, seed: int = 0, prefetch_depth: int = 2,
    start_step: int = 0,
) -> Iterator[jax.Array]:
    """The full pipeline: memmap shards → per-host stripe → global sharded
    arrays → prefetch. One call site for the training job; pass the
    resumed step as ``start_step`` so training continues through the
    dataset instead of replaying it from the top."""
    ds = TokenDataset(path, seq, vocab_size)
    it = global_batches(
        local_batches(ds, global_batch, seed=seed, start_step=start_step),
        sharding,
    )
    return prefetch(it, depth=prefetch_depth)
