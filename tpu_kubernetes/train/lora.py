"""LoRA finetuning: low-rank adapters over frozen base weights.

Finetuning a pretrained model (e.g. one imported via
models/convert_hf.load_hf) rarely needs — or can afford — full-parameter
training: AdamW keeps two f32 moments per parameter, ~6× the bf16 weight
bytes. LoRA (Hu et al., 2021) trains only low-rank deltas
``W_eff = W + (α/r)·A@B`` with A (in, r), B (r, out) — optimizer state
shrinks by the rank ratio and the base stays frozen byte-for-byte.

TPU-first shape of the implementation:

* **Merge-form forward.** Each step materializes ``W + scale·A@B`` for
  the adapted leaves and calls the UNMODIFIED model forward — no
  per-matmul hook points, both model families (dense and MoE expert
  stacks) work unchanged, and XLA sees one fused outer-product-add per
  stacked weight (cheap next to the matmuls that consume it). Gradients
  flow to A/B through the merge; the base is frozen simply by
  differentiating only the adapter argument.
* **Adapters inherit sharding from their base leaf**: A shards like the
  weight's input axis, B like its output axis, the rank axis replicated —
  derived mechanically from models.logical_axes, so tensor/fsdp/expert
  sharded finetuning works with the existing mesh rules.
* B initializes to zero (standard): step 0 is exactly the base model.
* ``merge_lora`` exports plain params for serving/quantization.

The reference provisioner has no training plane (SURVEY §0); this
extends the in-tree stack's finetuning surface.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpu_kubernetes.models import ModelConfig, logical_axes, loss_fn
from tpu_kubernetes.parallel import (
    batch_sharding,
    logical_to_spec,
    param_shardings,
)


@dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    # layer-stack leaves to adapt (attention projections by default — the
    # standard LoRA target set; add the mlp trio for higher capacity)
    targets: tuple[str, ...] = ("wq", "wk", "wv", "wo")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def init_lora(
    rng: jax.Array, params: dict, cfg: ModelConfig, lc: LoraConfig
) -> dict:
    """→ {leaf_name: {"a": (..., in, r), "b": (..., r, out)}} for every
    target leaf present in params["layers"]. Leading (layer / expert)
    stack dims are preserved so the scan/expert structure is unchanged.
    A ~ N(0, 1/in), B = 0 — the merged model starts exactly at the base."""
    adapters: dict[str, dict] = {}
    for name in lc.targets:
        if name not in params["layers"]:
            raise ValueError(f"LoRA target {name!r} not in params.layers")
        w = params["layers"][name]
        if w.ndim < 3:
            # every adaptable leaf is a stacked matrix (layer[, expert],
            # in, out); a 2-D leaf like a norm gain would silently couple
            # the layer stack as if it were a feature dimension
            raise ValueError(
                f"LoRA target {name!r} has shape {w.shape} — only stacked "
                "matmul weights can carry adapters"
            )
        *lead, d_in, d_out = w.shape
        rng, k = jax.random.split(rng)
        adapters[name] = {
            "a": (jax.random.normal(k, (*lead, d_in, lc.rank), w.dtype)
                  / jnp.sqrt(jnp.asarray(d_in, jnp.float32)).astype(w.dtype)),
            "b": jnp.zeros((*lead, lc.rank, d_out), w.dtype),
        }
    return adapters


def merge_lora(
    params: dict, adapters: dict, lc: LoraConfig
) -> dict:
    """Base params + scale·A@B on every adapted leaf → plain params
    pytree (same structure as the base — feed to forward/generate/
    quantize_for_decode/serving unchanged)."""
    layers = dict(params["layers"])
    for name, ab in adapters.items():
        w = layers[name]
        delta = jnp.matmul(ab["a"], ab["b"]) * jnp.asarray(
            lc.scale, w.dtype
        )
        layers[name] = w + delta
    return {**params, "layers": layers}


def lora_loss_fn(
    adapters: dict, params: dict, tokens: jax.Array, cfg: ModelConfig,
    lc: LoraConfig,
) -> jax.Array:
    """loss_fn over the merged model, differentiable in the ADAPTERS only
    (differentiate argument 0; the base rides along as a constant)."""
    return loss_fn(merge_lora(params, adapters, lc), tokens, cfg)


def make_lora_optimizer(lc: LoraConfig, learning_rate: float = 1e-4):
    """AdamW over adapter leaves only — no weight decay on B (it starts
    at zero; decaying it fights the update direction for nothing)."""
    del lc
    return optax.adamw(learning_rate, weight_decay=0.0)


def init_lora_state(
    rng: jax.Array, params: dict, cfg: ModelConfig, lc: LoraConfig,
    learning_rate: float = 1e-4,
) -> dict:
    adapters = init_lora(rng, params, cfg, lc)
    opt_state = make_lora_optimizer(lc, learning_rate).init(adapters)
    return {
        "adapters": adapters,
        "opt_state": opt_state,
        "step": jnp.zeros((), jnp.int32),
    }


def lora_train_step(
    state: dict, params: dict, batch: jax.Array, cfg: ModelConfig,
    lc: LoraConfig, learning_rate: float = 1e-4,
) -> tuple[dict, jax.Array]:
    """One finetuning step: grads w.r.t. adapters only, base untouched."""
    loss_value, grads = jax.value_and_grad(lora_loss_fn)(
        state["adapters"], params, batch, cfg, lc
    )
    updates, new_opt = make_lora_optimizer(lc, learning_rate).update(
        grads, state["opt_state"], state["adapters"]
    )
    new_adapters = optax.apply_updates(state["adapters"], updates)
    return (
        {
            "adapters": new_adapters,
            "opt_state": new_opt,
            "step": state["step"] + 1,
        },
        loss_value,
    )


def lora_shardings(cfg: ModelConfig, lc: LoraConfig, mesh: Mesh):
    """Adapter shardings derived from each base leaf's logical axes:
    A keeps the leading + input axes and replicates the rank dim; B keeps
    the leading axes, replicates rank, and keeps the output axis."""
    layer_axes = logical_axes(cfg)["layers"]
    out: dict[str, dict] = {}
    for name in lc.targets:
        axes = layer_axes[name]          # e.g. ("layer", "embed", "heads")
        lead, ax_in, ax_out = axes[:-2], axes[-2], axes[-1]
        spec_a = logical_to_spec((*lead, ax_in, None), mesh=mesh)
        spec_b = logical_to_spec((*lead, None, ax_out), mesh=mesh)
        out[name] = {
            "a": NamedSharding(mesh, spec_a),
            "b": NamedSharding(mesh, spec_b),
        }
    return out


def make_sharded_lora_step(
    cfg: ModelConfig, lc: LoraConfig, mesh: Mesh, state: dict, params: dict,
    learning_rate: float = 1e-4,
) -> tuple[Callable, Any, Any, Any]:
    """→ (jitted step(state, params, batch), state shardings, param
    shardings, batch sharding) — the finetuning analog of
    make_sharded_train_step. The base params stay sharded by the model's
    own logical axes and are never donated (they are reused every step)."""
    from tpu_kubernetes.train.trainer import opt_state_shardings

    a_sh = lora_shardings(cfg, lc, mesh)
    replicated = NamedSharding(mesh, PartitionSpec())
    state_sh = {
        "adapters": a_sh,
        # adamw moments mirror the adapter pytree per-leaf
        "opt_state": opt_state_shardings(
            state["opt_state"], state["adapters"], a_sh, replicated
        ),
        "step": replicated,
    }
    p_sh = param_shardings(logical_axes(cfg), mesh)
    b_sh = batch_sharding(mesh)
    step = jax.jit(
        functools.partial(
            lora_train_step, cfg=cfg, lc=lc, learning_rate=learning_rate
        ),
        in_shardings=(state_sh, p_sh, b_sh),
        out_shardings=(state_sh, replicated),
        donate_argnums=(0,),
    )
    return step, state_sh, p_sh, b_sh

