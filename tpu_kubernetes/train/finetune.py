"""LoRA finetuning job entrypoint: pretrained checkpoint in, merged
(and optionally HF-exported) weights out.

The deployable form of docs/guide/finetuning.md — what a JobSet pod runs
on a provisioned slice, sibling of train/job.py (pretraining) and
serve/job.py (inference). Env contract:

  FT_HF_CHECKPOINT  LOCAL transformers checkpoint to adapt
                    (models/convert_hf.load_hf; llama or mixtral)
  FT_MODEL          preset name instead (random init — smoke/bring-up
                    mode; default llama-test)
  FT_DATA_PATH      token shards (train/corpus.py format); synthetic
                    tokens when unset (smoke mode)
  FT_STEPS          optimizer steps (default 100)
  FT_BATCH / FT_SEQ batch rows / sequence length (defaults: device count
                    scaled / model max_seq)
  FT_RANK / FT_ALPHA / FT_TARGETS   LoRA shape (defaults 8 / 16 /
                    wq,wk,wv,wo — comma-separated layer leaves)
  FT_LR             adapter learning rate (default 1e-4)
  FT_MESH           e.g. 'fsdp=4,tensor=2' (default: auto for the
                    device count)
  FT_OUT            directory for the MERGED weights as an orbax
                    checkpoint (required — a finetune that saves nothing
                    is a smoke test, use FT_STEPS=0 for that)
  FT_EXPORT_HF      optional directory: additionally export the merged
                    model as a transformers checkpoint (dense only)

The base stays frozen; only adapter moments exist (train/lora.py), so
this fits where full training would not.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path


def log(*args) -> None:
    print("[finetune]", *args, file=sys.stderr, flush=True)


def run_finetune(env: dict | None = None) -> None:
    env = dict(os.environ if env is None else env)

    import jax

    # honor an explicit JAX_PLATFORMS even where a sitecustomize re-forces
    # a tunneled TPU platform at import (same stance as train/job.py —
    # local CPU smoke runs must be possible)
    if env.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", env["JAX_PLATFORMS"])

    from tpu_kubernetes.parallel import (
        enable_persistent_compile_cache,
        initialize,
    )

    cache = enable_persistent_compile_cache()
    if cache:
        log(f"compile cache: {cache}")
    denv = initialize()  # multi-host slice: jax.distributed bootstrap
    log(f"process {denv.process_id}/{denv.num_processes}")

    from tpu_kubernetes.models import CONFIGS, MoEConfig, init_params
    from tpu_kubernetes.parallel import create_mesh, mesh_shape_for_devices
    from tpu_kubernetes.train import synthetic_batches
    from tpu_kubernetes.train.checkpoint import save
    from tpu_kubernetes.train.lora import (
        LoraConfig,
        init_lora_state,
        make_sharded_lora_step,
        merge_lora,
    )

    out_dir = env.get("FT_OUT", "")
    steps = int(env.get("FT_STEPS", "100"))
    if not out_dir and steps > 0:
        raise SystemExit("FT_OUT must name the merged-weights directory "
                         "(FT_STEPS=0 for a no-output smoke run)")

    t_start = time.time()
    hf_path = env.get("FT_HF_CHECKPOINT", "")
    if hf_path:
        from tpu_kubernetes.models import load_hf

        params, cfg = load_hf(hf_path)
        log(f"base: HF checkpoint {hf_path}")
    else:
        cfg = CONFIGS[env.get("FT_MODEL", "llama-test")]
        params = init_params(jax.random.PRNGKey(0), cfg)
        log(f"base: random-init {env.get('FT_MODEL', 'llama-test')} "
            "(smoke mode)")
    if env.get("FT_EXPORT_HF", "") and isinstance(cfg, MoEConfig):
        # fail in seconds, not after the whole finetune (job.py stance)
        raise SystemExit("FT_EXPORT_HF supports the dense family only")

    n = len(jax.devices())
    batch = int(env.get("FT_BATCH", str(max(4, n))))
    seq = int(env.get("FT_SEQ", str(cfg.max_seq)))
    lc = LoraConfig(
        rank=int(env.get("FT_RANK", "8")),
        alpha=float(env.get("FT_ALPHA", "16")),
        targets=tuple(
            t for t in env.get("FT_TARGETS", "wq,wk,wv,wo").split(",") if t
        ),
    )
    lr = float(env.get("FT_LR", "1e-4"))

    mesh_spec = env.get("FT_MESH", "")
    if mesh_spec:
        from tpu_kubernetes.topology import parse_mesh_shape

        mesh = create_mesh(parse_mesh_shape(mesh_spec))
    else:
        mesh = create_mesh(mesh_shape_for_devices(n))
    log(f"devices={n} mesh={dict(mesh.shape)} rank={lc.rank} "
        f"targets={','.join(lc.targets)} batch={batch} seq={seq}")

    state = init_lora_state(jax.random.PRNGKey(1), params, cfg, lc,
                            learning_rate=lr)
    step_fn, s_sh, p_sh, b_sh = make_sharded_lora_step(
        cfg, lc, mesh, state, params, learning_rate=lr
    )
    state = jax.device_put(state, s_sh)
    params = jax.device_put(params, p_sh)

    data_path = env.get("FT_DATA_PATH", "")
    if data_path:
        from tpu_kubernetes.train import input_pipeline

        batches = input_pipeline(data_path, batch, seq, cfg.vocab_size, b_sh)
        log(f"data: {data_path}")
    else:
        from tpu_kubernetes.train import prefetch

        batches = prefetch(
            jax.device_put(b, b_sh)
            for b in synthetic_batches(cfg.vocab_size, batch, seq)
        )
        log("data: synthetic")

    first = True
    for i in range(steps):
        state, loss = step_fn(state, params, next(batches))
        if first:
            jax.block_until_ready(loss)
            log(f"FIRST FINETUNE STEP at +{time.time() - t_start:.1f}s "
                f"loss={float(loss):.4f}")
            first = False
        if (i + 1) % 25 == 0 or i + 1 == steps:
            log(f"step {i + 1}/{steps} loss={float(loss):.4f}")

    merged = merge_lora(params, state["adapters"], lc)
    if out_dir:
        save(out_dir, {"params": merged}, step=steps)
        log(f"merged weights → {out_dir}")
    export_dir = env.get("FT_EXPORT_HF", "")
    if export_dir:
        from tpu_kubernetes.models import export_hf_llama

        export_hf_llama(merged, cfg, Path(export_dir))
        log(f"HF export → {export_dir}")


def main() -> int:
    run_finetune()
    return 0


if __name__ == "__main__":
    sys.exit(main())
