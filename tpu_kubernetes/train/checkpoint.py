"""Checkpoint/resume for the training job (orbax-backed).

For *infrastructure*, the state-doc + tfstate pair is the checkpoint
(SURVEY §5.4); model/optimizer checkpointing belongs to the training job and
lives here: async-capable orbax save/restore of the whole train state, with
step-numbered directories and latest-step discovery — the GCS-destination
analog of MaxText's checkpointing.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any

import jax

from tpu_kubernetes.obs import REGISTRY

CKPT_SECONDS = REGISTRY.histogram(
    "tpu_train_checkpoint_seconds",
    "checkpoint save/restore wall time",
    labelnames=("op",),
)


class CheckpointError(Exception):
    pass


def _import_ocp():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:  # pragma: no cover
        raise CheckpointError("orbax-checkpoint is not installed") from e
    return ocp


def _resolve_dir(directory: str | Path):
    """Absolute local path, or the unmodified URL for remote stores —
    Path().absolute() would mangle gs://bucket/x into a local path."""
    s = str(directory)
    if "://" in s:
        return s
    return Path(directory).absolute()


def _manager(directory: str | Path, max_to_keep: int = 3):
    ocp = _import_ocp()
    return ocp.CheckpointManager(
        _resolve_dir(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
    )


def save(directory: str | Path, state: dict[str, Any], step: int,
         max_to_keep: int = 3, wait: bool = True) -> None:
    ocp = _import_ocp()
    t0 = time.monotonic()
    mgr = _manager(directory, max_to_keep)
    mgr.save(step, args=ocp.args.StandardSave(state))
    if wait:
        mgr.wait_until_finished()
    mgr.close()
    CKPT_SECONDS.labels("save").observe(time.monotonic() - t0)


def latest_step(directory: str | Path) -> int | None:
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(directory: str | Path, like: dict[str, Any],
            step: int | None = None) -> dict[str, Any]:
    """Restore into the structure/shardings of ``like`` (an abstract or
    concrete train state)."""
    ocp = _import_ocp()
    t0 = time.monotonic()
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
    if step is None:
        raise CheckpointError(f"no checkpoints under {directory}")
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
    restored = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    mgr.close()
    CKPT_SECONDS.labels("restore").observe(time.monotonic() - t0)
    return restored
