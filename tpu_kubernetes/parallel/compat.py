"""Version shims for shard_map-era jax APIs.

The tree targets jax ≥ 0.8 — top-level ``jax.shard_map`` and the
varying-type system (``jax.lax.pcast``). Older runtimes keep shard_map
under ``jax.experimental`` and have no varying/invariant distinction at
all: inside the mapped region every value is already per-shard, so
``pcast`` degrades to identity there. Importing through this module keeps
the rest of the package importable (and the serving/training stack
bootable) on both sides of the API change.
"""

from __future__ import annotations

import jax

try:  # jax ≥ 0.8
    from jax import shard_map  # noqa: F401
except ImportError:  # pragma: no cover — jax < 0.8
    from jax.experimental.shard_map import shard_map  # noqa: F401

if hasattr(jax.lax, "pcast"):  # jax ≥ 0.8
    pcast = jax.lax.pcast
else:  # pragma: no cover — no varying types pre-0.8; identity is exact
    def pcast(x, axes, to="varying"):
        del axes, to
        return x


def shard_map_compat(f, mesh, in_specs, out_specs,
                     axis_names=frozenset(), check_vma=True):
    """Front-end for the full jax ≥ 0.8 shard_map surface (``axis_names``
    manual-axis subsetting, ``check_vma``). On older jax, ``axis_names``
    maps to the experimental API's complement (``auto`` = the mesh axes
    left automatic) and ``check_vma`` to ``check_rep``."""
    if hasattr(jax, "shard_map"):  # jax ≥ 0.8
        kwargs = {"check_vma": check_vma}
        if axis_names:
            kwargs["axis_names"] = set(axis_names)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    auto = (
        frozenset(mesh.axis_names) - frozenset(axis_names)
        if axis_names else frozenset()
    )
    if auto:
        # Partial-manual mode on this runtime lowers through a PartitionId
        # instruction XLA's SPMD partitioner hard-aborts on (killing the
        # whole process, not raising). Refuse catchably instead.
        raise NotImplementedError(
            "partial-manual shard_map (axis_names subsetting) requires "
            f"jax >= 0.8; mesh axes {sorted(mesh.axis_names)} with manual "
            f"axes {sorted(axis_names)} cannot compile on jax "
            f"{jax.__version__}"
        )
    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
