"""Device mesh + logical-axis sharding rules.

The scaling-book recipe: pick a mesh, annotate shardings with logical names,
let XLA insert the collectives. Axes:

  data     — pure data parallelism (gradient psum over DCN or ICI)
  fsdp     — fully-sharded data parallel (params sharded, all-gathered
             per-layer; rides ICI)
  tensor   — megatron-style tensor parallelism (heads/mlp sharded; psum
             per-block; innermost, fastest ICI axis)
  sequence — context parallelism for long sequences (ring attention,
             parallel/ring_attention.py)
  expert   — expert parallelism for MoE models (models/moe.py); doubles as
             a data axis for the non-MoE path, GShard-style
  stage    — pipeline parallelism (parallel/pipeline.py); layers split
             into stages, microbatches flow stage-to-stage over ppermute

Logical param/activation axes (models/llama.py logical_axes) map to mesh
axes through RULES; the same model code runs on any mesh shape.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("data", "fsdp", "expert", "stage", "tensor", "sequence")

# logical axis → mesh axis (None = replicate). The fsdp axis shards the
# embed dimension of every weight (ZeRO-3-style); tensor shards heads/mlp.
DEFAULT_RULES: dict[str, str | None] = {
    "batch": "data",          # activation batch over data axis
    "fsdp_batch": "fsdp",     # batch also over fsdp when it's a data axis
    "embed": "fsdp",
    "heads": "tensor",
    "kv": "tensor",
    "mlp": "tensor",
    "vocab": "tensor",
    "layer": None,            # scan axis is never sharded (pipeline shards
                              # it over "stage" via pipeline_param_shardings)
    "seq": "sequence",
    "expert": "expert",       # MoE expert weights over the expert axis
}

# mesh axes that carry the batch (data-like); "expert" is data-like for the
# non-MoE path, GShard-style (the same devices that hold different experts
# also hold different tokens, so the dispatch einsum becomes an all-to-all)
DATA_AXES = ("data", "fsdp", "expert")


def data_axes_in(mesh: Mesh) -> tuple[str, ...]:
    """The data-like axes actually present (and non-trivial) in a mesh."""
    return tuple(
        a for a in DATA_AXES if a in mesh.axis_names and mesh.shape[a] > 1
    )


def device_prefix_for(
    shape: Mapping[str, int],
    devices: Sequence[jax.Device],
    allow_partial: bool = True,
    label: str = "mesh",
) -> list:
    """Devices a ``shape``-sized mesh should use: a PREFIX of ``devices``
    when the mesh is smaller than the host and partial use is allowed
    (e.g. tensor=4 serving on a v5e-8), everything otherwise. Zero-size
    axes are ignored (matching :func:`create_mesh`); asking for more
    devices than exist is a clear, label-attributed error. One
    implementation for every serving entry point, so the policy cannot
    drift."""
    sizes = [v for v in shape.values() if v != 0]
    total = int(np.prod(sizes)) if sizes else 1
    if total > len(devices):
        raise ValueError(
            f"{label} {dict(shape)} wants {total} devices, "
            f"have {len(devices)}"
        )
    if allow_partial and total < len(devices):
        return list(devices[:total])
    return list(devices)


def create_mesh(
    shape: Mapping[str, int], devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a Mesh from {axis: size}. Axis order follows MESH_AXES so the
    innermost (fastest-varying, best-ICI-locality) axis is tensor/sequence."""
    shape = {k: v for k, v in shape.items() if v != 0}
    unknown = set(shape) - set(MESH_AXES)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; known {MESH_AXES}")
    axis_names = tuple(a for a in MESH_AXES if a in shape)
    sizes = tuple(shape[a] for a in axis_names)
    if devices is None:
        devices = jax.devices()
    total = int(np.prod(sizes)) if sizes else 1
    if total != len(devices):
        raise ValueError(
            f"mesh {dict(shape)} wants {total} devices, have {len(devices)}"
        )
    device_array = mesh_utils.create_device_mesh(sizes, devices=list(devices))
    return Mesh(device_array, axis_names)


def logical_to_spec(
    logical: Sequence[str | None],
    rules: Mapping[str, str | None] = DEFAULT_RULES,
    mesh: Mesh | None = None,
) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec, dropping mesh
    axes that don't exist on (or are trivial in) the given mesh."""
    entries = []
    for name in logical:
        mesh_axis = rules.get(name) if name is not None else None
        if mesh_axis is not None and mesh is not None:
            if mesh_axis not in mesh.axis_names or mesh.shape[mesh_axis] == 1:
                mesh_axis = None
        entries.append(mesh_axis)
    return PartitionSpec(*entries)


def param_shardings(
    logical_tree: Any, mesh: Mesh, rules: Mapping[str, str | None] = DEFAULT_RULES
) -> Any:
    """Pytree of NamedShardings matching a logical_axes() tree."""
    return jax.tree.map(
        lambda logical: NamedSharding(mesh, logical_to_spec(logical, rules, mesh)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input batch: sharded over every data-like axis present (DATA_AXES)."""
    data_axes = data_axes_in(mesh)
    if not data_axes:
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh, PartitionSpec(data_axes))


def create_hybrid_mesh(
    ici_shape: Mapping[str, int],
    dcn_shape: Mapping[str, int],
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh spanning multiple TPU slices: ``dcn_shape`` axes cross slice
    boundaries (data-center network — orders of magnitude less bandwidth
    than ICI), ``ici_shape`` axes stay within a slice. The scaling-book
    recipe: put data/pipeline parallelism on DCN axes and
    fsdp/tensor/sequence on ICI axes, so per-step collectives ride ICI and
    only gradient reductions cross slices.

    Devices are grouped into slices by their ``slice_index`` attribute
    (real multislice TPU) or evenly by order (CPU test meshes). DCN axes
    vary slowest; an axis may not appear (non-trivially) in both shapes.
    Size-1 axes are dropped — so the auto-mesh default ``data=1`` composes
    with a DCN ``data`` axis instead of colliding with it."""
    ici_shape = {k: v for k, v in ici_shape.items() if v not in (0, 1)}
    dcn_shape = {k: v for k, v in dcn_shape.items() if v not in (0, 1)}
    overlap = set(ici_shape) & set(dcn_shape)
    if overlap:
        raise ValueError(
            f"axes {sorted(overlap)} appear in both ici and dcn shapes — "
            "an axis is either intra-slice (ICI) or cross-slice (DCN)"
        )
    unknown = (set(ici_shape) | set(dcn_shape)) - set(MESH_AXES)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; known {MESH_AXES}")

    if devices is None:
        devices = jax.devices()
    n_slices = int(np.prod(list(dcn_shape.values()) or [1]))
    per_slice = int(np.prod(list(ici_shape.values()) or [1]))
    if n_slices * per_slice != len(devices):
        raise ValueError(
            f"hybrid mesh dcn={dict(dcn_shape)} × ici={dict(ici_shape)} wants "
            f"{n_slices}×{per_slice} devices, have {len(devices)}"
        )

    # group by slice_index when the runtime provides it, else evenly
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        by_slice: dict[int, list] = {}
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
        if len(by_slice) != n_slices or any(
            len(g) != per_slice for g in by_slice.values()
        ):
            raise ValueError(
                f"found {len(by_slice)} hardware slices of sizes "
                f"{[len(g) for g in by_slice.values()]}; dcn×ici shape wants "
                f"{n_slices} slices of {per_slice}"
            )
        groups = [by_slice[k] for k in sorted(by_slice)]
    else:
        groups = [
            list(devices[i * per_slice:(i + 1) * per_slice])
            for i in range(n_slices)
        ]

    dcn_axes = tuple(a for a in MESH_AXES if a in dcn_shape)
    ici_axes = tuple(a for a in MESH_AXES if a in ici_shape)
    ici_sizes = tuple(ici_shape[a] for a in ici_axes)
    # good ICI ordering within each slice, then stack over the DCN axes
    slice_meshes = [
        mesh_utils.create_device_mesh(ici_sizes, devices=g) if ici_sizes
        else np.array(g)
        for g in groups
    ]
    dcn_sizes = tuple(dcn_shape[a] for a in dcn_axes)
    device_array = np.stack(slice_meshes).reshape(*dcn_sizes, *ici_sizes)
    return Mesh(device_array, (*dcn_axes, *ici_axes))


def mesh_shape_for_devices(n: int) -> dict[str, int]:
    """A sensible default mesh for n devices: tensor innermost (2 if even),
    rest fsdp, data=1 (fsdp already data-parallels the batch)."""
    tensor = 2 if n % 2 == 0 and n >= 2 else 1
    fsdp = n // tensor
    return {"data": 1, "fsdp": fsdp, "tensor": tensor}
