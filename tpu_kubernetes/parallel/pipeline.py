"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh
``stage`` axis.

The decoder's layer stack is split into S contiguous stages (layer-stacked
params sharded over ``stage`` on their leading axis — each device holds
n_layers/S layers). The batch is split into M microbatches which flow
stage-to-stage over ``lax.ppermute`` (ICI neighbor exchange): a scan over
M+S-1 ticks where every tick each stage runs its layers on one in-flight
microbatch and hands the activation to the next stage. Bubble fraction is
the standard (S-1)/(M+S-1); autodiff through ``ppermute`` generates the
reverse-direction 1F1B-equivalent communication for the backward pass, so
one ``jax.grad`` of :func:`pipeline_loss_fn` is a complete pipelined
training step.

Embedding and the LM head run outside the pipelined region (they are the
boundary layers); the microbatch dimension may additionally be sharded
over the data axes, composing PP × DP in one jit.

TPU-first notes: everything is one compiled program — no host-side stage
scheduler (the reference's analog of orchestration is its terraform
subprocess, SURVEY §1 layer 7; here the schedule is `lax.scan` inside the
XLA program and the "scheduler" is the compiler). Static tick count,
static shapes, remat per stage-visit via ``jax.checkpoint``.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpu_kubernetes.models import ModelConfig
from tpu_kubernetes.models.llama import _block, remat_policy_kwargs
from tpu_kubernetes.ops import next_token_nll, rms_norm, rope_frequencies
from tpu_kubernetes.parallel.compat import pcast, shard_map
from tpu_kubernetes.parallel.mesh import (
    DEFAULT_RULES,
    data_axes_in,
    logical_to_spec,
)


def _pipeline_body(
    layers: dict, x_mb: jax.Array, cfg: ModelConfig, n_stages: int,
    stage_axis: str, data_axes: tuple[str, ...] = (),
):
    """Runs on one stage device inside shard_map. layers: this stage's
    (n_layers/S, ...) slice; x_mb: (M, mb, s, d) local microbatches."""
    my = jax.lax.axis_index(stage_axis)
    M = x_mb.shape[0]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)

    def run_stage(act):
        block = lambda x, layer: (_block(cfg, cos, sin, x, layer), None)
        if cfg.remat:
            block = jax.checkpoint(block, **remat_policy_kwargs(cfg))
        out, _ = jax.lax.scan(block, act, layers)
        return out

    mb_shape = x_mb.shape[1:]
    act0 = jnp.zeros(mb_shape, x_mb.dtype)
    buf0 = jnp.zeros_like(x_mb)
    # the carry becomes stage-varying (ingest depends on axis_index) and
    # data-varying (microbatches are data-sharded) inside the loop; mark
    # the initial values varying so the loop types are stable
    act0 = pcast(act0, (stage_axis, *data_axes), to="varying")
    buf0 = pcast(buf0, (stage_axis,), to="varying")  # data-varying already
    fwd = [(i, i + 1) for i in range(n_stages - 1)]  # non-cyclic shift

    def tick(carry, t):
        act, buf = carry
        # stage 0 ingests microbatch t (idles past the last one)
        ingest = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False
        )
        act = jnp.where(my == 0, ingest, act)
        out = run_stage(act)
        # the last stage finishes microbatch t-(S-1) this tick
        idx = t - (n_stages - 1)
        written = jax.lax.dynamic_update_index_in_dim(
            buf, out, jnp.clip(idx, 0, M - 1), 0
        )
        buf = jnp.where((my == n_stages - 1) & (idx >= 0), written, buf)
        # hand the activation to the next stage (stage 0 receives zeros,
        # overwritten by next tick's ingest)
        nxt = jax.lax.ppermute(out, stage_axis, fwd)
        return (nxt, buf), None

    (_, buf), _ = jax.lax.scan(
        tick, (act0, buf0), jnp.arange(M + n_stages - 1)
    )
    # replicate the last stage's output buffer across the stage axis
    return jax.lax.psum(
        jnp.where(my == n_stages - 1, buf, jnp.zeros_like(buf)), stage_axis
    )


def pipeline_forward(
    params: dict, tokens: jax.Array, cfg: ModelConfig, mesh: Mesh,
    n_microbatches: int, stage_axis: str = "stage",
) -> jax.Array:
    """tokens (batch, seq) int32 → logits (batch, seq, vocab) f32, with the
    layer stack pipelined over ``stage_axis`` and microbatches sharded over
    the data axes."""
    S = mesh.shape[stage_axis]
    if cfg.n_layers % S:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by {S} stages")
    B = tokens.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by {n_microbatches} microbatches")

    x = params["embed"][tokens]                       # (B, s, d)
    mb = B // n_microbatches
    x_mb = x.reshape(n_microbatches, mb, *x.shape[1:])

    dspec = data_axes_in(mesh) or None
    layer_spec = jax.tree.map(
        lambda _: PartitionSpec(stage_axis), params["layers"]
    )
    x_spec = PartitionSpec(None, dspec)
    body = shard_map(
        functools.partial(
            _pipeline_body, cfg=cfg, n_stages=S, stage_axis=stage_axis,
            data_axes=data_axes_in(mesh),
        ),
        mesh=mesh,
        in_specs=(layer_spec, x_spec),
        out_specs=x_spec,
    )
    x = body(params["layers"], x_mb).reshape(B, *x.shape[1:])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def pipeline_loss_fn(
    params: dict, tokens: jax.Array, cfg: ModelConfig, mesh: Mesh,
    n_microbatches: int,
) -> jax.Array:
    logits = pipeline_forward(params, tokens[:, :-1], cfg, mesh, n_microbatches)
    return next_token_nll(logits, tokens[:, 1:])


def pipeline_param_shardings(logical_tree: Any, mesh: Mesh) -> Any:
    """Shardings for pipelined training: layer-stacked weights shard their
    leading axis over ``stage`` (and nothing else — they are consumed by a
    shard_map whose in_spec is exactly P('stage')); boundary weights
    (embed/head) follow the default logical rules."""
    def leaf(logical):
        if "layer" in logical:
            spec = PartitionSpec(
                *("stage" if name == "layer" else None for name in logical)
            )
        else:
            spec = logical_to_spec(logical, DEFAULT_RULES, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree.map(
        leaf, logical_tree, is_leaf=lambda x: isinstance(x, tuple)
    )
