"""Ring attention: exact context parallelism over a mesh ``sequence`` axis.

Long-context training shards the sequence dimension across devices; each
device holds one sequence block of Q, K, V. K/V blocks rotate around the
ring with ``lax.ppermute`` (ICI neighbor exchange — bandwidth-optimal) while
every device accumulates its Q block's attention with the online-softmax
combine, so the full O(seq²) score matrix never materializes on any one
device and communication overlaps compute ring-step by ring-step.

Runs inside ``shard_map``; :func:`ring_attention_sharded` is the convenience
wrapper. Causality is handled by the block order: the block originating at
ring position j contributes fully when j < i (all its positions precede
mine), causally when j == i, and not at all when j > i.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from tpu_kubernetes.parallel.compat import pcast, shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, mask, sm_scale):
    """Scores + masked partial softmax stats for one (q-block, kv-block)
    pair. q: (b,h,sq,d); k,v: (b,h,sk,d); mask broadcastable to (sq,sk)."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                   # (b,h,sq,1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m, l, pv


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
    causal: bool = True, sm_scale: float | None = None,
) -> jax.Array:
    """Call INSIDE shard_map. q,k,v: the local sequence shard
    (batch, heads, seq_local, head_dim)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    sq = q.shape[2]
    qf = q.astype(jnp.float32)

    local_tri = (
        jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
        <= jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
    )
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        m, l, acc, kt, vt = carry
        j = (my - t) % n  # ring position this K/V block originated at
        if causal:
            # j < my: full block; j == my: causal triangle; j > my: nothing
            mask = jnp.where(
                j < my,
                jnp.ones((sq, sq), bool),
                jnp.where(j == my, local_tri, jnp.zeros((sq, sq), bool)),
            )
        else:
            mask = jnp.ones((sq, sq), bool)
        bm, bl, bpv = _block_attn(qf, kt, vt, mask, sm_scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l_new = l * alpha + bl * beta
        acc_new = acc * alpha + bpv * beta
        # rotate K/V to the next ring position (ICI neighbor exchange)
        kt = jax.lax.ppermute(kt, axis_name, perm)
        vt = jax.lax.ppermute(vt, axis_name, perm)
        return m_new, l_new, acc_new, kt, vt

    b, h, _, d = q.shape
    m0 = jnp.full((b, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    # the carry becomes device-varying inside the loop; mark the initial
    # values as varying over the ring axis so the loop types are stable
    m0, l0, acc0 = pcast((m0, l0, acc0), (axis_name,), to='varying')
    m, l, acc, _, _ = jax.lax.fori_loop(
        0, n, step, (m0, l0, acc0, k.astype(jnp.float32), v.astype(jnp.float32))
    )
    # guard fully-masked rows (can't happen with causal j==my triangle, but
    # keeps the non-square/edge cases NaN-free)
    l = jnp.maximum(l, 1e-30)
    return (acc / l).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
    seq_axis: str = "sequence", causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """shard_map wrapper: shards the sequence dim of (b, h, seq, d) inputs
    over ``seq_axis`` and runs the ring."""
    spec = PartitionSpec(None, None, seq_axis, None)
    fn = shard_map(
        functools.partial(
            ring_attention, axis_name=seq_axis, causal=causal, sm_scale=sm_scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
