"""Sharded (multi-chip) serving: tensor-parallel KV-cache generation.

A 7B+ model doesn't fit one chip's HBM at serving time, and even when it
does, decode throughput scales with aggregate HBM bandwidth — so serving
wants the same mesh machinery as training. This module jits
``models.decode.generate`` over a Mesh with the training stack's own
logical-axis rules (parallel/mesh.py): attention heads and MLP hidden
shard over ``tensor``, the batch shards over the data-like axes, and XLA
inserts the collectives (one psum per attention/MLP block output — the
standard Megatron-style decode pattern) so they ride ICI.

The KV cache never crosses the API: it is created inside the jitted
program and XLA propagates shardings onto it from the sharded K/V
projections (cache kv-heads follow ``tensor``, batch follows data), so
each chip holds only its slice of the cache.

The continuous-batching slot engine (serve/server.py) is the exception:
its caches ARE the API — a persistent (slots, max_seq) KV block (or
paged pool) that insert/clear/segment programs mutate across calls. The
second half of this module shards that engine. Every KV storage array
models/decode.py defines keeps its kv-heads on axis 2 — dense k/v
``(L, slots, kv, S, hd)`` and their scales ``(L, slots, kv, S)``, paged
pools ``(L, pages+1, kv, ps[, hd])``, prefill row caches
``(L, 1, kv, width[, hd])`` — so ONE PartitionSpec
(:func:`kv_partition_spec`, heads over ``tensor`` via the
logical-axis rules) shards all of them, and a rank test (>= 4-d = KV
storage) tells them apart from the replicated operands (SlotState, page
tables, slot indices, tokens, logits). Two builders split the work by
what correctness needs:

* :func:`kv_shard_map` — the pure data-movement primitives
  (``cache_insert_row`` / ``cache_clear_row`` / ``paged_insert_row`` /
  ``paged_clear_pages`` / ``gather_pages``) run under full-manual
  ``shard_map``: no cross-shard math anywhere in their bodies, so each
  shard performs bitwise the single-device program on its head slice.
* :func:`kv_jit` — the transformer programs (prefill, resume, slot and
  paged decode segments) run as ``jax.jit`` with explicit, stable
  NamedShardings and GSPMD-inserted collectives (the same mechanism as
  :func:`make_sharded_generate`, which the serving identity suite pins
  down as bitwise token-identical on a host mesh). Full-manual
  shard_map would silently drop the attention-output/MLP psums, and
  partial-manual shard_map needs jax >= 0.8 (parallel/compat.py), so
  explicit-sharding jit is the one mechanism that is exact on every
  runtime this tree supports.

Works with raw bf16 params or the int8 export (models/quant.py): the
quantized ``{"q", "s"}`` leaves carry the same logical axes as the
weights they replace, scales sharded like the output channel they scale.

The program contracts this module builds on — PartitionSpec axes inside
the ``MESH_AXES`` vocabulary, ``kv_partition_spec`` keeping kv-heads at
axis 2, donated buffers never read after the donating call, no host
effects inside traced bodies — are enforced statically by the
``jaxcontract`` analyzer pass, and the runtime retrace sentinel
(``TPU_K8S_RETRACE=1``, ``make jax-check``) proves each builder's
programs compile exactly once per input signature in steady state
(docs/guide/static-analysis.md).

The reference provisioner has no inference plane (SURVEY §0); this
completes the serving side of the in-tree stack the same way
make_sharded_train_step completes training (train/trainer.py:107).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpu_kubernetes.models import logical_axes
from tpu_kubernetes.models.decode import generate
from tpu_kubernetes.models.llama import ModelConfig
from tpu_kubernetes.models.quant import is_quantized
from tpu_kubernetes.parallel.compat import shard_map_compat
from tpu_kubernetes.parallel.mesh import (
    batch_sharding,
    logical_to_spec,
    param_shardings,
)


def serving_param_shardings(params: dict, cfg: ModelConfig, mesh: Mesh):
    """Shardings for a serving pytree: the model's logical axes mapped
    onto the mesh, extended leaf-wise over int8-quantized ``{"q", "s"}``
    leaves — ``q`` shards exactly like the weight it replaces, ``s``
    (shape ``(..., 1, out)``) like the weight with the contraction dim
    replicated."""
    axes = logical_axes(cfg)
    base = param_shardings(axes, mesh)

    def extend(leaf, sharding):
        if not is_quantized(leaf):
            return sharding
        spec = sharding.spec
        # scale's axis -2 is the kept (size-1) contraction dim — replicate
        s_spec = PartitionSpec(*spec[:-2], None, spec[-1]) if len(spec) >= 2 \
            else spec
        return {
            "q": sharding,
            "s": NamedSharding(mesh, s_spec),
        }

    return jax.tree_util.tree_map(
        extend, params, base,
        is_leaf=lambda x: is_quantized(x) or not isinstance(x, dict),
    )


def make_sharded_generate(
    cfg: ModelConfig, mesh: Mesh, params: dict, *,
    max_new_tokens: int, temperature: float = 0.0, top_k: int = 0,
    top_p: float = 0.0, eos_id: int | None = None, pad_id: int = 0,
    cache_span: int | None = None, kv_quant: bool = False,
    shard_batch: bool = True,
) -> tuple[Callable, Any, NamedSharding]:
    """→ (generate_fn(params, prompt, rng=None, prompt_lengths=None) ->
    tokens, param shardings, prompt sharding). Mirrors
    make_sharded_train_step's contract: the caller ``jax.device_put``s
    params/prompt with the returned shardings and calls the function;
    tokens come back replicated. ``rng`` feeds the sampler
    (temperature > 0) — it is part of the compiled signature (replicated)
    so successive serving calls can actually draw different samples;
    omitted, it defaults to a fixed key (fine for greedy decoding).
    ``prompt_lengths`` serves a right-padded ragged batch (replicated —
    it is (batch,) int32, bytes not worth sharding); ``eos_id``/``pad_id``
    are static per compiled program like the sampling knobs.
    ``shard_batch=False`` replicates the prompt instead of sharding it
    over the data-like axes — what a live server passes, since its
    requests are batch-1 rows an ``expert``-axis mesh (MoE serving)
    could not split."""
    p_shardings = serving_param_shardings(params, cfg, mesh)
    prompt_sharding = (batch_sharding(mesh) if shard_batch
                       else NamedSharding(mesh, PartitionSpec()))
    replicated = NamedSharding(mesh, PartitionSpec())

    def _gen(params, prompt, rng, prompt_lengths=None):
        return generate(
            params, prompt, cfg, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
            prompt_lengths=prompt_lengths, eos_id=eos_id, pad_id=pad_id,
            cache_span=cache_span, kv_quant=kv_quant,
        )

    jitted = jax.jit(
        _gen,
        in_shardings=(p_shardings, prompt_sharding, replicated),
        out_shardings=replicated,
    )
    jitted_ragged = jax.jit(
        _gen,
        in_shardings=(p_shardings, prompt_sharding, replicated, replicated),
        out_shardings=replicated,
    )

    def run(params, prompt, rng: jax.Array | None = None,
            prompt_lengths: jax.Array | None = None):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        if prompt_lengths is None:
            return jitted(params, prompt, rng)
        return jitted_ragged(params, prompt, rng, prompt_lengths)

    return run, p_shardings, prompt_sharding


# -- sharded continuous batching: the slot/paged engine's program set -------


def kv_partition_spec(mesh: Mesh) -> PartitionSpec:
    """The one KV-storage sharding rule: every cache array in
    models/decode.py keeps its kv-heads on axis 2 — dense k/v
    ``(L, slots, kv, S, hd)``, scales ``(L, slots, kv, S)``, paged
    pools ``(L, pages+1, kv, ps[, hd])``, prefill row caches
    ``(L, 1, kv, width[, hd])`` — so heads shard over ``tensor``
    (DEFAULT_RULES ``kv`` → ``tensor``; dropped when the mesh has no
    non-trivial tensor axis) and every other dim replicates (trailing
    dims are implicitly replicated by PartitionSpec)."""
    return logical_to_spec((None, None, "kv"), mesh=mesh)


def _kv_spec_of(leaf, kv: PartitionSpec) -> PartitionSpec:
    # rank test: in the slot/paged engine's programs, KV storage is the
    # only operand >= 4-d; SlotState rows, page tables, slot indices,
    # tokens, and logits are all <= 2-d and replicate
    return kv if getattr(leaf, "ndim", 0) >= 4 else PartitionSpec()


def kv_tree_shardings(tree: Any, mesh: Mesh) -> Any:
    """NamedShardings for a KVCache / PagedKVCache pytree (or any tree
    mixing KV storage with small replicated leaves) under the
    :func:`kv_partition_spec` rule — what the engine ``jax.device_put``s
    its persistent caches with at init and after a cold reset."""
    kv = kv_partition_spec(mesh)
    return jax.tree_util.tree_map(
        lambda a: NamedSharding(mesh, _kv_spec_of(a, kv)), tree
    )


def kv_shard_map(fn: Callable, mesh: Mesh, example_args: tuple,
                 donate_argnums: tuple = ()) -> Callable:
    """Run a pure data-movement slot/paged primitive (insert, clear,
    page wipe, page gather) under FULL-MANUAL shard_map: KV storage
    shards by :func:`kv_partition_spec`, everything else (slot indices,
    page-id vectors, row metadata) replicates. The bodies do no
    cross-shard math — pads, dynamic_update_slices, and gathers along
    non-head axes — so each shard computes bitwise the single-device
    program on its head slice; full-manual mode (empty ``axis_names``)
    compiles on every jax this tree supports (parallel/compat.py).
    ``example_args`` fixes the in/out pytree structure (via
    ``jax.eval_shape``) so None-able KVCache fields resolve; matching
    in/out specs keep ``donate_argnums`` effective across the engine's
    repeated calls."""
    kv = kv_partition_spec(mesh)
    in_specs = jax.tree_util.tree_map(
        lambda a: _kv_spec_of(a, kv), tuple(example_args)
    )
    out_specs = jax.tree_util.tree_map(
        lambda a: _kv_spec_of(a, kv), jax.eval_shape(fn, *example_args)
    )
    mapped = shard_map_compat(fn, mesh, in_specs, out_specs,
                              check_vma=False)
    return jax.jit(mapped, donate_argnums=donate_argnums)


def kv_jit(fn: Callable, mesh: Mesh, example_args: tuple, *,
           params_shardings: Any = None,
           donate_argnums: tuple = ()) -> Callable:
    """jit a model program (prefill, resume, slot/paged decode segment)
    for the mesh with explicit, STABLE shardings: arg 0 is the params
    pytree (``params_shardings`` from :func:`serving_param_shardings`),
    KV storage shards by :func:`kv_partition_spec`, and everything else
    — tokens, lengths, SlotState, page tables, logits — replicates.
    Outputs follow the same rank rule (via ``jax.eval_shape``), so a
    donated cache keeps one sharding across every segment (GSPMD never
    drifts the layout between calls, and donation actually reuses the
    buffer). GSPMD inserts the collectives, the mechanism the serving
    identity suite pins down as bitwise token-identical to
    single-device decode on a host mesh."""
    kv = kv_partition_spec(mesh)

    def shard_of(leaf):
        return NamedSharding(mesh, _kv_spec_of(leaf, kv))

    args = tuple(example_args)
    in_sh = [jax.tree_util.tree_map(shard_of, a) for a in args]
    if params_shardings is not None:
        in_sh[0] = params_shardings
    out_sh = jax.tree_util.tree_map(shard_of, jax.eval_shape(fn, *args))
    return jax.jit(fn, in_shardings=tuple(in_sh), out_shardings=out_sh,
                   donate_argnums=donate_argnums)
