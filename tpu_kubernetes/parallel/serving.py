"""Sharded (multi-chip) serving: tensor-parallel KV-cache generation.

A 7B+ model doesn't fit one chip's HBM at serving time, and even when it
does, decode throughput scales with aggregate HBM bandwidth — so serving
wants the same mesh machinery as training. This module jits
``models.decode.generate`` over a Mesh with the training stack's own
logical-axis rules (parallel/mesh.py): attention heads and MLP hidden
shard over ``tensor``, the batch shards over the data-like axes, and XLA
inserts the collectives (one psum per attention/MLP block output — the
standard Megatron-style decode pattern) so they ride ICI.

The KV cache never crosses the API: it is created inside the jitted
program and XLA propagates shardings onto it from the sharded K/V
projections (cache kv-heads follow ``tensor``, batch follows data), so
each chip holds only its slice of the cache.

Works with raw bf16 params or the int8 export (models/quant.py): the
quantized ``{"q", "s"}`` leaves carry the same logical axes as the
weights they replace, scales sharded like the output channel they scale.

The reference provisioner has no inference plane (SURVEY §0); this
completes the serving side of the in-tree stack the same way
make_sharded_train_step completes training (train/trainer.py:107).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpu_kubernetes.models import logical_axes
from tpu_kubernetes.models.decode import generate
from tpu_kubernetes.models.llama import ModelConfig
from tpu_kubernetes.models.quant import is_quantized
from tpu_kubernetes.parallel.mesh import batch_sharding, param_shardings


def serving_param_shardings(params: dict, cfg: ModelConfig, mesh: Mesh):
    """Shardings for a serving pytree: the model's logical axes mapped
    onto the mesh, extended leaf-wise over int8-quantized ``{"q", "s"}``
    leaves — ``q`` shards exactly like the weight it replaces, ``s``
    (shape ``(..., 1, out)``) like the weight with the contraction dim
    replicated."""
    axes = logical_axes(cfg)
    base = param_shardings(axes, mesh)

    def extend(leaf, sharding):
        if not is_quantized(leaf):
            return sharding
        spec = sharding.spec
        # scale's axis -2 is the kept (size-1) contraction dim — replicate
        s_spec = PartitionSpec(*spec[:-2], None, spec[-1]) if len(spec) >= 2 \
            else spec
        return {
            "q": sharding,
            "s": NamedSharding(mesh, s_spec),
        }

    return jax.tree_util.tree_map(
        extend, params, base,
        is_leaf=lambda x: is_quantized(x) or not isinstance(x, dict),
    )


def make_sharded_generate(
    cfg: ModelConfig, mesh: Mesh, params: dict, *,
    max_new_tokens: int, temperature: float = 0.0, top_k: int = 0,
    top_p: float = 0.0, eos_id: int | None = None, pad_id: int = 0,
    cache_span: int | None = None, kv_quant: bool = False,
) -> tuple[Callable, Any, NamedSharding]:
    """→ (generate_fn(params, prompt, rng=None, prompt_lengths=None) ->
    tokens, param shardings, prompt sharding). Mirrors
    make_sharded_train_step's contract: the caller ``jax.device_put``s
    params/prompt with the returned shardings and calls the function;
    tokens come back replicated. ``rng`` feeds the sampler
    (temperature > 0) — it is part of the compiled signature (replicated)
    so successive serving calls can actually draw different samples;
    omitted, it defaults to a fixed key (fine for greedy decoding).
    ``prompt_lengths`` serves a right-padded ragged batch (replicated —
    it is (batch,) int32, bytes not worth sharding); ``eos_id``/``pad_id``
    are static per compiled program like the sampling knobs."""
    p_shardings = serving_param_shardings(params, cfg, mesh)
    prompt_sharding = batch_sharding(mesh)
    replicated = NamedSharding(mesh, PartitionSpec())

    def _gen(params, prompt, rng, prompt_lengths=None):
        return generate(
            params, prompt, cfg, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
            prompt_lengths=prompt_lengths, eos_id=eos_id, pad_id=pad_id,
            cache_span=cache_span, kv_quant=kv_quant,
        )

    jitted = jax.jit(
        _gen,
        in_shardings=(p_shardings, prompt_sharding, replicated),
        out_shardings=replicated,
    )
    jitted_ragged = jax.jit(
        _gen,
        in_shardings=(p_shardings, prompt_sharding, replicated, replicated),
        out_shardings=replicated,
    )

    def run(params, prompt, rng: jax.Array | None = None,
            prompt_lengths: jax.Array | None = None):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        if prompt_lengths is None:
            return jitted(params, prompt, rng)
        return jitted_ragged(params, prompt, rng, prompt_lengths)

    return run, p_shardings, prompt_sharding
