"""Multi-host bootstrap: consume the env contract the provisioner emits.

The gcp-tpu node module bakes /etc/tpu-kubernetes/jax.env into every slice
host (terraform/modules/files/install_tpu_agent.sh.tpl):

  JAX_COORDINATOR_ADDRESS  host:port of process 0
  JAX_NUM_PROCESSES        hosts in the slice
  JAX_PROCESS_ID           this host's index
  TPU_ACCELERATOR_TYPE / TPU_SLICE_TOPOLOGY / TPU_SLICE_NAME

This module is the consumer side (SURVEY §5.8): the training job calls
:func:`initialize` first thing and the slice assembles over DCN while
collectives inside the slice ride ICI. On single-host (or when the env is
absent) it is a no-op, so the same entrypoint runs everywhere.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class DistributedEnv:
    coordinator_address: str | None
    num_processes: int
    process_id: int
    accelerator_type: str | None
    slice_topology: str | None
    # multislice (DCN-connected pod slices): the provisioner emits these
    # when a cluster spans several slices (MEGASCALE_* is the libtpu
    # convention; the node module mirrors it into jax.env)
    num_slices: int = 1
    slice_id: int = 0

    @property
    def multi_host(self) -> bool:
        return self.num_processes > 1

    @property
    def multi_slice(self) -> bool:
        return self.num_slices > 1


def read_env(env: dict[str, str] | None = None) -> DistributedEnv:
    e = env if env is not None else os.environ
    return DistributedEnv(
        coordinator_address=e.get("JAX_COORDINATOR_ADDRESS"),
        num_processes=int(e.get("JAX_NUM_PROCESSES", "1")),
        process_id=int(e.get("JAX_PROCESS_ID", "0")),
        accelerator_type=e.get("TPU_ACCELERATOR_TYPE"),
        slice_topology=e.get("TPU_SLICE_TOPOLOGY"),
        num_slices=int(e.get("MEGASCALE_NUM_SLICES", "1")),
        slice_id=int(e.get("MEGASCALE_SLICE_ID", "0")),
    )


def initialize(env: dict[str, str] | None = None) -> DistributedEnv:
    """Call jax.distributed.initialize from the provisioner's env contract.
    No-op on single-host. Safe to call exactly once, before device use."""
    denv = read_env(env)
    if denv.multi_host and denv.coordinator_address:
        import jax

        jax.distributed.initialize(
            coordinator_address=denv.coordinator_address,
            num_processes=denv.num_processes,
            process_id=denv.process_id,
        )
    return denv


# the path the packer image bake pre-warms (packer/scripts/bake_tpu_agent.sh)
# — enabling the cache here is what makes that warming reach the job runtime
DEFAULT_COMPILE_CACHE = "/var/cache/tpu-kubernetes/xla"


def enable_persistent_compile_cache(cache_dir: str | None = None) -> str:
    """Serialize compiled executables to disk so repeat runs skip XLA
    compilation entirely — create→first-step latency drops on every boot
    after the first (and on tunneled chips it also sidesteps the remote
    compile service). Resolution order: explicit arg →
    ``JAX_COMPILATION_CACHE_DIR`` env → the image's pre-warmed cache dir →
    a user cache dir when that path isn't writable. Returns the dir used
    ("" = caching disabled by explicit empty setting)."""
    import jax

    if cache_dir is None:
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir is None:
        cache_dir = DEFAULT_COMPILE_CACHE
        if not os.access(os.path.dirname(cache_dir) or "/", os.W_OK):
            cache_dir = os.path.join(
                os.path.expanduser("~"), ".cache", "tpu-kubernetes", "xla"
            )
    if not cache_dir:
        return ""
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        return ""  # unwritable: run uncached rather than fail the job
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return cache_dir
