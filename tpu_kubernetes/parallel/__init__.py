"""tpu_kubernetes.parallel — mesh/sharding, multi-host bootstrap, and
context parallelism for the in-tree training stack."""

from tpu_kubernetes.parallel.distributed import (  # noqa: F401
    enable_persistent_compile_cache,
    DistributedEnv,
    initialize,
    read_env,
)
from tpu_kubernetes.parallel.mesh import (  # noqa: F401
    DATA_AXES,
    DEFAULT_RULES,
    MESH_AXES,
    batch_sharding,
    create_hybrid_mesh,
    create_mesh,
    data_axes_in,
    device_prefix_for,
    logical_to_spec,
    mesh_shape_for_devices,
    param_shardings,
)
from tpu_kubernetes.parallel.pipeline import (  # noqa: F401
    pipeline_forward,
    pipeline_loss_fn,
    pipeline_param_shardings,
)
from tpu_kubernetes.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_sharded,
)
from tpu_kubernetes.parallel.serving import (  # noqa: F401
    make_sharded_generate,
    serving_param_shardings,
)
