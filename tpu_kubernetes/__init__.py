"""tpu-kubernetes: TPU-native multi-cloud Kubernetes provisioning framework.

A brand-new framework with the capability surface of the reference
``triton-kubernetes`` CLI (see SURVEY.md): an interactive/scriptable CLI that
creates and destroys a global cluster manager (control plane), Kubernetes
clusters across cloud providers, and individual nodes — persisting each
deployment as a declarative Terraform-JSON state document in a pluggable
backend and applying it through an executor.

Unlike the reference, **Cloud TPU is a first-class provider**: the ``gcp-tpu``
provider stands up v5e/v5p pod slices as slice-shaped node groups, and the
in-tree JAX stack (``tpu_kubernetes.models`` / ``ops`` / ``parallel`` /
``train``) provides the training job that runs on them — sharded over a
``jax.sharding.Mesh`` with XLA collectives riding ICI.

Layer map (mirrors reference SURVEY.md §1):
  cli/       — command dispatch            (ref: cmd/)
  config/    — precedence config system    (ref: viper wiring, cmd/root.go)
  create/    — create workflows            (ref: create/)
  destroy/   — destroy workflows           (ref: destroy/)
  get/       — query workflows             (ref: get/)
  util/      — prompt/UX utilities         (ref: util/)
  state/     — state document model        (ref: state/state.go)
  backend/   — state persistence backends  (ref: backend/)
  shell/     — executors                   (ref: shell/)
  providers/ — provider registry + configs (ref: create/*_{triton,aws,gcp,azure}.go)
  topology/  — typed TPU slice topology    (new; no reference analog)
  models/, ops/, parallel/, train/ — the TPU compute stack (new; north star)
"""

__version__ = "0.8.0"
