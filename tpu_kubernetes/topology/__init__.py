from tpu_kubernetes.topology.tpu import (  # noqa: F401
    TopologyError,
    TpuTopology,
    parse_accelerator_type,
    parse_mesh_shape,
    slice_host_env,
    validate_mesh,
)
