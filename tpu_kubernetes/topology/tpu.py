"""Typed Cloud TPU slice topology.

The reference treats machine shapes as free strings (e.g. GCP machine types
prompted at create/manager_gcp.go:112-324). A TPU pod slice is *one*
schedulable unit spanning multiple hosts (SURVEY §7 hard part #2), so the
gcp-tpu provider models it as a first-class type: parse an accelerator type
like ``v5p-32``, know its chip count / host count / physical ICI topology,
and validate a requested JAX mesh against it **at render time** — before any
money is spent.

Chip-count conventions (Cloud TPU naming):
  * v2/v3/v4/v5p: the suffix counts TensorCores; chips = suffix / 2.
  * v5e (v5litepod) / v6e: the suffix counts chips directly.
Hosts: v4/v5p have 4 chips per host. v5e/v6e single-host slices carry up to
8 chips on the one VM, but **multi-host** v5e/v6e slices place only 4 chips
per VM (per the Cloud TPU v5e/v6e system docs), so e.g. v5e-16 is 4 hosts of
4 chips, not 2 hosts of 8.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import reduce


class TopologyError(Exception):
    pass


# generation → (suffix counts cores?, chips_per_host, 3d ICI?)
_GENERATIONS = {
    "v2": (True, 4, False),
    "v3": (True, 4, False),
    "v4": (True, 4, True),
    "v5p": (True, 4, True),
    "v5e": (False, 8, False),
    "v5litepod": (False, 8, False),
    "v6e": (False, 8, False),
}

# well-known physical topologies (generation, chips) → "XxY[xZ]"
_KNOWN_TOPOLOGIES: dict[tuple[str, int], str] = {
    ("v5e", 1): "1x1",
    ("v5e", 4): "2x2",
    ("v5e", 8): "2x4",
    ("v5e", 16): "4x4",
    ("v5e", 32): "4x8",
    ("v5e", 64): "8x8",
    ("v5e", 128): "8x16",
    ("v5e", 256): "16x16",
    ("v6e", 1): "1x1",
    ("v6e", 4): "2x2",
    ("v6e", 8): "2x4",
    ("v6e", 16): "4x4",
    ("v6e", 32): "4x8",
    ("v6e", 64): "8x8",
    ("v6e", 128): "8x16",
    ("v6e", 256): "16x16",
    ("v4", 4): "2x2x1",
    ("v4", 8): "2x2x2",
    ("v4", 16): "2x2x4",
    ("v4", 32): "2x4x4",
    ("v4", 64): "4x4x4",
    ("v5p", 4): "2x2x1",
    ("v5p", 8): "2x2x2",
    ("v5p", 16): "2x2x4",
    ("v5p", 32): "2x4x4",
    ("v5p", 64): "4x4x4",
    ("v5p", 128): "4x4x8",
    ("v5p", 256): "4x8x8",
    ("v5p", 512): "8x8x8",
}


@dataclass(frozen=True)
class TpuTopology:
    accelerator_type: str  # e.g. "v5p-32"
    generation: str        # e.g. "v5p"
    chips: int
    topology: str          # physical ICI mesh, e.g. "2x4x4"
    hosts: int
    chips_per_host: int

    @property
    def dims(self) -> tuple[int, ...]:
        return tuple(int(d) for d in self.topology.split("x"))

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1

    @property
    def devices(self) -> int:
        """JAX device count on the slice (1 device per chip: v4+ megacore;
        v5e/v6e have one core per chip)."""
        return self.chips

    @property
    def api_name(self) -> str:
        """The accelerator type string the Cloud TPU v2 API accepts. v5e is
        named ``v5litepod-N`` by the API; other generations use their
        canonical name verbatim."""
        if self.generation == "v5e":
            return f"v5litepod-{self.chips}"
        return self.accelerator_type


def parse_accelerator_type(accelerator_type: str) -> TpuTopology:
    """``v5p-32`` → TpuTopology(chips=16, topology='2x4x4', hosts=4, …)."""
    parts = accelerator_type.lower().split("-")
    if len(parts) != 2 or not parts[1].isdigit():
        raise TopologyError(
            f"invalid accelerator type {accelerator_type!r}: "
            "expected <generation>-<size>, e.g. v5e-4 or v5p-32"
        )
    gen, size = parts[0], int(parts[1])
    if gen == "v5litepod":
        gen = "v5e"
    if gen not in _GENERATIONS:
        raise TopologyError(
            f"unknown TPU generation {gen!r} "
            f"(known: {sorted(set(_GENERATIONS) - {'v5litepod'})})"
        )
    counts_cores, chips_per_host, is_3d = _GENERATIONS[gen]
    if counts_cores:
        if size % 2:
            raise TopologyError(
                f"{accelerator_type}: {gen} sizes count TensorCores and must be even"
            )
        chips = size // 2
    else:
        chips = size
    if chips < 1:
        raise TopologyError(f"{accelerator_type}: no chips")
    topology = _KNOWN_TOPOLOGIES.get((gen, chips)) or _factor_topology(chips, is_3d)
    if gen in ("v5e", "v6e") and chips > 8:
        # multi-host v5e/v6e slices place 4 chips per VM
        chips_per_host = 4
    hosts = max(1, math.ceil(chips / chips_per_host))
    return TpuTopology(
        accelerator_type=f"{gen}-{size}",
        generation=gen,
        chips=chips,
        topology=topology,
        hosts=hosts,
        chips_per_host=min(chips, chips_per_host),
    )


def _factor_topology(chips: int, is_3d: bool) -> str:
    """Near-cubic/square factorization for sizes not in the table."""
    if not is_3d:
        x = 1
        for cand in range(int(math.isqrt(chips)), 0, -1):
            if chips % cand == 0:
                x = cand
                break
        return f"{x}x{chips // x}"
    best = (1, 1, chips)
    best_score = float("inf")
    for a in range(1, int(round(chips ** (1 / 3))) + 2):
        if chips % a:
            continue
        rest = chips // a
        for b in range(a, int(math.isqrt(rest)) + 1):
            if rest % b:
                continue
            c = rest // b
            score = c - a  # flattest factorization wins
            if score < best_score:
                best, best_score = (a, b, c), score
    return "x".join(str(d) for d in best)


def parse_mesh_shape(spec: str) -> dict[str, int]:
    """``"data=2,fsdp=8"`` → {"data": 2, "fsdp": 8}."""
    out: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise TopologyError(
                f"invalid mesh_shape entry {part!r}: expected axis=size"
            )
        axis, _, size = part.partition("=")
        if not size.strip().isdigit():
            raise TopologyError(
                f"mesh_shape axis {axis.strip()!r} size must be an integer"
            )
        out[axis.strip()] = int(size)
    return out


def validate_mesh(topology: TpuTopology, mesh_shape: dict[str, int]) -> None:
    """Check a requested JAX mesh fits the slice **before** provisioning.

    The mesh's total device count must equal the slice's, and every axis must
    divide it (so `jax.sharding.Mesh(mesh_utils.create_device_mesh(...))`
    can actually be built on the slice).
    """
    sizes = list(mesh_shape.values())
    if any(s < 1 for s in sizes):
        raise TopologyError(f"mesh axes must be >=1, got {mesh_shape}")
    total = reduce(lambda a, b: a * b, sizes, 1)
    if total != topology.devices:
        raise TopologyError(
            f"mesh {mesh_shape} wants {total} devices but "
            f"{topology.accelerator_type} has {topology.devices} "
            f"(topology {topology.topology})"
        )


def slice_host_env(
    topology: TpuTopology,
    coordinator_address: str,
    host_index: int,
    extra: dict[str, str] | None = None,
) -> dict[str, str]:
    """Environment to bake into one TPU-VM host's job spec so
    ``jax.distributed.initialize`` assembles the slice over DCN.

    This is the TPU analog of the reference agent's ``--server/--token/
    --ca-checksum`` trio (reference:
    gcp-rancher-k8s-host/files/install_rancher_agent.sh.tpl:44): the three
    facts a worker needs to join the collective.
    """
    if not 0 <= host_index < topology.hosts:
        raise TopologyError(
            f"host_index {host_index} out of range for {topology.hosts} hosts"
        )
    env = {
        "JAX_COORDINATOR_ADDRESS": coordinator_address,
        "JAX_NUM_PROCESSES": str(topology.hosts),
        "JAX_PROCESS_ID": str(host_index),
        "TPU_ACCELERATOR_TYPE": topology.accelerator_type,
        "TPU_SLICE_TOPOLOGY": topology.topology,
        "TPU_CHIPS_PER_HOST": str(topology.chips_per_host),
    }
    if extra:
        env.update(extra)
    return env
