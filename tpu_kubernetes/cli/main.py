"""CLI command dispatch (the reference's cobra layer).

reference: cmd/root.go (root + config init), cmd/create.go:15-84,
cmd/destroy.go:15-82, cmd/get.go:15-75, cmd/version.go:13-26 — four commands
``create|destroy|get|version``, the first three taking one positional
argument ``manager|cluster|node`` (get: manager|cluster only), plus
``--config`` and ``--non-interactive`` persistent flags.
"""

from __future__ import annotations

import argparse
import json
import sys

import tpu_kubernetes
from tpu_kubernetes import create as create_wf
from tpu_kubernetes import destroy as destroy_wf
from tpu_kubernetes import get as get_wf
from tpu_kubernetes import repair as repair_wf
from tpu_kubernetes.backend import BackendError
from tpu_kubernetes.config import Config, ConfigError
from tpu_kubernetes.get.kubeconfig import KubeconfigError
from tpu_kubernetes.providers.base import ProviderError
from tpu_kubernetes.shell import ExecutorError, ValidationError, default_executor
from tpu_kubernetes.state import StateError
from tpu_kubernetes.topology import TopologyError
from tpu_kubernetes.util import log
from tpu_kubernetes.util.backend_prompt import prompt_for_backend
from tpu_kubernetes.util.prompts import PromptError
from tpu_kubernetes.util.trace import TRACER


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpu-kubernetes",
        description=(
            "TPU-native multi-cloud Kubernetes provisioning: create and "
            "destroy cluster managers, clusters (including Cloud TPU pod "
            "slices), and nodes."
        ),
    )
    parser.add_argument(
        "--config", metavar="FILE",
        help="YAML config for silent install (reference: cmd/root.go:39)",
    )
    parser.add_argument(
        "--non-interactive", action="store_true",
        help="never prompt; missing keys are errors (reference: cmd/root.go:40)",
    )
    parser.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="override a config key (highest precedence; repeatable)",
    )
    parser.add_argument(
        "--timing", action="store_true",
        help="print phase timing JSON to stderr on exit",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress progress output (warnings and errors still print)",
    )
    parser.add_argument(
        "--verbose", action="store_true",
        help="debug-level detail (rendered paths, control-plane calls)",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    create = sub.add_parser("create", help="create a manager, cluster, or node")
    create.add_argument("kind", choices=["manager", "cluster", "node"])

    destroy = sub.add_parser("destroy", help="destroy a manager, cluster, or node")
    destroy.add_argument("kind", choices=["manager", "cluster", "node"])

    get = sub.add_parser(
        "get",
        help="query a manager or cluster, fetch a kubeconfig, list "
             "recorded workflow runs, dump in-process metrics, render "
             "a serving worker's phase-profile breakdown, its goodput "
             "ledger, its flight-recorder black box, or a metric's "
             "scraped history",
    )
    get.add_argument(
        "kind",
        choices=["manager", "cluster", "kubeconfig", "runs", "metrics",
                 "profile", "goodput", "history", "flightrec", "alerts",
                 "incidents", "trace", "actions"],
        help="profile renders the worker's phase table — cold (prefill) "
             "vs warm (prefill_warm) prefills split out, so prefix-cache "
             "savings are read off one row pair; goodput renders the "
             "token ledger (useful/cancelled/expired/shed-spent/bubble), "
             "slot-engine bubble fraction, and analytical MFU/roofline; "
             "history scrapes a metric over a few spaced cycles and "
             "renders per-series latest/rate/min/max + a sparkline; "
             "flightrec renders the engine's live black box "
             "(GET /debug/flightrec); alerts renders the worker's rule "
             "alerts and silences (GET /debug/alerts); incidents lists "
             "local incident bundles (see --dir); trace stitches one "
             "distributed trace's spans from every --targets instance "
             "into a cross-instance tree with a critical-path breakdown; "
             "actions lists the fleet controller's action ledger "
             "(see --file)",
    )
    get.add_argument(
        "metric", nargs="?", metavar="METRIC",
        help="with history: the metric family to query "
             "(e.g. tpu_serve_requests_total); with trace: the trace id "
             "to stitch (32 hex chars, from a traceparent header or a "
             "latency exemplar)",
    )
    get.add_argument(
        "--manager", metavar="NAME",
        help="cluster manager to query (sugar for --set cluster_manager=NAME)",
    )
    get.add_argument(
        "--json", dest="as_json", action="store_true",
        help="with runs/profile/goodput/history/flightrec/alerts/"
             "incidents: dump the raw JSON instead of the table",
    )
    get.add_argument(
        "--target", metavar="HOST:PORT", default="127.0.0.1:8000",
        help="with profile/goodput/flightrec/alerts: the serving worker "
             "to query (default 127.0.0.1:8000)",
    )
    get.add_argument(
        "--dir", dest="incidents_dir", metavar="DIR",
        default=None,
        help="with incidents: the bundle directory to list (default "
             "runs/incidents, or TPU_K8S_INCIDENTS_DIR)",
    )
    get.add_argument(
        "--targets", metavar="HOST:PORT[,HOST:PORT...]", default=None,
        help="with history/trace: comma-separated worker endpoints to "
             "query (default: the --target value)",
    )
    get.add_argument(
        "--file", dest="actions_file", metavar="FILE", default=None,
        help="with actions: the JSONL ledger sink to list (default "
             "runs/actions.jsonl, or TPU_K8S_ACTIONS_FILE)",
    )
    get.add_argument(
        "--window", type=float, default=60.0, metavar="SECONDS",
        help="with history: the trailing window rates/sparklines cover "
             "(default 60)",
    )
    get.add_argument(
        "--samples", type=int, default=5, metavar="N",
        help="with history: scrape cycles to take before rendering "
             "(default 5)",
    )
    get.add_argument(
        "--interval", type=float, default=1.0, metavar="SECONDS",
        help="with history: seconds between the scrape cycles "
             "(default 1)",
    )

    repair = sub.add_parser(
        "repair",
        help="re-apply a cluster after TPU preemption or node loss "
             "(no reference analog)",
    )
    repair.add_argument("kind", choices=["cluster"])
    repair.add_argument(
        "--auto", action="store_true",
        help="diagnose node health via the manager and report; exits "
             "nonzero when nodes are unhealthy (add --replace_nodes to act)",
    )
    repair.add_argument(
        "--replace_nodes", action="store_true",
        help="destroy + re-create node modules (with --auto: exactly the "
             "diagnosed-unhealthy ones)",
    )
    repair.add_argument(
        "--grace", type=int, metavar="SECONDS",
        help="with --auto: re-check after this many seconds and spare "
             "nodes that recover (a kubelet restart shows as a NotReady "
             "blip)",
    )

    monitor = sub.add_parser(
        "monitor",
        help="watch a fleet of serving workers: scrape each /metrics, "
             "render per-instance RPS / latency / TTFT / tokens-per-sec "
             "/ queue depth, and evaluate SLO burn-rate alerts",
    )
    monitor.add_argument(
        "--targets", metavar="HOST:PORT[,HOST:PORT...]",
        default="127.0.0.1:8000",
        help="comma-separated worker endpoints (default 127.0.0.1:8000)",
    )
    monitor.add_argument(
        "--interval", type=float, default=5.0, metavar="SECONDS",
        help="seconds between scrape cycles (default 5)",
    )
    monitor.add_argument(
        "--json", dest="as_json", action="store_true",
        help="emit one JSON snapshot per cycle instead of the table",
    )
    monitor.add_argument(
        "--once", action="store_true",
        help="one scrape cycle, then exit (scripting/smoke checks); a "
             "cold start takes one short-spaced second scrape so the "
             "rate columns are real, not '-'",
    )
    monitor.add_argument(
        "--window", type=float, default=60.0, metavar="SECONDS",
        help="trailing window the rate and sparkline trend columns "
             "cover (default 60)",
    )

    fleet = sub.add_parser(
        "fleet",
        help="close the observability loop: scrape the fleet, evaluate "
             "the standard alert rules, and let the controller "
             "remediate (scale up/down, drain-and-replace) — dry-run "
             "by default, every action ledgered (obs/controller.py)",
    )
    fleet.add_argument("action", choices=["control"])
    fleet.add_argument(
        "--targets", metavar="HOST:PORT[,HOST:PORT...]",
        default="127.0.0.1:8000",
        help="comma-separated worker endpoints (default 127.0.0.1:8000)",
    )
    fleet.add_argument(
        "--interval", type=float, default=5.0, metavar="SECONDS",
        help="seconds between control cycles (default 5)",
    )
    fleet.add_argument(
        "--once", action="store_true",
        help="one control cycle, then exit (scripting/smoke checks)",
    )
    fleet.add_argument(
        "--max-cycles", type=int, default=None, metavar="N",
        help="stop after N control cycles (default: run until ^C)",
    )
    fleet.add_argument(
        "--json", dest="as_json", action="store_true",
        help="emit one JSON snapshot per cycle (replicas, per-instance "
             "state, active alerts, actions) instead of the status line",
    )
    fleet.add_argument(
        "--apply", action="store_true",
        help="actually actuate: scale via the Terraform executor and "
             "drain via POST /drain; without this every decision is "
             "recorded as suppressed (equivalent to "
             "TPU_K8S_CONTROLLER_DRY_RUN=0)",
    )

    bench = sub.add_parser(
        "bench",
        help="run the in-tree CPU-deterministic microbenchmark suites "
             "(obs/perfbench.py): median-of-N timings appended to "
             "benchmarks/history/, with optional regression gating",
    )
    bench.add_argument("action", choices=["run"])
    bench.add_argument(
        "--suite", choices=["ops", "serve", "train", "all"], default="all",
        help="which bench suite to run (default all)",
    )
    bench.add_argument(
        "--check", action="store_true",
        help="compare against the rolling baseline and exit 3 on "
             "regression",
    )
    bench.add_argument(
        "--require-baseline", action="store_true",
        help="with --check: also exit 3 when a baselined metric is "
             "missing from the run entirely (the CI gate's guard "
             "against silently-deleted benches)",
    )
    bench.add_argument(
        "--json", dest="as_json", action="store_true",
        help="emit one JSON object instead of the table",
    )
    bench.add_argument(
        "--baseline", metavar="FILE",
        help="JSONL baseline file for --check (default: the suite's own "
             "history under --history-dir)",
    )
    bench.add_argument(
        "--threshold", type=float, default=1.5, metavar="RATIO",
        help="regression when current > RATIO x baseline (default 1.5)",
    )
    bench.add_argument(
        "--n", type=int, default=5, metavar="N",
        help="timed iterations per bench; the median is recorded "
             "(default 5)",
    )
    bench.add_argument(
        "--warmup", type=int, default=2, metavar="N",
        help="untimed warmup iterations (absorbs jit trace+compile; "
             "default 2)",
    )
    bench.add_argument(
        "--only", metavar="SUBSTR",
        help="run only benches whose name contains SUBSTR",
    )
    bench.add_argument(
        "--history-dir", default="benchmarks/history", metavar="DIR",
        help="where per-suite JSONL history accumulates "
             "(default benchmarks/history)",
    )

    analyze = sub.add_parser(
        "analyze",
        help="run the repo-native invariant analyzer over the package: "
             "closed-vocabulary contracts (fault sites, metrics, ledger "
             "classes, alert kinds), the env contract, concurrency "
             "discipline, and JAX program contracts; exits 1 on "
             "findings not in the baseline",
    )
    analyze.add_argument(
        "--json", dest="as_json", action="store_true",
        help="emit the machine-readable findings payload with per-pass "
             "wall times (schema version pinned by "
             "tests/test_analysis.py)",
    )
    analyze.add_argument(
        "--pass", dest="passes", action="append",
        choices=["contracts", "env", "concurrency", "jaxcontract"],
        metavar="NAME",
        help="run only this pass (repeatable; default: all of "
             "contracts, env, concurrency, jaxcontract)",
    )
    analyze.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file to suppress exactly the "
             "current findings (atomic, sorted) and print an "
             "added/removed diff summary to stderr; exits 0",
    )
    analyze.add_argument(
        "--root", metavar="DIR", default=None,
        help="repo root to analyze (default: the tree containing the "
             "installed tpu_kubernetes package)",
    )
    analyze.add_argument(
        "--baseline", metavar="FILE", default=None,
        help="baseline file of suppressed findings (default: "
             "analysis-baseline.json under the root; ships empty)",
    )

    sub.add_parser("version", help="print the version")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    log.set_verbosity(quiet=args.quiet, verbose=args.verbose)

    if args.command == "version":
        # reference: cmd/version.go:13-26
        print(f"tpu-kubernetes v{tpu_kubernetes.__version__}")
        return 0

    if args.command == "monitor":
        # fleet observation needs no backend, config, or prompts — just
        # the worker endpoints to scrape (obs/monitor.py)
        from tpu_kubernetes.obs.monitor import run_monitor

        targets = [t.strip() for t in args.targets.split(",") if t.strip()]
        if not targets:
            print("error: monitor needs at least one --targets endpoint",
                  file=sys.stderr)
            return 2
        return run_monitor(
            targets, interval=args.interval, once=args.once,
            as_json=args.as_json, window=args.window,
        )

    if args.command == "get" and args.kind == "history":
        # scrape-and-render a metric's recent history (obs/monitor.py +
        # obs/tsdb.py) — no backend, config, or prompts involved
        from tpu_kubernetes.obs.monitor import run_history

        if not args.metric:
            print("error: get history needs a metric name "
                  "(e.g. tpu_serve_requests_total)", file=sys.stderr)
            return 2
        raw = args.targets if args.targets else args.target
        targets = [t.strip() for t in raw.split(",") if t.strip()]
        if not targets:
            print("error: get history needs at least one target",
                  file=sys.stderr)
            return 2
        return run_history(
            args.metric, targets, window=args.window,
            samples=args.samples, interval=args.interval,
            as_json=args.as_json,
        )

    if args.command == "get" and args.kind == "trace":
        # stitch one distributed trace across the fleet: every --targets
        # instance is asked for GET /debug/trace/<id>; instances that
        # never saw the trace (404) or are down just drop out of the
        # stitch — one live instance with spans is enough to render
        from tpu_kubernetes.obs import tracing

        if not args.metric:
            print("error: get trace needs a trace id "
                  "(32 hex chars, e.g. from a traceparent header)",
                  file=sys.stderr)
            return 2
        raw = args.targets if args.targets else args.target
        targets = [t.strip() for t in raw.split(",") if t.strip()]
        if not targets:
            print("error: get trace needs at least one target",
                  file=sys.stderr)
            return 2
        payloads: dict[str, dict] = {}
        errors: list[str] = []
        for target in targets:
            try:
                payloads[target] = tracing.fetch_trace(target, args.metric)
            except Exception as e:  # noqa: BLE001 — skip dead/unaware instances
                errors.append(f"{target}: {e}")
        if not payloads:
            print(f"error: no instance returned trace {args.metric}:",
                  file=sys.stderr)
            for line in errors:
                print(f"  {line}", file=sys.stderr)
            return 1
        stitched = tracing.stitch_trace(args.metric, payloads)
        if args.as_json:
            print(json.dumps(stitched, indent=2, sort_keys=True))
        else:
            print(tracing.render_trace(stitched), end="")
            for line in errors:
                print(f"  (no data: {line})", file=sys.stderr)
        return 0

    if args.command == "get" and args.kind == "flightrec":
        # a remote worker's GET /debug/flightrec, rendered — same
        # stance as get profile/goodput
        from tpu_kubernetes.obs.flightrec import (
            fetch_flightrec,
            render_flightrec,
        )

        try:
            data = fetch_flightrec(args.target)
        except Exception as e:  # noqa: BLE001 — network errors → exit 1
            print(f"error: cannot fetch flight recorder from "
                  f"{args.target}: {e}", file=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(data, indent=2, sort_keys=True))
        else:
            print(render_flightrec(data), end="")
        return 0

    if args.command == "get" and args.kind == "alerts":
        # a remote worker's GET /debug/alerts, rendered — same stance
        # as get flightrec
        from tpu_kubernetes.obs.alerts import fetch_alerts, render_alerts

        try:
            data = fetch_alerts(args.target)
        except Exception as e:  # noqa: BLE001 — network errors → exit 1
            print(f"error: cannot fetch alerts from {args.target}: {e}",
                  file=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(data, indent=2, sort_keys=True))
        else:
            print(render_alerts(data), end="")
        return 0

    if args.command == "fleet":
        # the self-driving loop needs the worker endpoints, not a
        # backend/config — same stance as monitor (obs/controller.py)
        from tpu_kubernetes.obs.controller import run_controller

        targets = [t.strip() for t in args.targets.split(",") if t.strip()]
        if not targets:
            print("error: fleet control needs at least one --targets "
                  "endpoint", file=sys.stderr)
            return 2
        return run_controller(
            targets, interval=args.interval, once=args.once,
            as_json=args.as_json, max_cycles=args.max_cycles,
            dry_run=False if args.apply else None,
        )

    if args.command == "get" and args.kind == "actions":
        # the controller's JSONL action ledger, rendered — offline
        # audits need no live worker (obs/controller.py)
        import os as _os

        from tpu_kubernetes.obs.controller import (
            ENV_ACTIONS_FILE as _ACTIONS_ENV,
            list_actions,
            render_actions,
        )

        path = (args.actions_file
                or _os.environ.get(_ACTIONS_ENV, "")
                or "runs/actions.jsonl")
        actions = list_actions(path)
        if args.as_json:
            print(json.dumps(actions, indent=2, sort_keys=True))
        else:
            print(render_actions(actions), end="")
        return 0

    if args.command == "get" and args.kind == "incidents":
        # local incident bundles (obs/incidents.py writes them next to
        # runs/ reports) — offline postmortems need no live worker
        import os as _os

        from tpu_kubernetes.obs.incidents import (
            DEFAULT_DIR as _INCIDENTS_DIR,
            list_incidents,
            render_incidents,
        )

        directory = (args.incidents_dir
                     or _os.environ.get("TPU_K8S_INCIDENTS_DIR", "")
                     or _INCIDENTS_DIR)
        payloads = list_incidents(directory)
        if args.as_json:
            print(json.dumps(payloads, indent=2, sort_keys=True))
        else:
            print(render_incidents(payloads), end="")
        return 0

    if args.command == "bench":
        # microbenches need jax, not a backend/config — short-circuit
        # like monitor (obs/perfbench.py owns the run/check logic)
        from tpu_kubernetes.obs.perfbench import run as perfbench_run

        return perfbench_run(
            args.suite, check=args.check, as_json=args.as_json,
            history_dir=args.history_dir, baseline=args.baseline,
            threshold=args.threshold, n=args.n, warmup=args.warmup,
            only=args.only, require_baseline=args.require_baseline,
        )

    if args.command == "get" and args.kind == "profile":
        # a remote worker's GET /debug/profile, rendered — no backend,
        # config, or prompts involved
        from tpu_kubernetes.obs.profile import fetch_profile, render_profile

        try:
            data = fetch_profile(args.target)
        except Exception as e:  # noqa: BLE001 — network errors → exit 1
            print(f"error: cannot fetch profile from {args.target}: {e}",
                  file=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(data, indent=2, sort_keys=True))
        else:
            print(render_profile(data), end="")
        return 0

    if args.command == "get" and args.kind == "goodput":
        # a remote worker's GET /debug/ledger, rendered — same stance as
        # get profile: no backend, config, or prompts involved
        from tpu_kubernetes.obs.ledger import fetch_ledger, render_ledger

        try:
            data = fetch_ledger(args.target)
        except Exception as e:  # noqa: BLE001 — network errors → exit 1
            print(f"error: cannot fetch ledger from {args.target}: {e}",
                  file=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(data, indent=2, sort_keys=True))
        else:
            print(render_ledger(data), end="")
        return 0

    if args.command == "analyze":
        # pure AST + text scanning, no backend/config/prompts — and no
        # import of the analyzed package (docs/guide/static-analysis.md)
        from pathlib import Path

        from tpu_kubernetes import analysis

        root = Path(args.root) if args.root else \
            Path(tpu_kubernetes.__file__).resolve().parent.parent
        passes = args.passes or list(analysis.PASS_NAMES)
        try:
            findings, timings = analysis.run_analysis_timed(root, passes)
            baseline_path = Path(args.baseline) if args.baseline \
                else root / analysis.BASELINE_NAME
            baseline = analysis.load_baseline(baseline_path)
        except (analysis.ProjectError, SyntaxError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.update_baseline:
            # rewrite the gate to suppress exactly the current findings,
            # loudly: the added/removed entries are the review surface
            want = sorted({f.key() for f in findings})
            added = [k for k in want if k not in baseline]
            removed = sorted(k for k in baseline if k not in set(want))
            analysis.write_baseline(baseline_path, findings)
            print(
                f"baseline {baseline_path}: {len(want)} entr"
                f"{'y' if len(want) == 1 else 'ies'} "
                f"(+{len(added)} added, -{len(removed)} removed)",
                file=sys.stderr,
            )
            for code, p, symbol in added:
                print(f"  + {code} {p} [{symbol}]", file=sys.stderr)
            for code, p, symbol in removed:
                print(f"  - {code} {p} [{symbol}]", file=sys.stderr)
            return 0
        new, old = analysis.split_baselined(findings, baseline)
        if args.as_json:
            print(json.dumps(
                analysis.report_json(new, old, str(root), passes,
                                     timings=timings),
                indent=2, sort_keys=True,
            ))
        else:
            print(analysis.render_findings(new, old), end="")
        return 1 if new else 0

    if args.command == "get" and args.kind == "metrics":
        # this process's registry (terraform command families registered by
        # the shell layer; families populate as workflows run in-process) —
        # no backend or prompts needed, mirror of the server's GET /metrics
        from tpu_kubernetes.obs import REGISTRY

        print(REGISTRY.render(), end="")
        return 0

    cfg = Config.load(args.config, non_interactive=args.non_interactive)
    for item in args.set:
        key, sep, value = item.partition("=")
        if not sep:
            print(f"error: --set expects KEY=VALUE, got {item!r}", file=sys.stderr)
            return 2
        cfg.set(key, value)
    if getattr(args, "manager", None):
        cfg.set("cluster_manager", args.manager)

    try:
        backend = prompt_for_backend(cfg)
        executor = default_executor()
        if args.command == "create":
            log.info(f"creating {args.kind}")  # reference: cmd/create.go:46,53,60
            if args.kind == "manager":
                create_wf.new_manager(backend, cfg, executor)
            elif args.kind == "cluster":
                create_wf.new_cluster(backend, cfg, executor)
            else:
                create_wf.new_node(backend, cfg, executor)
        elif args.command == "destroy":
            log.info(f"destroying {args.kind}")
            if args.kind == "manager":
                destroy_wf.delete_manager(backend, cfg, executor)
            elif args.kind == "cluster":
                destroy_wf.delete_cluster(backend, cfg, executor)
            else:
                destroy_wf.delete_node(backend, cfg, executor)
        elif args.command == "repair":
            log.info("repairing cluster")
            # argparse flags are sugar over the config keys (YAML/--set
            # spellings keep working)
            if args.auto:
                cfg.set("auto", "true")
            if args.replace_nodes:
                cfg.set("replace_nodes", "true")
            if args.grace is not None:
                cfg.set("grace", str(args.grace))
            keys = repair_wf.repair_cluster(backend, cfg, executor)
            if keys:
                print(f"Repaired {len(keys)} module(s).")
        elif args.command == "get":
            if args.kind == "kubeconfig":
                # raw YAML on stdout so `... get kubeconfig > kubeconfig` works
                print(get_wf.get_kubeconfig(backend, cfg, executor), end="")
            elif args.kind == "runs":
                reports = get_wf.get_runs(backend, cfg)
                if args.as_json:
                    print(json.dumps(reports, indent=2, sort_keys=True))
                else:
                    print(get_wf.format_runs(reports), end="")
            else:
                out = (
                    get_wf.get_manager(backend, cfg, executor)
                    if args.kind == "manager"
                    else get_wf.get_cluster(backend, cfg, executor)
                )
                print(json.dumps(out, indent=2, sort_keys=True))
    except (
        ConfigError,
        ProviderError,
        BackendError,
        ExecutorError,
        PromptError,
        ValidationError,
        StateError,
        TopologyError,
        KubeconfigError,
    ) as e:
        # reference prints the error then exits 1 (cmd/create.go:48-50)
        log.error(str(e))
        return 1
    finally:
        if args.timing:
            print(TRACER.dump_json(), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
