from tpu_kubernetes.cli.main import build_parser, main  # noqa: F401
