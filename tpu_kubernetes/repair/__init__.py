"""``repair cluster`` — preemption-aware slice recreate, with detection.

No reference analog: the reference has no failure recovery at all (SURVEY
§5.3 — its only resilience is that terraform state lets a failed apply be
retried). Cloud TPU slices are preemptible and a v5p pod slice is one
schedulable unit spanning several hosts, so a preempted slice must be
re-created as a whole. This workflow re-applies a cluster's module set:

* default — targeted ``terraform apply``; terraform's refresh notices
  deleted/preempted machines and re-creates exactly those, an idempotent
  no-op for healthy ones (the same property the reference leans on for
  retries, rancher_cluster.sh:6,24-27).
* ``replace_nodes`` — targeted ``terraform destroy`` of the node modules
  first, then re-apply; for machines that are STOPPED-but-present (GCE/TPU
  preemption leaves the resource visible, so refresh alone won't replace it).
* ``auto`` — the user stops being the failure detector (round-3 VERDICT
  Missing #2): ask the manager's kube API about every Node the cluster
  should have (fleet/nodes.py) and print the diagnosis. All healthy →
  no-op. Unhealthy → **diagnose-and-report by default** (nonzero exit so
  monitors can alert); destroying machines takes the explicit
  ``auto + replace_nodes`` combination, which replaces exactly the
  unhealthy modules. ``grace`` re-checks after a sleep and spares nodes
  that recover — a transient kubelet restart must not cost a machine.
  The manager being unreachable fails the repair loudly — guessing a
  replace set without data would destroy healthy machines.

Before replacing, the doomed machines' kube Node objects are cordoned,
drained (eviction-free — they are dead or about to be) and deleted, so the
re-created machines don't join a control plane still advertising their
ghosts.

Holds the backend lock across the whole window, like every other mutation.
"""

from __future__ import annotations

import time

from tpu_kubernetes.backend import Backend
from tpu_kubernetes.config import Config
from tpu_kubernetes.create.node import select_cluster, select_manager
from tpu_kubernetes.fleet import drain_and_delete, resolve_fleet_api
from tpu_kubernetes.fleet.nodes import (
    count_running_pods_on,
    diagnose_nodes,
    expected_node_names,
    unhealthy_hosts,
)
from tpu_kubernetes.providers.base import ProviderError
from tpu_kubernetes.shell import Executor
from tpu_kubernetes.shell.executor import dry_run_skip
from tpu_kubernetes.util.runlog import run_recorder
from tpu_kubernetes.util.trace import TRACER

__all__ = ["repair_cluster"]


def _auto_diagnose(fleet_api, state, cluster_key: str) -> list[str]:
    """→ the unhealthy hostnames, with the per-node diagnosis printed.
    Raises ProviderError when the manager can't answer."""
    if fleet_api is None:
        raise ProviderError(
            "repair --auto needs the manager's live api_url/secret_key "
            "outputs to diagnose node health — apply the manager first, "
            "or repair without --auto"
        )
    expected = expected_node_names(state, cluster_key)
    try:
        diagnosis = diagnose_nodes(fleet_api, expected)
    except Exception as e:  # noqa: BLE001 — no data, no destructive guesses
        raise ProviderError(
            f"repair --auto could not diagnose {cluster_key}: {e} — "
            "manager unreachable? Repair without --auto to force a re-apply"
        ) from e
    for hostname in sorted(diagnosis):
        for name, status in diagnosis[hostname].items():
            print(f"  {hostname}: node {name}: {status}")
    return unhealthy_hosts(diagnosis)


def repair_cluster(backend: Backend, cfg: Config, executor: Executor) -> list[str]:
    """Re-apply one cluster's modules; returns the repaired module keys
    (empty when running dry or --auto found nothing wrong). The document
    itself is never mutated, so there is nothing to persist."""
    manager = select_manager(backend, cfg)
    with run_recorder(backend, manager, "repair cluster") as run_info, \
            backend.lock(manager):
        state = backend.state(manager)
        cluster_key = select_cluster(state, cfg)
        nodes = state.nodes(cluster_key)  # hostname → module key
        run_info["cluster"] = cluster_key
        replace = cfg.get_bool("replace_nodes", default=False)
        auto = cfg.get_bool("auto", default=False)
        grace_set = cfg.get("grace", default=None) is not None
        grace = cfg.get_int("grace", default=0) if grace_set else 0
        if grace_set and not auto:
            # validated where ALL spellings converge (--grace flag and
            # --set grace=N alike), and on SET-ness, not value — a
            # computed --grace 0 without --auto is the same misuse: the
            # re-check only exists on the diagnosis path, and silently
            # ignoring it before a replace-all would be exactly the
            # footgun it guards against
            raise ProviderError(
                "grace requires auto (the re-check spares "
                "diagnosed-unhealthy nodes that recover) — add --auto "
                "or drop --grace"
            )

        fleet_api = resolve_fleet_api(executor, state, cluster_key)

        if auto:
            bad_hosts = _auto_diagnose(fleet_api, state, cluster_key)
            run_info["diagnosed_unhealthy"] = bad_hosts
            if bad_hosts and grace > 0:
                # a transient kubelet restart shows as a NotReady blip;
                # only nodes unhealthy across the whole window are acted on
                print(
                    f"{cluster_key}: {len(bad_hosts)} unhealthy — "
                    f"re-checking after a {grace}s grace window"
                )
                time.sleep(grace)
                second = set(
                    _auto_diagnose(fleet_api, state, cluster_key)
                )
                for h in sorted(set(bad_hosts) - second):
                    print(f"  {h}: recovered within grace — spared")
                bad_hosts = [h for h in bad_hosts if h in second]
                run_info["unhealthy_after_grace"] = bad_hosts
            if not bad_hosts:
                print(f"{cluster_key}: all nodes Ready — nothing to repair")
                return []
            if not replace:
                # detection must not imply destruction: --auto alone is
                # diagnose-and-report (nonzero exit so monitors can alert);
                # destroying machines takes the explicit --replace_nodes
                raise ProviderError(
                    f"{cluster_key}: {len(bad_hosts)} unhealthy node(s): "
                    f"{', '.join(sorted(bad_hosts))} — re-run with "
                    "`repair cluster --auto --replace_nodes` to replace "
                    "exactly these (add --grace <seconds> to spare "
                    "transient NotReady blips)"
                )
            replace_hosts = bad_hosts
        else:
            replace_hosts = sorted(nodes)

        node_keys = sorted(nodes[h] for h in replace_hosts)
        if replace:
            # advisory: what is actually RUNNING on the doomed machines
            # (round-3 VERDICT Weak #5 — one confirm covered dead and live
            # nodes alike). Computed whenever the fleet API can answer —
            # force/non-interactive runs still get it as a printed line —
            # and 'could not check' keeps the generic warning: it must
            # never read as 'verified idle'.
            will_prompt = not (
                cfg.get_bool("force", default=False) or cfg.non_interactive
            )
            pod_note = " Make sure no job you care about is running on them."
            if fleet_api is not None:
                expected = expected_node_names(state, cluster_key)
                counts = [
                    count_running_pods_on(fleet_api, name)
                    for host in replace_hosts
                    for name in expected.get(host, [host])
                ]
                if None not in counts:
                    n_pods = sum(counts)
                    pod_note = (
                        f" {n_pods} pod(s) are currently Running on them "
                        "and will be killed." if n_pods
                        else " No running pods on them."
                    )
            if not will_prompt:
                print(f"{cluster_key}: replacing {len(node_keys)} node "
                      f"module(s).{pod_note}")
            question = (
                f"Replace the nodes of cluster {cluster_key} "
                f"({len(node_keys)} node module(s))? This DESTROYS those "
                f"machines.{pod_note}"
            )
        else:
            question = (
                f"Repair cluster {cluster_key} "
                f"({len(node_keys)} node module(s))?"
            )
        if not cfg.confirm(question):
            raise ProviderError("aborted by user")

        # drive the executor even when dry — it renders/records the exact
        # target set, so a dry repair surfaces what the real one would touch
        node_targets = [f"module.{k}" for k in node_keys]
        targets = [f"module.{cluster_key}"] + node_targets
        if replace and node_targets:
            # the doomed machines' Node objects must not outlive them
            # (best-effort; dry runs touch nothing)
            if fleet_api and not getattr(executor, "dry_run", False):
                with TRACER.phase("drop kube nodes", cluster=cluster_key):
                    drain_and_delete(fleet_api, replace_hosts)
            with TRACER.phase("replace: destroy nodes", cluster=cluster_key):
                executor.destroy(state, targets=node_targets)
        with TRACER.phase("repair apply", manager=manager, cluster=cluster_key):
            executor.apply(state, targets=targets)
        if dry_run_skip(
            executor,
            f"nothing was actually repaired for {cluster_key} "
            "(re-run with terraform installed to really repair)",
        ):
            return []
    return [cluster_key, *node_keys]
