"""``repair cluster`` — preemption-aware slice recreate.

No reference analog: the reference has no failure recovery at all (SURVEY
§5.3 — its only resilience is that terraform state lets a failed apply be
retried). Cloud TPU slices are preemptible and a v5p pod slice is one
schedulable unit spanning several hosts, so a preempted slice must be
re-created as a whole. This workflow re-applies a cluster's module set:

* default — targeted ``terraform apply``; terraform's refresh notices
  deleted/preempted machines and re-creates exactly those, an idempotent
  no-op for healthy ones (the same property the reference leans on for
  retries, rancher_cluster.sh:6,24-27).
* ``replace_nodes`` — targeted ``terraform destroy`` of the node modules
  first, then re-apply; for machines that are STOPPED-but-present (GCE/TPU
  preemption leaves the resource visible, so refresh alone won't replace it).

Holds the backend lock across the whole window, like every other mutation.
"""

from __future__ import annotations

from tpu_kubernetes.backend import Backend
from tpu_kubernetes.config import Config
from tpu_kubernetes.create.node import select_cluster, select_manager
from tpu_kubernetes.providers.base import ProviderError
from tpu_kubernetes.shell import Executor
from tpu_kubernetes.shell.executor import dry_run_skip
from tpu_kubernetes.util.runlog import run_recorder
from tpu_kubernetes.util.trace import TRACER

__all__ = ["repair_cluster"]


def repair_cluster(backend: Backend, cfg: Config, executor: Executor) -> list[str]:
    """Re-apply one cluster's modules; returns the repaired module keys
    (empty when running dry — nothing was actually repaired). The document
    itself is never mutated, so there is nothing to persist."""
    manager = select_manager(backend, cfg)
    with run_recorder(backend, manager, "repair cluster") as run_info, \
            backend.lock(manager):
        state = backend.state(manager)
        cluster_key = select_cluster(state, cfg)
        node_keys = sorted(state.nodes(cluster_key).values())
        run_info["cluster"] = cluster_key
        replace = cfg.get_bool("replace_nodes", default=False)

        action = "Replace the nodes of" if replace else "Repair"
        if not cfg.confirm(
            f"{action} cluster {cluster_key} ({len(node_keys)} node module(s))?"
        ):
            raise ProviderError("aborted by user")

        # drive the executor even when dry — it renders/records the exact
        # target set, so a dry repair surfaces what the real one would touch
        targets = [f"module.{cluster_key}"] + [f"module.{k}" for k in node_keys]
        node_targets = [f"module.{k}" for k in node_keys]
        if replace and node_targets:
            with TRACER.phase("replace: destroy nodes", cluster=cluster_key):
                executor.destroy(state, targets=node_targets)
        with TRACER.phase("repair apply", manager=manager, cluster=cluster_key):
            executor.apply(state, targets=targets)
        if dry_run_skip(
            executor,
            f"nothing was actually repaired for {cluster_key} "
            "(re-run with terraform installed to really repair)",
        ):
            return []
    return [cluster_key, *node_keys]
