"""Render-time schema validation of the state document against the terraform
modules it references.

The reference's cross-module plumbing (``${module.x.y}`` interpolation
contracts, SURVEY §2.3) is easy to break silently — a typo'd output name only
surfaces minutes into a terraform apply. This validator (SURVEY §7 hard part
#5 fix) checks, before anything is applied:

  1. every config key a module instance carries is a declared variable of its
     module (catches renamed/typo'd keys),
  2. every variable without a default is supplied (catches missing config),
  3. every ``${module.X.y}`` interpolation references an existing module
     instance X whose module declares output y (catches contract breakage).

Only modules with local directory sources are checked; remote (git) sources
are skipped — terraform validates those at init time.
"""

from __future__ import annotations

import functools
import re
from pathlib import Path

from tpu_kubernetes.state import State

_VARIABLE_RE = re.compile(r'^\s*variable\s+"([^"]+)"', re.MULTILINE)
_OUTPUT_RE = re.compile(r'^\s*output\s+"([^"]+)"', re.MULTILINE)
_INTERP_RE = re.compile(r"\$\{module\.([^.}]+)\.([^}]+)\}")


class ValidationError(Exception):
    def __init__(self, errors: list[str]):
        self.errors = errors
        super().__init__(
            "state document failed render-time validation:\n  - "
            + "\n  - ".join(errors)
        )


@functools.lru_cache(maxsize=256)
def _parse_module_dir(module_dir: Path) -> tuple[dict[str, bool], dict[str, bool]]:
    """→ ({variable: has_default}, {output: is_sensitive}) from .tf files.
    Memoized per directory — a 64-node cluster references the same module 64
    times per validate/inject pass. Module files are treated as immutable for
    the life of the process."""
    variables: dict[str, bool] = {}
    outputs: dict[str, bool] = {}
    for tf in sorted(module_dir.glob("*.tf")):
        text = tf.read_text()
        for match in _VARIABLE_RE.finditer(text):
            name = match.group(1)
            # attribute presence is checked within the block's braces
            block = _block_after(text, match.end())
            variables[name] = bool(re.search(r"^\s*default\s*=", block, re.MULTILINE))
        for match in _OUTPUT_RE.finditer(text):
            block = _block_after(text, match.end())
            outputs[match.group(1)] = bool(
                re.search(r"^\s*sensitive\s*=\s*true", block, re.MULTILINE)
            )
    return variables, outputs


def module_outputs(module_dir: Path) -> dict[str, bool]:
    """Public helper: {output_name: is_sensitive} for a local module dir."""
    _, outputs = _parse_module_dir(module_dir)
    return outputs


def _block_after(text: str, pos: int) -> str:
    """The {...} block starting at/after pos (brace matching)."""
    start = text.find("{", pos)
    if start < 0:
        return ""
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start:i + 1]
    return text[start:]


def validate_document(state: State) -> None:
    """Raise :class:`ValidationError` on any contract breakage."""
    modules = state.get("module", {})
    if not isinstance(modules, dict):
        return
    errors: list[str] = []

    for key, config in modules.items():
        if not isinstance(config, dict):
            errors.append(f"module.{key}: config is not an object")
            continue
        source = config.get("source", "")
        module_dir = Path(source) if source else None
        if module_dir is None or not module_dir.is_dir():
            continue  # remote source — terraform init will fetch + validate
        variables, _ = _parse_module_dir(module_dir)
        for cfg_key in config:
            if cfg_key == "source":
                continue
            if cfg_key not in variables:
                errors.append(
                    f"module.{key}: {cfg_key!r} is not a variable of "
                    f"{module_dir.name} (declared: {sorted(variables)})"
                )
        for var, has_default in variables.items():
            if not has_default and var not in config:
                errors.append(
                    f"module.{key}: required variable {var!r} of "
                    f"{module_dir.name} is not set"
                )

        # interpolation contract check
        for cfg_key, value in config.items():
            if not isinstance(value, str):
                continue
            for target_key, output in _INTERP_RE.findall(value):
                target = modules.get(target_key)
                if target is None:
                    errors.append(
                        f"module.{key}.{cfg_key}: references missing module "
                        f"{target_key!r}"
                    )
                    continue
                target_source = (
                    target.get("source", "") if isinstance(target, dict) else ""
                )
                target_dir = Path(target_source) if target_source else None
                if target_dir is None or not target_dir.is_dir():
                    continue
                _, outputs = _parse_module_dir(target_dir)
                if output not in outputs:
                    errors.append(
                        f"module.{key}.{cfg_key}: module {target_key!r} "
                        f"({target_dir.name}) declares no output {output!r} "
                        f"(declared: {sorted(outputs)})"
                    )

    if errors:
        raise ValidationError(errors)
