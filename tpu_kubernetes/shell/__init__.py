from tpu_kubernetes.shell.validate import (  # noqa: F401
    ValidationError,
    validate_document,
)
from tpu_kubernetes.shell.executor import (  # noqa: F401
    Executor,
    ExecutorError,
    FakeExecutor,
    RecordedCall,
    TerraformExecutor,
    default_executor,
    render_to_dir,
)
