"""Execution layer: render the state document and run terraform over it.

reference: shell/run_terraform.go:11-80 — write the document to a temp dir as
``main.tf.json``, run ``terraform init -force-copy`` then
``terraform apply -auto-approve`` (or ``destroy [-target=…]``), streaming
subprocess output through (reference: shell/run_shell_cmd.go:8-13).

Two implementations of one :class:`Executor` protocol:

* :class:`TerraformExecutor` — the real thing (subprocess boundary).
* :class:`FakeExecutor` — records rendered documents and command lines and
  returns canned outputs. The reference has **no** shell mocking, so its tests
  can only cover the validation prefix of each workflow (SURVEY §4); this
  class is the hermetic-testing fix carried forward knowingly.
"""

from __future__ import annotations

import abc
import contextlib
import json
import os
import shutil
import subprocess
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Sequence

from tpu_kubernetes.obs import REGISTRY
from tpu_kubernetes.obs.faults import FAULTS
from tpu_kubernetes.state import State
from tpu_kubernetes.util.trace import TRACER, Tracer

STATE_FILE = "main.tf.json"

# failure-message substrings that mark a terraform run as TRANSIENT —
# worth a bounded retry instead of failing the workflow. Lock contention
# and network blips are the classes the reference provisioner's users
# hit in practice; "injected fault" is the fault harness
# (obs/faults.py, site "shell.terraform") emulating exactly such a blip.
# Classification is best-effort on the error text (with stream_output
# the subprocess detail goes to the console, not the exception).
TRANSIENT_PATTERNS = (
    "state lock",
    "lock info",
    "connection reset",
    "connection refused",
    "temporary failure",
    "tls handshake",
    "rate limit",
    "throttl",
    "injected fault",
)


def is_transient(err: Exception) -> bool:
    msg = str(err).lower()
    return any(p in msg for p in TRANSIENT_PATTERNS)

# terraform command telemetry (persisted into run reports, util/runlog.py):
# init/apply/destroy/output durations and failure counts are THE create→
# first-train-step latency breakdown the ROADMAP optimizes for
TF_SECONDS = REGISTRY.histogram(
    "tpu_tf_command_seconds",
    "terraform command wall time by subcommand",
    labelnames=("command",),
)
TF_FAILURES = REGISTRY.counter(
    "tpu_tf_failures_total",
    "terraform commands that exited nonzero (or failed to spawn)",
    labelnames=("command",),
)
TF_RETRIES = REGISTRY.counter(
    "tpu_tf_retries_total",
    "transient terraform failures retried (lock/network classes) — "
    "rides run reports via the tpu_tf_ prefix (util/runlog.py), so a "
    "flaky-but-recovered apply is visible after the fact",
    labelnames=("command",),
)


@contextlib.contextmanager
def _tf_timed(command: str):
    """Time one terraform subcommand into the registry; failures count by
    subcommand so flaky applies are distinguishable from broken inits."""
    t0 = time.monotonic()
    try:
        yield
    except Exception:
        TF_FAILURES.labels(command).inc()
        raise
    finally:
        TF_SECONDS.labels(command).observe(time.monotonic() - t0)


class ExecutorError(Exception):
    pass


class Executor(abc.ABC):
    @abc.abstractmethod
    def apply(self, state: State, targets: Sequence[str] = ()) -> None:
        """terraform init + apply [-target=module.X …].
        reference: shell/run_terraform.go:11-44 (the reference never targets
        an apply; ``repair cluster`` — no reference analog — does)."""

    @abc.abstractmethod
    def destroy(self, state: State, targets: Sequence[str] = ()) -> None:
        """terraform init + destroy [-target=module.X …].
        reference: shell/run_terraform.go:46-80."""

    @abc.abstractmethod
    def output(self, state: State, module_key: str) -> dict[str, Any]:
        """terraform init + output for one module.
        reference: get/cluster.go:129-138."""


def render_to_dir(state: State, directory: str | Path) -> Path:
    """Write the document to ``<dir>/main.tf.json``.
    reference: shell/run_terraform.go:13-24."""
    from tpu_kubernetes.util import log

    path = Path(directory) / STATE_FILE
    path.write_bytes(state.to_bytes())
    log.debug(f"rendered {state.name!r} to {path}")
    return path


class TerraformExecutor(Executor):
    def __init__(
        self,
        terraform_bin: str = "terraform",
        tracer: Tracer | None = None,
        stream_output: bool = True,
        timeout_s: float = 0.0,
        retries: int = 2,
        retry_backoff_s: float = 0.5,
    ):
        self.terraform_bin = terraform_bin
        self.tracer = tracer or TRACER
        self.stream_output = stream_output
        # 0 = no deadline; set to bound a wedged terraform apply
        self.timeout_s = timeout_s
        # bounded retries for TRANSIENT failures only (is_transient) —
        # timeouts and real config/plan errors fail on the first attempt
        self.retries = max(0, int(retries))
        self.retry_backoff_s = max(0.0, float(retry_backoff_s))

    def _run(self, args: Sequence[str], cwd: Path) -> None:
        """Stream a subprocess through (reference: shell/run_shell_cmd.go:8-13)
        via the native C++ runner when built (tpu_kubernetes/native — adds
        deadline enforcement and an output tail in errors), else plain
        subprocess. Transient failures (lock contention, network blips —
        is_transient) retry up to ``retries`` times with exponential
        backoff; only the FINAL failure counts in tpu_tf_failures_total,
        recovered attempts count in tpu_tf_retries_total."""
        cmd = [self.terraform_bin, *args]
        from tpu_kubernetes.util import log

        log.debug(f"exec: {' '.join(cmd)} (cwd {cwd})")
        command = args[0]
        with _tf_timed(command):
            attempt = 0
            while True:
                try:
                    FAULTS.fire("shell.terraform")
                    self._run_inner(cmd, cwd)
                    return
                except Exception as e:  # noqa: BLE001 — reclassified below
                    if attempt >= self.retries or not is_transient(e):
                        raise
                    attempt += 1
                    TF_RETRIES.labels(command).inc()
                    delay = self.retry_backoff_s * 2.0 ** (attempt - 1)
                    log.warn(
                        f"terraform {command}: transient failure "
                        f"(attempt {attempt}/{self.retries}, retrying "
                        f"in {delay:.1f}s): {e}"
                    )
                    if delay:
                        time.sleep(delay)

    def _run_inner(self, cmd: list[str], cwd: Path) -> None:
        from tpu_kubernetes import native

        if native.available():
            code, tail = native.run_streaming(
                cmd, cwd=cwd, timeout_s=self.timeout_s,
                stream=self.stream_output,
            )
            if code == native.SPAWN_FAILURE:
                raise ExecutorError(
                    f"terraform binary {self.terraform_bin!r} not found on PATH "
                    "(install terraform, or use the fake executor for dry runs)"
                )
            if code == native.TIMEOUT:
                raise ExecutorError(
                    f"{' '.join(cmd)} killed after {self.timeout_s}s timeout"
                )
            if code == native.SIGNALED:
                raise ExecutorError(
                    f"{' '.join(cmd)} terminated by signal (interrupted?)"
                )
            if code != 0:
                detail = "" if self.stream_output else f"\n{tail}"
                raise ExecutorError(
                    f"{' '.join(cmd)} exited with status {code}{detail}"
                )
            return

        try:
            proc = subprocess.run(
                cmd,
                cwd=cwd,
                stdout=None if self.stream_output else subprocess.PIPE,
                stderr=None if self.stream_output else subprocess.STDOUT,
                timeout=self.timeout_s or None,
            )
        except FileNotFoundError as e:
            raise ExecutorError(
                f"terraform binary {self.terraform_bin!r} not found on PATH "
                "(install terraform, or use the fake executor for dry runs)"
            ) from e
        except subprocess.TimeoutExpired as e:
            raise ExecutorError(
                f"{' '.join(cmd)} killed after {self.timeout_s}s timeout"
            ) from e
        if proc.returncode != 0:
            detail = "" if self.stream_output else f"\n{proc.stdout.decode(errors='replace')}"
            raise ExecutorError(
                f"{' '.join(cmd)} exited with status {proc.returncode}{detail}"
            )

    def _capture(self, args: Sequence[str], cwd: Path) -> str:
        cmd = [self.terraform_bin, *args]
        with _tf_timed(args[0]):
            proc = subprocess.run(cmd, cwd=cwd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise ExecutorError(
                    f"{' '.join(cmd)} exited with status {proc.returncode}\n{proc.stderr}"
                )
        return proc.stdout

    def apply(self, state: State, targets: Sequence[str] = ()) -> None:
        with tempfile.TemporaryDirectory(prefix="tpu-k8s-") as d:
            render_to_dir(state, d)
            with self.tracer.phase("terraform init", manager=state.name):
                self._run(["init", "-force-copy"], Path(d))
            args = ["apply", "-auto-approve"]
            args += [f"-target={t}" for t in targets]
            with self.tracer.phase("terraform apply", manager=state.name):
                self._run(args, Path(d))

    def destroy(self, state: State, targets: Sequence[str] = ()) -> None:
        with tempfile.TemporaryDirectory(prefix="tpu-k8s-") as d:
            render_to_dir(state, d)
            with self.tracer.phase("terraform init", manager=state.name):
                self._run(["init", "-force-copy"], Path(d))
            args = ["destroy", "-auto-approve"]
            args += [f"-target={t}" for t in targets]
            with self.tracer.phase("terraform destroy", manager=state.name):
                self._run(args, Path(d))

    def output(self, state: State, module_key: str) -> dict[str, Any]:
        # terraform cannot read child-module outputs post-0.12, so the apply
        # path injects root forwards named <module_key>__<output>
        # (shell/outputs.py) and this filters them back out.
        from tpu_kubernetes.shell.outputs import filter_module_outputs

        with tempfile.TemporaryDirectory(prefix="tpu-k8s-") as d:
            render_to_dir(state, d)
            with self.tracer.phase("terraform init", manager=state.name):
                self._run(["init", "-force-copy"], Path(d))
            raw = self._capture(["output", "-json"], Path(d))
            data = json.loads(raw or "{}")
            # terraform >=0.12 nests values as {"value": ...}
            flat = {
                k: (v.get("value") if isinstance(v, dict) and "value" in v else v)
                for k, v in data.items()
            }
            return filter_module_outputs(flat, module_key)


@dataclass
class RecordedCall:
    command: str  # "apply" | "destroy" | "output"
    document: dict[str, Any]
    targets: tuple[str, ...] = ()
    module_key: str | None = None


@dataclass
class FakeExecutor(Executor):
    """Hermetic executor: records every call, optionally fails on demand.

    ``dry_run=True`` marks the executor as a stand-in for missing terraform
    (default_executor fallback). Destroy workflows check it and refuse to
    forget state for infrastructure that was never actually destroyed.
    """

    calls: list[RecordedCall] = field(default_factory=list)
    outputs: dict[str, dict[str, Any]] = field(default_factory=dict)
    fail_with: str | None = None
    dry_run: bool = False

    def _record(self, call: RecordedCall) -> None:
        if self.fail_with:
            raise ExecutorError(self.fail_with)
        self.calls.append(call)

    def apply(self, state: State, targets: Sequence[str] = ()) -> None:
        self._record(RecordedCall("apply", state.to_dict(), targets=tuple(targets)))

    def destroy(self, state: State, targets: Sequence[str] = ()) -> None:
        self._record(RecordedCall("destroy", state.to_dict(), targets=tuple(targets)))

    def output(self, state: State, module_key: str) -> dict[str, Any]:
        self._record(RecordedCall("output", state.to_dict(), module_key=module_key))
        return self.outputs.get(module_key, {})


def dry_run_skip(executor: Executor, message: str) -> bool:
    """True (with a stderr warning) when ``executor`` is a dry-run stand-in
    for missing terraform — callers use it to skip state side-effects for
    infrastructure that was never actually touched."""
    if not getattr(executor, "dry_run", False):
        return False
    import sys

    print(f"[tpu-k8s] dry-run: {message}", file=sys.stderr)
    return True


def default_executor() -> Executor:
    """Real terraform if present on PATH, else a fake (dry-run) executor with
    a loud warning — lets the whole CLI be exercised hermetically."""
    if shutil.which(os.environ.get("TPU_K8S_TERRAFORM_BIN", "terraform")):
        # TPU_K8S_TF_TIMEOUT_S bounds a wedged command (0 = no deadline);
        # TPU_K8S_TF_RETRIES bounds transient-failure retries
        from tpu_kubernetes.util.envparse import env_float, env_int

        return TerraformExecutor(
            os.environ.get("TPU_K8S_TERRAFORM_BIN", "terraform"),
            timeout_s=env_float("TPU_K8S_TF_TIMEOUT_S", 0.0),
            retries=env_int("TPU_K8S_TF_RETRIES", 2),
        )
    import sys

    print(
        "[tpu-k8s] WARNING: terraform not found on PATH — running in dry-run "
        "mode (state documents are rendered and persisted, nothing is applied)",
        file=sys.stderr,
    )
    return FakeExecutor(dry_run=True)
