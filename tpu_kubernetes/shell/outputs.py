"""Root-level output forwarding.

Modern terraform cannot read child-module outputs after apply (the
``terraform output -module`` the reference relied on — get/cluster.go:135 —
was removed in 0.12). So before every apply the document gets one root output
``<module_key>__<output>`` forwarding each local module's outputs; after
apply, ``terraform output -json`` serves them and the executor's
:meth:`output` filters by module key. Rebuilt from scratch on every call, so
destroyed modules' forwards disappear with them.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

from tpu_kubernetes.shell.validate import module_outputs
from tpu_kubernetes.state import State

SEPARATOR = "__"


def forward_key(module_key: str, output_name: str) -> str:
    return f"{module_key}{SEPARATOR}{output_name}"


def inject_root_outputs(state: State) -> None:
    """(Re)build the root ``output`` section from the current module set."""
    modules = state.get("module", {})
    forwards: dict[str, Any] = {}
    if isinstance(modules, dict):
        for key, config in modules.items():
            source = config.get("source", "") if isinstance(config, dict) else ""
            module_dir = Path(source) if source else None
            if module_dir is None or not module_dir.is_dir():
                continue
            for name, sensitive in module_outputs(module_dir).items():
                block: dict[str, Any] = {"value": f"${{module.{key}.{name}}}"}
                if sensitive:
                    block["sensitive"] = True
                forwards[forward_key(key, name)] = block
    if forwards:
        state.set("output", forwards)
    else:
        state.delete("output")


def filter_module_outputs(
    root_outputs: dict[str, Any], module_key: str
) -> dict[str, Any]:
    """Strip ``<module_key>__`` forwards back to plain output names."""
    prefix = module_key + SEPARATOR
    return {
        k[len(prefix):]: v for k, v in root_outputs.items() if k.startswith(prefix)
    }
