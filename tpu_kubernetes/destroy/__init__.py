from tpu_kubernetes.destroy.workflows import (  # noqa: F401
    delete_cluster,
    delete_manager,
    delete_node,
)
