"""``destroy manager|cluster|node`` workflows.

reference: destroy/manager.go:16-94 (full destroy + DeleteState),
destroy/cluster.go:16-161 (targeted destroy of cluster module + every node
module, then delete them from the document), destroy/node.go:16-180 (one
node module).
"""

from __future__ import annotations

from tpu_kubernetes.backend import Backend
from tpu_kubernetes.config import Config
from tpu_kubernetes.create.node import select_cluster, select_manager
from tpu_kubernetes.destroy.deregister import deregister_cluster
from tpu_kubernetes.fleet import drain_and_delete, resolve_fleet_api
from tpu_kubernetes.providers.base import ProviderError
from tpu_kubernetes.state import cluster_key_parts
from tpu_kubernetes.shell import Executor
from tpu_kubernetes.shell.executor import dry_run_skip
from tpu_kubernetes.shell.outputs import inject_root_outputs
from tpu_kubernetes.util.runlog import run_recorder
from tpu_kubernetes.util.trace import TRACER


def _destroy_skipped(executor: Executor, what: str) -> bool:
    return dry_run_skip(
        executor,
        f"nothing was destroyed — keeping state for {what} "
        "(re-run with terraform installed to actually destroy)",
    )


def _warn_no_fleet(what: str) -> None:
    from tpu_kubernetes.util import log

    log.warn(
        f"{what} was NOT cleaned up on the manager "
        "(no live api_url/secret_key outputs) — stale kube Node objects "
        "and/or its join token may remain; see tpu_kubernetes/fleet/nodes.py"
    )


def delete_manager(backend: Backend, cfg: Config, executor: Executor) -> None:
    """Destroy everything under a manager and forget it.
    reference: destroy/manager.go:16-94."""
    manager = select_manager(backend, cfg)
    if not cfg.confirm(f"Destroy cluster manager {manager!r} and ALL its clusters?"):
        raise ProviderError("aborted by user")
    with backend.lock(manager):
        state = backend.state(manager)
        with TRACER.phase("destroy manager", manager=manager):
            executor.destroy(state)  # full destroy, no targets
        if _destroy_skipped(executor, f"manager {manager!r}"):
            # never forget state for infrastructure that wasn't actually destroyed
            return
        backend.delete_state(manager)


def delete_cluster(backend: Backend, cfg: Config, executor: Executor) -> None:
    """Targeted destroy of one cluster + its nodes.
    reference: destroy/cluster.go:16-161."""
    manager = select_manager(backend, cfg)
    with run_recorder(backend, manager, "destroy cluster") as run_info:
        # lock held from the state READ through destroy+persist (see create/)
        with backend.lock(manager):
            state = backend.state(manager)
            cluster_key = select_cluster(state, cfg)
            hostnames = sorted(state.nodes(cluster_key))
            node_keys = sorted(state.nodes(cluster_key).values())
            run_info["cluster"] = cluster_key

            if not cfg.confirm(
                f"Destroy cluster {cluster_key} and its {len(node_keys)} node(s)?"
            ):
                raise ProviderError("aborted by user")

            # resolve fleet credentials BEFORE destroying: the cluster's
            # ca_checksum output (CA pinning) dies with its module
            fleet_api = resolve_fleet_api(executor, state, cluster_key)

            # targets: the cluster module + one per node module
            # (reference: destroy/cluster.go:126-138)
            targets = [f"module.{cluster_key}"] + [f"module.{k}" for k in node_keys]
            with TRACER.phase("destroy cluster", manager=manager, cluster=cluster_key):
                executor.destroy(state, targets=targets)
            if _destroy_skipped(executor, f"cluster {cluster_key}"):
                return

            # remove from the document (reference: destroy/cluster.go:147-158)
            for key in [cluster_key, *node_keys]:
                state.delete_module(key)
            inject_root_outputs(state)  # drop forwards of deleted modules
            backend.persist_state(state)

            # control-plane cleanup, both best-effort (the infrastructure
            # is already gone — nothing on this path may fail the destroy):
            # 1. delete the pool's kube Node objects (the machines are
            #    gone; their Nodes would linger NotReady forever — the
            #    stale-Node leak the reference carries, destroy/node.go:167-177)
            # 2. revoke the join credential — left behind, the bootstrap
            #    token still authenticates agent joins
            parts = cluster_key_parts(cluster_key)
            if fleet_api and parts:
                with TRACER.phase("drop kube nodes", cluster=cluster_key):
                    drain_and_delete(fleet_api, hostnames)
                with TRACER.phase("deregister cluster", cluster=cluster_key):
                    deregister_cluster(fleet_api, parts[1])
            else:
                _warn_no_fleet(cluster_key)


def delete_node(backend: Backend, cfg: Config, executor: Executor) -> None:
    """Targeted destroy of one node module. reference: destroy/node.go:16-180."""
    manager = select_manager(backend, cfg)
    with run_recorder(backend, manager, "destroy node") as run_info:
        # lock held from the state READ through destroy+persist (see create/)
        with backend.lock(manager):
            state = backend.state(manager)
            cluster_key = select_cluster(state, cfg)
            nodes = state.nodes(cluster_key)
            if not nodes:
                raise ProviderError(f"cluster {cluster_key} has no nodes")
            hostname = cfg.get("hostname", prompt="node to destroy", choices=sorted(nodes))
            node_key = nodes[hostname]
            run_info["node"] = node_key

            if not cfg.confirm(f"Destroy node {node_key}?"):
                raise ProviderError("aborted by user")

            # resolve fleet credentials before the module (and its pinning
            # ca_checksum output) is gone
            fleet_api = resolve_fleet_api(executor, state, cluster_key)

            with TRACER.phase("destroy node", manager=manager, node=node_key):
                executor.destroy(state, targets=[f"module.{node_key}"])
            if _destroy_skipped(executor, f"node {node_key}"):
                return
            state.delete_module(node_key)
            inject_root_outputs(state)  # drop forwards of deleted modules
            backend.persist_state(state)

            # best-effort: delete the machine's kube Node object(s) so the
            # fleet API stops seeing a permanently-NotReady ghost (the
            # reference destroys the VM and tells nobody, destroy/node.go:167-177)
            if fleet_api:
                with TRACER.phase("drop kube nodes", node=node_key):
                    drain_and_delete(fleet_api, [hostname])
            else:
                _warn_no_fleet(f"node {node_key}")
