"""Deregister a destroyed cluster from the fleet control plane.

``terraform destroy`` removes the cloud resources, but the cluster's
registration lives in the MANAGER's kube API: the fleet registry ConfigMap
(``tpu-fleet/cluster-<name>``) and — security-relevant — the
``bootstrap.kubernetes.io/token`` Secret minted at registration
(register_cluster.sh). Left behind, that token still authenticates agent
joins: any host holding it could re-join a "destroyed" cluster's
credentials. The reference has the same leak (its Rancher cluster object
and registration token survive ``destroy cluster`` — nothing in
destroy/cluster.go:16-161 talks to Rancher), carried knowingly there and
closed here.

Best-effort by design: the manager may itself be gone or unreachable, and
the infrastructure is already destroyed — a deregistration failure must
never fail the destroy. It warns, and re-registration under the same name
would mint a fresh token anyway (register_cluster.sh re-mint path).

Credentials ride the CA-pinned client (fleet/api.py + bootstrap_tls
TOFU-pinning, ADVICE r03) — the fleet-admin token is never sent over an
unverified TLS connection.
"""

from __future__ import annotations

import re

from tpu_kubernetes.fleet.api import FleetAPI
from tpu_kubernetes.util.log import warn as _warn

_TOKEN_RE = re.compile(r"^([a-z0-9]{6})\.[a-z0-9]{16}$")


def deregister_cluster(api: FleetAPI, cluster_name: str) -> bool:
    """Delete the cluster's registry record and revoke its bootstrap token.
    Returns True when fully deregistered; False (with a stderr warning)
    on any failure — callers must not treat that as a destroy failure.
    Never raises: the infrastructure is already gone."""
    cm_path = f"/api/v1/namespaces/tpu-fleet/configmaps/cluster-{cluster_name}"
    try:
        # read the record first: it names the bootstrap token to revoke
        status, doc = api.get(cm_path)
        token_id = None
        if status == 200 and isinstance(doc, dict):
            data = doc.get("data") or {}
            token = data.get("registration_token", "")
            m = _TOKEN_RE.match(token if isinstance(token, str) else "")
            if m:
                token_id = m.group(1)

        failures = []
        if token_id:
            status, _ = api.delete(
                f"/api/v1/namespaces/kube-system/secrets/"
                f"bootstrap-token-{token_id}"
            )
            if status not in (200, 202, 404):
                failures.append(f"bootstrap token Secret (HTTP {status})")
        status, _ = api.delete(cm_path)
        if status not in (200, 202, 404):
            failures.append(f"registry ConfigMap (HTTP {status})")
        if failures:
            _warn(
                f"could not fully deregister cluster {cluster_name!r} from "
                f"the manager — failed: {', '.join(failures)}; its join "
                "token may still be valid. Delete the "
                f"tpu-fleet/cluster-{cluster_name} ConfigMap and the "
                "bootstrap token Secret by hand"
            )
        return not failures
    except Exception as e:  # noqa: BLE001 — must never fail a finished destroy
        _warn(
            f"cluster {cluster_name!r} deregistration skipped ({e}) — "
            "manager unreachable? Its join token may still be valid"
        )
        return False
