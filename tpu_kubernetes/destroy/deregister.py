"""Deregister a destroyed cluster from the fleet control plane.

``terraform destroy`` removes the cloud resources, but the cluster's
registration lives in the MANAGER's kube API: the fleet registry ConfigMap
(``tpu-fleet/cluster-<name>``) and — security-relevant — the
``bootstrap.kubernetes.io/token`` Secret minted at registration
(register_cluster.sh). Left behind, that token still authenticates agent
joins: any host holding it could re-join a "destroyed" cluster's
credentials. The reference has the same leak (its Rancher cluster object
and registration token survive ``destroy cluster`` — nothing in
destroy/cluster.go:16-161 talks to Rancher), carried knowingly there and
closed here.

Best-effort by design: the manager may itself be gone or unreachable, and
the infrastructure is already destroyed — a deregistration failure must
never fail the destroy. It warns, and re-registration under the same name
would mint a fresh token anyway (register_cluster.sh re-mint path).
"""

from __future__ import annotations

import json
import re
import sys
import urllib.error
import urllib.request

from tpu_kubernetes.util.bootstrap_tls import urlopen_kwargs

_TOKEN_RE = re.compile(r"^([a-z0-9]{6})\.[a-z0-9]{16}$")


def _request(
    method: str, url: str, token: str, timeout_s: float = 10.0
) -> tuple[int, bytes]:
    req = urllib.request.Request(url, method=method)
    req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(
            req, timeout=timeout_s, **urlopen_kwargs(url)
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _warn(msg: str) -> None:
    print(f"[tpu-k8s] WARNING: {msg}", file=sys.stderr)


def deregister_cluster(
    api_url: str, secret_key: str, cluster_name: str
) -> bool:
    """Delete the cluster's registry record and revoke its bootstrap token.
    Returns True when fully deregistered; False (with a stderr warning)
    on any failure — callers must not treat that as a destroy failure.
    Never raises: the infrastructure is already gone."""
    base = api_url.rstrip("/")
    cm_url = f"{base}/api/v1/namespaces/tpu-fleet/configmaps/cluster-{cluster_name}"
    try:
        # read the record first: it names the bootstrap token to revoke
        status, body = _request("GET", cm_url, secret_key)
        token_id = None
        if status == 200:
            try:
                doc = json.loads(body)
                data = doc.get("data") or {}
                token = data.get("registration_token", "")
            except (ValueError, AttributeError, TypeError):
                token = ""
            m = _TOKEN_RE.match(token if isinstance(token, str) else "")
            if m:
                token_id = m.group(1)

        failures = []
        if token_id:
            status, _ = _request(
                "DELETE",
                f"{base}/api/v1/namespaces/kube-system/secrets/"
                f"bootstrap-token-{token_id}",
                secret_key,
            )
            if status not in (200, 202, 404):
                failures.append(f"bootstrap token Secret (HTTP {status})")
        status, _ = _request("DELETE", cm_url, secret_key)
        if status not in (200, 202, 404):
            failures.append(f"registry ConfigMap (HTTP {status})")
        if failures:
            _warn(
                f"could not fully deregister cluster {cluster_name!r} from "
                f"the manager — failed: {', '.join(failures)}; its join "
                "token may still be valid. Delete the "
                f"tpu-fleet/cluster-{cluster_name} ConfigMap and the "
                "bootstrap token Secret by hand"
            )
        return not failures
    except Exception as e:  # noqa: BLE001 — must never fail a finished destroy
        _warn(
            f"cluster {cluster_name!r} deregistration skipped ({e}) — "
            "manager unreachable? Its join token may still be valid"
        )
        return False


def deregister_from_state(executor, state, cluster_key: str) -> bool:
    """Workflow-level entry: resolve the manager's live outputs and
    deregister ``cluster_key``. Same never-raises contract — every failure
    mode (unreadable outputs, missing outputs, HTTP errors) degrades to a
    warning, because the caller's infrastructure is already destroyed."""
    from tpu_kubernetes.state import MANAGER_KEY, cluster_key_parts

    parts = cluster_key_parts(cluster_key)
    try:
        outputs = executor.output(state, MANAGER_KEY)
    except Exception as e:  # noqa: BLE001
        outputs = {}
        _warn(f"could not read manager outputs for deregistration ({e})")
    api_url = outputs.get("api_url")
    secret_key = outputs.get("secret_key")
    if not (parts and api_url and secret_key):
        _warn(
            f"cluster {cluster_key} was NOT deregistered from the manager "
            "(no live api_url/secret_key outputs) — its join token may "
            "still be valid; see tpu_kubernetes/destroy/deregister.py"
        )
        return False
    return deregister_cluster(str(api_url), str(secret_key), parts[1])
