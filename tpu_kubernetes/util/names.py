"""Name validation and collision-free hostname series generation.

reference: create/node.go:350-380 (``getNewHostnames``) — given a hostname
prefix and the set of existing hostnames in the cluster, produce the next N
names ``{prefix}-{i}`` without colliding, filling gaps from 1 upward.
"""

from __future__ import annotations

import re

NAME_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9-]*$")


def validate_name(value: str) -> str | None:
    """Validator usable with Config.get / Prompter.text: returns an error
    message or None. Underscores are forbidden because they are the state-doc
    key separators (see state/document.py); dots are forbidden because module
    keys must be valid Terraform module names (letters/digits/underscores/
    dashes only)."""
    if not value:
        return "a name is required"
    if not NAME_RE.match(value):
        return (
            "names must start with an alphanumeric and contain only "
            "alphanumerics and '-'"
        )
    return None


def new_hostnames(prefix: str, count: int, existing: set[str] | list[str]) -> list[str]:
    """Next ``count`` collision-free hostnames ``{prefix}-{i}``.

    reference: create/node.go:350-380 — indexes start at 1 and skip any index
    already taken (whether created by us earlier or by hand).
    """
    taken = set(existing)
    out: list[str] = []
    i = 1
    while len(out) < count:
        candidate = f"{prefix}-{i}"
        if candidate not in taken:
            out.append(candidate)
            taken.add(candidate)
        i += 1
    return out
