"""One chokepoint for env-var parsing across serve/obs/shell.

The same three idioms were re-derived in a dozen modules —
``int(env.get("X", "8"))`` (crashes the process on a typo'd value),
``int(env.get("X", "") or default)`` (silently treats ``"abc"`` as a
crash too), and per-module ``_int_env`` fallback helpers (flightrec,
incidents) that at least survived. This module is the one surviving
spelling, and the one place the env-contract static pass
(tpu_kubernetes/analysis/envcontract.py) has to understand:

* **Bad values fall back, loudly.** A deployment with
  ``SERVE_BATCH=eight`` serves with the default batch and one stderr
  warning instead of dying in the pod restart loop — config mistakes
  degrade, they don't outage (the stance every obs module already
  took; now the serve/shell paths agree).
* **Empty string means unset.** ``SERVE_KV_POOL_MB=""`` is the
  default, matching the ``or default`` idiom these helpers replace.
* **One falsy-string rule.** :func:`env_bool` is the
  ``truthy_env`` rule ("", "0", "false", "no", "off" → False) the
  batch job and HTTP server already shared; serve/job.py's
  ``truthy_env`` now delegates here.

Every helper takes the mapping explicitly or defaults to
``os.environ`` — the serve stack injects its env for testability, the
shell/obs layers read the process env.
"""

from __future__ import annotations

import os
from typing import Mapping

FALSY = ("", "0", "false", "no", "off")


def _resolve(env: Mapping[str, str] | None) -> Mapping[str, str]:
    return os.environ if env is None else env


def _warn(name: str, raw: str, default) -> None:
    from tpu_kubernetes.util import log

    log.warn(
        f"env {name}={raw!r} is not a valid value; using default "
        f"{default!r}"
    )


def env_str(name: str, default: str = "", *,
            env: Mapping[str, str] | None = None) -> str:
    """The raw value, or ``default`` when unset/empty."""
    return _resolve(env).get(name, "") or default


def env_int(name: str, default: int, *,
            env: Mapping[str, str] | None = None) -> int:
    raw = _resolve(env).get(name, "")
    if raw is None or str(raw).strip() == "":
        return default
    try:
        return int(str(raw).strip())
    except (ValueError, TypeError):
        _warn(name, raw, default)
        return default


def env_float(name: str, default: float, *,
              env: Mapping[str, str] | None = None) -> float:
    raw = _resolve(env).get(name, "")
    if raw is None or str(raw).strip() == "":
        return default
    try:
        return float(str(raw).strip())
    except (ValueError, TypeError):
        _warn(name, raw, default)
        return default


def env_bool(name: str, default: bool = False, *,
             env: Mapping[str, str] | None = None) -> bool:
    """The shared falsy-string rule: unset → ``default``; set →
    anything outside :data:`FALSY` (case/space-insensitive) is True."""
    raw = _resolve(env).get(name)
    if raw is None:
        return default
    return str(raw).strip().lower() not in FALSY
