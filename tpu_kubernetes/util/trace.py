"""Structured step timing.

The reference has no tracing at all — only fmt.Println progress lines
(SURVEY §5.1; reference: cmd/create.go:46,53,60). Since the north-star metric
is create→first-train-step latency, every workflow phase here runs under a
:func:`phase` timer and the spans are retrievable/dumpable as JSON.
"""

from __future__ import annotations

import contextlib
import json
import sys
import time
from dataclasses import dataclass, field

from tpu_kubernetes.util import log


@dataclass
class Span:
    name: str
    start: float
    end: float | None = None
    meta: dict = field(default_factory=dict)

    @property
    def seconds(self) -> float:
        return (self.end if self.end is not None else time.monotonic()) - self.start


class Tracer:
    def __init__(self, stream=None, enabled: bool = True):
        self.spans: list[Span] = []
        self.stream = stream if stream is not None else sys.stderr
        self.enabled = enabled

    @contextlib.contextmanager
    def phase(self, name: str, **meta):
        span = Span(name=name, start=time.monotonic(), meta=dict(meta))
        self.spans.append(span)
        show = self.enabled and log.level() >= log.NORMAL
        if show:
            print(f"[tpu-k8s] ▶ {name}", file=self.stream)
        try:
            yield span
        finally:
            span.end = time.monotonic()
            if show:
                print(f"[tpu-k8s] ✓ {name} ({span.seconds:.1f}s)", file=self.stream)

    def mark(self) -> int:
        """Current span count — pass to :meth:`report` to scope one run's
        spans when several workflows share a process (tests, silent-install
        fan-out)."""
        return len(self.spans)

    def report(self, since: int = 0) -> list[dict]:
        return [
            {"phase": s.name, "seconds": round(s.seconds, 3), **s.meta}
            for s in self.spans[since:]
        ]

    def dump_json(self) -> str:
        return json.dumps(self.report())


# module-level default tracer; workflows use this unless handed another
TRACER = Tracer()
