"""Structured step timing.

The reference has no tracing at all — only fmt.Println progress lines
(SURVEY §5.1; reference: cmd/create.go:46,53,60). Since the north-star metric
is create→first-train-step latency, every workflow phase here runs under a
:func:`phase` timer and the spans are retrievable/dumpable as JSON.

Spans also feed the structured-event channel (obs/events.py): each phase
carries a span id, its parent span's id (phases nest — terraform init
inside apply manager), and the ambient run/correlation id, and emits
span_start/span_end JSONL events when a sink is configured.

The tracer is thread-safe and BOUNDED: a long-lived server process runs
phases forever, so the span store is a ring (``max_spans``) — `mark()`
hands out monotonic positions that stay valid across evictions, and
:meth:`reset` drops history explicitly.
"""

from __future__ import annotations

import contextlib
import json
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from tpu_kubernetes.obs import events
from tpu_kubernetes.util import log


@dataclass
class Span:
    name: str
    start: float
    end: float | None = None
    meta: dict = field(default_factory=dict)
    span_id: str = ""
    parent_id: str | None = None
    run_id: str | None = None

    @property
    def seconds(self) -> float:
        return (self.end if self.end is not None else time.monotonic()) - self.start


class Tracer:
    # enough for hundreds of workflow runs in one process; a serve/train
    # process that phases forever stays O(max_spans) memory
    DEFAULT_MAX_SPANS = 4096

    def __init__(self, stream=None, enabled: bool = True,
                 max_spans: int = DEFAULT_MAX_SPANS):
        self._spans: deque[Span] = deque(maxlen=max_spans)
        self._total = 0          # spans ever recorded (marks stay valid
        self._lock = threading.Lock()  # across ring evictions)
        self.stream = stream if stream is not None else sys.stderr
        self.enabled = enabled

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    @contextlib.contextmanager
    def phase(self, name: str, quiet: bool = False, **meta):
        """Record a span over the block. ``quiet`` suppresses the
        progress lines only (per-request server spans must not spam
        stderr) — recording and event emission are unaffected."""
        span = Span(
            name=name, start=time.monotonic(), meta=dict(meta),
            span_id=events.new_id(),
            parent_id=events.current_span_id(),
            run_id=events.current_run_id(),
        )
        with self._lock:
            self._spans.append(span)
            self._total += 1
        events.emit(
            "span_start", span=span.span_id, parent=span.parent_id,
            name=name, **meta,
        )
        show = self.enabled and not quiet and log.level() >= log.NORMAL
        if show:
            print(f"[tpu-k8s] ▶ {name}", file=self.stream)
        try:
            with events.parent_scope(span.span_id):
                yield span
        finally:
            span.end = time.monotonic()
            events.emit(
                "span_end", span=span.span_id, parent=span.parent_id,
                name=name, seconds=round(span.seconds, 6), **meta,
            )
            if show:
                print(f"[tpu-k8s] ✓ {name} ({span.seconds:.1f}s)", file=self.stream)

    def record(self, name: str, seconds: float, *, run_id: str = "",
               parent_id: str | None = None, end: float | None = None,
               **meta) -> Span:
        """Append an already-finished span of known duration — for work
        measured outside a ``with phase(...)`` block (the continuous
        scheduler times its segment on the device clock and records the
        span after the fact, carrying links to the resident requests'
        traces in ``meta``)."""
        t_end = time.monotonic() if end is None else end
        span = Span(
            name=name, start=t_end - max(0.0, float(seconds)), end=t_end,
            meta=dict(meta), span_id=events.new_id(),
            parent_id=parent_id, run_id=run_id,
        )
        with self._lock:
            self._spans.append(span)
            self._total += 1
        events.emit(
            "span_end", span=span.span_id, parent=span.parent_id,
            name=name, seconds=round(span.seconds, 6), **meta,
        )
        return span

    def mark(self) -> int:
        """Current span count — pass to :meth:`report` to scope one run's
        spans when several workflows share a process (tests, silent-install
        fan-out). Marks are positions in the FULL history, so they remain
        meaningful after ring eviction."""
        with self._lock:
            return self._total

    def report(self, since: int = 0) -> list[dict]:
        with self._lock:
            dropped = self._total - len(self._spans)
            spans = list(self._spans)[max(0, since - dropped):]
        return [
            {"phase": s.name, "seconds": round(s.seconds, 3), **s.meta}
            for s in spans
        ]

    def reset(self, since: int | None = None) -> None:
        """Drop recorded spans: everything, or (with ``since`` = an earlier
        :meth:`mark`) only spans recorded before that mark — how a
        long-lived process trims history it has already reported."""
        with self._lock:
            if since is None:
                self._spans.clear()
                return
            dropped = self._total - len(self._spans)
            for _ in range(min(max(0, since - dropped), len(self._spans))):
                self._spans.popleft()

    def dump_json(self) -> str:
        return json.dumps(self.report())


def span_tree(spans: list[Span], run_id: str) -> list[dict]:
    """The spans of one run as a nested tree (parent links resolved) —
    what ``GET /debug/trace/<run_id>`` serves. Spans whose parent was
    evicted from the ring (or lives in another process) become roots, so
    a partial history still renders."""
    mine = [s for s in spans if s.run_id == run_id]
    nodes = {
        s.span_id: {
            "name": s.name,
            "seconds": round(s.seconds, 6),
            **({"meta": s.meta} if s.meta else {}),
            "children": [],
        }
        for s in mine
    }
    roots: list[dict] = []
    for s in mine:
        parent = nodes.get(s.parent_id) if s.parent_id else None
        if parent is not None and parent is not nodes[s.span_id]:
            parent["children"].append(nodes[s.span_id])
        else:
            roots.append(nodes[s.span_id])
    return roots


# module-level default tracer; workflows use this unless handed another
TRACER = Tracer()
