"""Interactive prompt utilities (the reference's promptui analog).

The reference uses promptui for free-text and select prompts
(e.g. create/manager.go:33-55) and a dedicated yes/no confirmation
(reference: util/confirm_prompt.go:10-35). Here a single injectable
:class:`Prompter` carries all three so workflow code is testable with
:class:`ScriptedPrompter`.
"""

from __future__ import annotations

import getpass
import sys
from typing import Callable, Sequence


class PromptError(Exception):
    pass


class Prompter:
    """Terminal prompter reading from stdin."""

    def text(
        self,
        label: str,
        default: str | None = None,
        validate: Callable[[str], str | None] | None = None,
        secret: bool = False,
    ) -> str:
        while True:
            suffix = f" [{default}]" if default not in (None, "") else ""
            try:
                if secret:
                    raw = getpass.getpass(f"{label}{suffix}: ")
                else:
                    raw = input(f"{label}{suffix}: ")
            except EOFError as e:
                raise PromptError(f"stdin closed while prompting for {label!r}") from e
            value = raw.strip() or (default or "")
            if validate is not None:
                err = validate(value)
                if err:
                    print(f"  ✗ {err}", file=sys.stderr)
                    continue
            if value:
                return value
            print("  ✗ a value is required", file=sys.stderr)

    def select(self, label: str, options: Sequence[str]) -> str:
        if not options:
            raise PromptError(f"no options available for {label!r}")
        print(f"{label}:")
        for i, opt in enumerate(options, 1):
            print(f"  {i}. {opt}")
        while True:
            try:
                raw = input(f"Select [1-{len(options)}]: ").strip()
            except EOFError as e:
                raise PromptError(f"stdin closed while prompting for {label!r}") from e
            if raw.isdigit() and 1 <= int(raw) <= len(options):
                return options[int(raw) - 1]
            if raw in options:
                return raw
            print("  ✗ invalid selection", file=sys.stderr)

    def confirm(self, label: str) -> bool:
        """Yes/no gate. reference: util/confirm_prompt.go:10-35."""
        try:
            raw = input(f"{label} (yes/no): ").strip().lower()
        except EOFError:
            return False
        return raw in ("y", "yes")


class ScriptedPrompter(Prompter):
    """Deterministic prompter for tests: answers come from a queue; running
    out of answers is a hard error (mirrors how reference tests force the
    non-interactive error path, e.g. destroy/cluster_test.go:19-100)."""

    def __init__(self, answers: Sequence[str] = (), confirm_answers: Sequence[bool] = ()):
        self.answers = list(answers)
        self.confirm_answers = list(confirm_answers)
        self.log: list[str] = []

    def _pop(self, label: str) -> str:
        if not self.answers:
            raise PromptError(f"unexpected prompt: {label!r}")
        self.log.append(label)
        return self.answers.pop(0)

    def text(self, label, default=None, validate=None, secret=False):  # type: ignore[override]
        value = self._pop(label) or (default or "")
        if validate is not None:
            err = validate(value)
            if err:
                raise PromptError(f"scripted answer for {label!r} invalid: {err}")
        return value

    def select(self, label, options):  # type: ignore[override]
        value = self._pop(label)
        if value not in options:
            raise PromptError(
                f"scripted answer {value!r} for {label!r} not in options {list(options)}"
            )
        return value

    def confirm(self, label):  # type: ignore[override]
        self.log.append(label)
        if not self.confirm_answers:
            return False
        return self.confirm_answers.pop(0)
