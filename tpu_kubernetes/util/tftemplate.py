"""Minimal terraform ``templatefile()`` renderer for the ``.sh.tpl``
provisioning templates.

Covers exactly what the in-tree templates use — ``${name}`` variable
substitution and the ``$$`` escape (terraform renders ``$${x}`` as the
literal ``${x}``) — so tests can render every template hermetically and
syntax-check the result without a terraform binary (VERDICT round-1: the
provisioning layer had zero coverage and that's where the real bug lived).
No HCL expressions, conditionals, or loops: a template that needs those
should fail loudly here rather than render wrongly.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Any, Mapping

_TOKEN_RE = re.compile(r"\$\$\{|\$\{([^}]*)\}")
_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class TemplateError(Exception):
    pass


def render_template(text: str, variables: Mapping[str, Any]) -> str:
    """Render ``${name}`` placeholders from ``variables``.

    Raises :class:`TemplateError` for placeholders that are missing from
    ``variables`` or are not plain variable names (an HCL expression in a
    template is beyond this renderer — and beyond what our templates may
    use).
    """

    def sub(m: re.Match) -> str:
        if m.group(0) == "$${":
            return "${"
        name = m.group(1)
        if not _NAME_RE.match(name):
            raise TemplateError(
                f"unsupported template expression ${{{name}}} — only plain "
                "variable names are renderable (and allowed in our templates)"
            )
        if name not in variables:
            raise TemplateError(f"template variable {name!r} not supplied")
        return str(variables[name])

    return _TOKEN_RE.sub(sub, text)


def render_template_file(path: str | Path, variables: Mapping[str, Any]) -> str:
    try:
        return render_template(Path(path).read_text(), variables)
    except TemplateError as e:
        raise TemplateError(f"{path}: {e}") from None


def template_variables(text: str) -> set[str]:
    """The set of variable names a template interpolates (escapes excluded)."""
    names = set()
    for m in _TOKEN_RE.finditer(text):
        if m.group(0) == "$${":
            continue
        if _NAME_RE.match(m.group(1)):
            names.add(m.group(1))
    return names
