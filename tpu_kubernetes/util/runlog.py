"""Persist one workflow run's phase timings to the state backend.

The reference has no observability beyond stdout (SURVEY §5.1/§5.5), yet the
north-star metric is create→first-train-step latency — so every workflow
records its phase breakdown (render/validate/apply/…) as a run report next
to the state document (``runs/<millis>.json``), where ``get manager`` and
``get runs`` surface it.

Each report also carries the run/correlation id (the same id on every
structured event the run emitted, obs/events.py) and a snapshot of the
terraform command metrics (durations + failure counts, shell/executor.py)
accumulated in this process — so a single report answers both "what did
this run spend its time on" and "how has terraform been behaving here".
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any

from tpu_kubernetes.obs import REGISTRY, events
from tpu_kubernetes.util.trace import TRACER

# the metric families snapshotted into run reports (the terraform layer —
# per-run phases already cover the workflow itself)
REPORT_METRIC_PREFIX = "tpu_tf_"

# runs/ retention: reports kept per manager (the backends prune on write,
# newest kept). One policy for every backend so `get runs` reads the same
# horizon whether the state lives on disk or in a bucket.
DEFAULT_RUNS_KEEP = 50


def runs_keep(default: int | None = None) -> int:
    """How many run reports to retain per manager. ``TPU_K8S_RUNS_KEEP``
    wins; otherwise ``default`` (a backend's configured cap) or the
    project default. Never below 1 — the latest run must survive."""
    raw = os.environ.get("TPU_K8S_RUNS_KEEP", "")
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass  # a bad override must not break persisting the report
    return max(1, default if default is not None else DEFAULT_RUNS_KEEP)


def record_run(
    backend: Any,
    manager: str,
    command: str,
    since: int,
    status: str = "ok",
    run_id: str | None = None,
    **extra: Any,
) -> None:
    """Write a run report; never let observability break the workflow."""
    phases = TRACER.report(since=since)
    report = {
        "command": command,
        "manager": manager,
        "status": status,
        "run_id": run_id or events.current_run_id() or events.new_id(),
        "finished_at": time.time(),
        "total_seconds": round(sum(p["seconds"] for p in phases), 3),
        "phases": phases,
        **extra,
    }
    metrics = {
        name: fam
        for name, fam in REGISTRY.snapshot(prefix=REPORT_METRIC_PREFIX).items()
        if fam["samples"]  # dry runs register families but never sample them
    }
    if metrics:
        report["metrics"] = metrics
    try:
        backend.persist_run_report(manager, report)
    except Exception as e:  # noqa: BLE001 — observability must not fail a run
        from tpu_kubernetes.util import log

        log.warn(f"could not persist run report: {e}")


@contextlib.contextmanager
def run_recorder(backend: Any, manager: str, command: str, **extra: Any):
    """Record the run whichever way it ends: failed runs are exactly the
    ones worth inspecting in ``get manager``, so an exception records
    ``status: error`` (with the phases that did complete) and re-raises.
    Yields a dict the workflow may add extras to (cluster=…, nodes=…).

    The whole block runs under one run/correlation id: every phase span
    and structured event inside carries it, and the persisted report
    names it — grep the JSONL event stream by that id to replay the run.
    """
    mark = TRACER.mark()
    info = dict(extra)
    with events.run_context() as rid:
        events.emit("run_start", command=command, manager=manager)
        try:
            yield info
        except BaseException as e:
            events.emit("run_end", command=command, manager=manager,
                        status="error", error=str(e)[:200])
            record_run(backend, manager, command, mark, status="error",
                       run_id=rid, error=str(e)[:200], **info)
            raise
        events.emit("run_end", command=command, manager=manager, status="ok")
        record_run(backend, manager, command, mark, run_id=rid, **info)
