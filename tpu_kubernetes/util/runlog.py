"""Persist one workflow run's phase timings to the state backend.

The reference has no observability beyond stdout (SURVEY §5.1/§5.5), yet the
north-star metric is create→first-train-step latency — so every workflow
records its phase breakdown (render/validate/apply/…) as a run report next
to the state document (``runs/<millis>.json``), where ``get manager``
surfaces the latest one.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any

from tpu_kubernetes.util.trace import TRACER


def record_run(
    backend: Any,
    manager: str,
    command: str,
    since: int,
    status: str = "ok",
    **extra: Any,
) -> None:
    """Write a run report; never let observability break the workflow."""
    phases = TRACER.report(since=since)
    report = {
        "command": command,
        "manager": manager,
        "status": status,
        "finished_at": time.time(),
        "total_seconds": round(sum(p["seconds"] for p in phases), 3),
        "phases": phases,
        **extra,
    }
    try:
        backend.persist_run_report(manager, report)
    except Exception as e:  # noqa: BLE001 — observability must not fail a run
        from tpu_kubernetes.util import log

        log.warn(f"could not persist run report: {e}")


@contextlib.contextmanager
def run_recorder(backend: Any, manager: str, command: str, **extra: Any):
    """Record the run whichever way it ends: failed runs are exactly the
    ones worth inspecting in ``get manager``, so an exception records
    ``status: error`` (with the phases that did complete) and re-raises.
    Yields a dict the workflow may add extras to (cluster=…, nodes=…)."""
    mark = TRACER.mark()
    info = dict(extra)
    try:
        yield info
    except BaseException:
        record_run(backend, manager, command, mark, status="error", **info)
        raise
    record_run(backend, manager, command, mark, **info)
