"""SSH key utilities.

reference: util/ssh_utils.go:13-42 — derive the MD5 public-key fingerprint
(colon-separated hex, the Triton/Joyent API format) from a private key file,
retrying with a passphrase prompt if the key is encrypted.
"""

from __future__ import annotations

import hashlib
from pathlib import Path


class SSHKeyError(Exception):
    pass


def public_key_md5_fingerprint(
    private_key_path: str, passphrase: str | None = None
) -> str:
    """MD5 fingerprint aa:bb:... of the public half of an SSH private key.

    reference: util/ssh_utils.go:13-42 (including the encrypted-key retry —
    callers catch :class:`SSHKeyNeedsPassphrase` and re-call with a
    passphrase).
    """
    try:
        from cryptography.hazmat.primitives import serialization
    except ImportError as e:  # pragma: no cover
        raise SSHKeyError("cryptography package unavailable") from e

    data = Path(private_key_path).expanduser().read_bytes()
    pw = passphrase.encode() if passphrase else None
    try:
        key = serialization.load_ssh_private_key(data, password=pw)
    except ValueError:
        try:
            key = serialization.load_pem_private_key(data, password=pw)
        except TypeError as e:
            raise SSHKeyNeedsPassphrase(str(private_key_path)) from e
        except ValueError as e:
            raise SSHKeyError(f"cannot parse private key {private_key_path}: {e}") from e
    except TypeError as e:
        raise SSHKeyNeedsPassphrase(str(private_key_path)) from e

    pub = key.public_key().public_bytes(
        encoding=serialization.Encoding.OpenSSH,
        format=serialization.PublicFormat.OpenSSH,
    )
    # OpenSSH format: "<type> <base64>"; fingerprint is md5 of the raw blob.
    import base64

    blob = base64.b64decode(pub.split()[1])
    digest = hashlib.md5(blob).hexdigest()
    return ":".join(digest[i:i + 2] for i in range(0, len(digest), 2))


class SSHKeyNeedsPassphrase(SSHKeyError):
    """Raised when the key is encrypted and no/wrong passphrase was given."""
