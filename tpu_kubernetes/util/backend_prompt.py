"""Backend selection (the reference's PromptForBackend).

reference: util/backend_prompt.go:18-168 — choose Local or Manta (with full
Manta credential prompting). Ours: local or gcs (the Manta analog).
"""

from __future__ import annotations

from tpu_kubernetes.backend import Backend, LocalBackend
from tpu_kubernetes.config import Config

BACKEND_PROVIDERS = ["local", "gcs"]


def prompt_for_backend(cfg: Config) -> Backend:
    provider = cfg.get(
        "backend_provider",
        prompt="state backend",
        choices=BACKEND_PROVIDERS,
        default="local",
    )
    if provider == "local":
        return LocalBackend()
    if provider == "gcs":
        from tpu_kubernetes.backend import new_gcs_backend

        bucket = cfg.get("gcs_bucket", prompt="GCS bucket for state")
        return new_gcs_backend(str(bucket))
    raise ValueError(f"unknown backend provider {provider!r}")
