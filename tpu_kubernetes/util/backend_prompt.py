"""Backend selection (the reference's PromptForBackend).

reference: util/backend_prompt.go:18-168 — choose Local or Manta (with full
Manta credential prompting). Ours: local, gcs, or s3 (the Manta analogs;
s3 also covers S3-compatible stores via ``s3_endpoint``).
"""

from __future__ import annotations

from tpu_kubernetes.backend import Backend, LocalBackend
from tpu_kubernetes.config import Config

BACKEND_PROVIDERS = ["local", "gcs", "s3"]


def prompt_for_backend(cfg: Config) -> Backend:
    provider = cfg.get(
        "backend_provider",
        prompt="state backend",
        choices=BACKEND_PROVIDERS,
        default="local",
    )
    if provider == "local":
        return LocalBackend()
    if provider == "gcs":
        from tpu_kubernetes.backend import new_gcs_backend

        bucket = cfg.get("gcs_bucket", prompt="GCS bucket for state")
        return new_gcs_backend(str(bucket))
    if provider == "s3":
        # full credential prompting, like the reference's Manta flow
        # (util/backend_prompt.go:49-168)
        from tpu_kubernetes.backend import new_s3_backend

        return new_s3_backend(
            str(cfg.get("s3_bucket", prompt="S3 bucket for state")),
            str(cfg.get("aws_access_key", prompt="AWS access key")),
            str(cfg.get("aws_secret_key", prompt="AWS secret key", secret=True)),
            region=str(cfg.get("aws_region", default="us-east-1")),
            endpoint=str(cfg.get("s3_endpoint", default="")),
        )
    raise ValueError(f"unknown backend provider {provider!r}")
