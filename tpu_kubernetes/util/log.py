"""One leveled logger for the CLI's human-facing channel (SURVEY §5.5:
the reference has no log levels at all — bare fmt.Println everywhere).

Three levels, set once by the CLI entry point:

  quiet (-q)        — warnings and errors only
  normal (default)  — + progress (phase markers, dry-run notices)
  verbose (--verbose) — + debug detail (rendered paths, API calls)

Everything here goes to STDERR: stdout belongs to command RESULTS
(kubeconfig text, `get` JSON) so they stay pipeable. The machine-readable
channel is the per-run report (util/runlog.py), not this."""

from __future__ import annotations

import sys

QUIET, NORMAL, VERBOSE = 0, 1, 2
_level = NORMAL


def set_level(level: int) -> None:
    global _level
    _level = level


def set_verbosity(quiet: bool = False, verbose: bool = False) -> None:
    """CLI flag mapping; --verbose wins when both are passed."""
    set_level(VERBOSE if verbose else QUIET if quiet else NORMAL)


def level() -> int:
    return _level


def debug(msg: str) -> None:
    if _level >= VERBOSE:
        print(f"[tpu-k8s] {msg}", file=sys.stderr)


def info(msg: str) -> None:
    if _level >= NORMAL:
        print(f"[tpu-k8s] {msg}", file=sys.stderr)


def warn(msg: str) -> None:
    """Warnings always print — quiet mode is for progress chatter, not for
    hiding that a best-effort step failed."""
    print(f"[tpu-k8s] WARNING: {msg}", file=sys.stderr)


def error(msg: str) -> None:
    print(f"error: {msg}", file=sys.stderr)
