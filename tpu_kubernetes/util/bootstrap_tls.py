"""The trust-bootstrap TLS stance, in one place.

Talking to a manager's kube API before its CA is locally trusted (fetching
/cacerts for a kubeconfig, revoking credentials during destroy) is the
same first-contact problem the joining agents solve with ``curl -ks`` +
checksum pinning (install_node_agent.sh.tpl). Both Python callers share
this helper so a future hardening change (e.g. CA pinning from the fleet
registry) lands in exactly one spot.
"""

from __future__ import annotations

import ssl
from typing import Any


def urlopen_kwargs(url: str) -> dict[str, Any]:
    """kwargs for ``urllib.request.urlopen``: an unverified SSL context for
    https URLs (the trust bootstrap), nothing for http."""
    if not url.startswith("https:"):
        return {}
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return {"context": ctx}
