"""The trust-bootstrap TLS stance, in one place.

Talking to a manager's kube API before its CA is locally trusted (fetching
/cacerts for a kubeconfig, revoking credentials during destroy, node
lifecycle calls) is the same first-contact problem the joining agents solve
with ``curl -ks`` + checksum pinning (install_node_agent.sh.tpl). Python
callers share these helpers so the policy lives in exactly one spot:

* ``urlopen_kwargs`` — fully unverified context. ONLY for the /cacerts
  bootstrap fetch itself (nothing secret is sent on that request).
* ``pinned_urlopen_kwargs`` — TOFU-pin (ADVICE r03): fetch /cacerts once
  (unverified), verify its sha256 against the ``ca_checksum`` recorded at
  cluster registration when one is available, then use that CA to verify
  every subsequent request. Credential-bearing calls (they send the
  fleet-admin token) go through THIS, never the unverified context — an
  active MITM on a destroy/repair path would otherwise capture the
  fleet-wide admin credential.
"""

from __future__ import annotations

import hashlib
import ssl
import urllib.request
from typing import Any


class BootstrapTLSError(Exception):
    """CA pinning failed — the checksum recorded at registration does not
    match what the endpoint serves now."""


def urlopen_kwargs(url: str) -> dict[str, Any]:
    """kwargs for ``urllib.request.urlopen``: an unverified SSL context for
    https URLs (the trust bootstrap), nothing for http. Use ONLY for the
    /cacerts fetch — see pinned_urlopen_kwargs for everything else."""
    if not url.startswith("https:"):
        return {}
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return {"context": ctx}


def pinned_urlopen_kwargs(
    api_url: str, ca_checksum: str | None = None, timeout_s: float = 15.0
) -> dict[str, Any]:
    """kwargs for urlopen with the server's own CA pinned.

    Fetches ``<api_url>/cacerts`` (k3s serves the cluster CA there —
    unverified by necessity, this IS the trust bootstrap), verifies its
    sha256 against ``ca_checksum`` when the caller has one recorded, and
    returns a context that REQUIRES that CA from then on. check_hostname
    stays off (managers are routinely addressed by bare IP), but an
    attacker without the cluster CA's key can no longer terminate TLS.

    Raises BootstrapTLSError on checksum mismatch and propagates fetch
    errors — callers on best-effort paths catch and warn."""
    if not api_url.startswith("https:"):
        return {}
    url = api_url.rstrip("/") + "/cacerts"
    with urllib.request.urlopen(
        url, timeout=timeout_s, **urlopen_kwargs(url)
    ) as resp:
        pem = resp.read()
    if not pem:
        raise BootstrapTLSError(f"{url} returned an empty body")
    actual = hashlib.sha256(pem).hexdigest()
    if ca_checksum and actual != ca_checksum:
        raise BootstrapTLSError(
            f"cluster CA checksum mismatch: registration recorded "
            f"{ca_checksum}, {url} serves {actual} — refusing to send "
            "credentials (possible MITM or rebuilt control plane)"
        )
    ctx = ssl.create_default_context(cadata=pem.decode("utf-8", "strict"))
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    return {"context": ctx}
