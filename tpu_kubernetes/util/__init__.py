from tpu_kubernetes.util.names import new_hostnames, validate_name  # noqa: F401
from tpu_kubernetes.util.prompts import (  # noqa: F401
    PromptError,
    Prompter,
    ScriptedPrompter,
)
from tpu_kubernetes.util.trace import TRACER, Span, Tracer  # noqa: F401
