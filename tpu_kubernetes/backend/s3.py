"""S3 object store for the state backend (the reference's Manta parity,
rounded out: SURVEY §7 phase 6 asks for "Manta→GCS/S3 backend parity" —
GCS landed in round 1, this is the S3 side).

No boto3 in the runtime: requests are signed with a self-contained AWS
Signature V4 implementation over stdlib ``hashlib``/``hmac``/``urllib`` —
the same stance as the Triton CloudAPI http-signature client
(catalog/triton.py). Works against AWS S3 and S3-compatible stores via
``endpoint`` (MinIO, Ceph RGW, GCS's S3 interop mode).

Reference analog: backend/manta/backend.go:50-95 builds an SSH-key-signed
Manta client by hand rather than pulling a heavyweight SDK; same spirit.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from tpu_kubernetes.backend.base import BackendError
from tpu_kubernetes.backend.objectstore import ObjectStore, ObjectStoreBackend

_ALGO = "AWS4-HMAC-SHA256"
_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret_key: str, date: str, region: str, service: str = "s3") -> bytes:
    """The SigV4 key-derivation chain (AWS docs: "Deriving the signing key")."""
    k = _hmac(("AWS4" + secret_key).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_query(params: dict[str, str]) -> str:
    """Sorted, URI-encoded query string per the SigV4 canonical form."""
    return "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(params.items())
    )


def sign_request(
    method: str,
    host: str,
    path: str,
    query: dict[str, str],
    headers: dict[str, str],
    payload: bytes,
    *,
    access_key: str,
    secret_key: str,
    region: str,
    now: datetime.datetime | None = None,
) -> dict[str, str]:
    """Return ``headers`` plus ``x-amz-*`` and ``Authorization`` for one
    request. Pure function of its inputs (``now`` injectable) so the test
    suite can pin it to the official AWS SigV4 vectors."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest() if payload else _EMPTY_SHA256

    all_headers = {
        **{k.lower(): v.strip() for k, v in headers.items()},
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed_names = ";".join(sorted(all_headers))
    canonical_headers = "".join(
        f"{k}:{all_headers[k]}\n" for k in sorted(all_headers)
    )
    canonical = "\n".join([
        method,
        urllib.parse.quote(path, safe="/"),
        canonical_query(query),
        canonical_headers,
        signed_names,
        payload_hash,
    ])
    scope = f"{date}/{region}/s3/aws4_request"
    string_to_sign = "\n".join([
        _ALGO, amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])
    signature = hmac.new(
        signing_key(secret_key, date, region),
        string_to_sign.encode(), hashlib.sha256,
    ).hexdigest()
    authorization = (
        f"{_ALGO} Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}"
    )
    out = dict(headers)
    out.update({
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
        "Authorization": authorization,
    })
    return out


class S3Store(ObjectStore):
    """Minimal S3 REST client: GET/PUT/DELETE object + ListObjectsV2.

    ``put_if_absent`` uses S3 conditional writes (``If-None-Match: *``;
    412 = already exists). Stores that predate conditional writes answer
    501 — surfaced as a BackendError naming the limitation rather than
    silently downgrading the locking guarantee (the reference's Manta
    backend has exactly that unguarded gap, backend/manta/backend.go:32).
    """

    def __init__(
        self,
        bucket: str,
        *,
        access_key: str,
        secret_key: str,
        region: str = "us-east-1",
        endpoint: str = "",
        timeout_s: float = 30.0,
    ):
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        # path-style addressing: one code path for AWS and S3-compatibles
        self.base = (endpoint.rstrip("/") if endpoint
                     else f"https://s3.{region}.amazonaws.com")
        self.timeout_s = timeout_s
        self._conditional_verified = False

    def _request(
        self,
        method: str,
        key: str = "",
        query: dict[str, str] | None = None,
        payload: bytes = b"",
        headers: dict[str, str] | None = None,
        ok: tuple[int, ...] = (200,),
    ) -> tuple[int, bytes]:
        query = query or {}
        parsed = urllib.parse.urlparse(self.base)
        # include any endpoint path prefix (reverse-proxied S3-compatibles,
        # e.g. https://proxy/minio) in the SIGNED path — signing only the
        # bucket path would 403 with SignatureDoesNotMatch on every call
        base_path = parsed.path.rstrip("/")
        path = f"{base_path}/{self.bucket}" + (f"/{key}" if key else "")
        signed = sign_request(
            method, parsed.netloc, path, query, headers or {}, payload,
            access_key=self.access_key, secret_key=self.secret_key,
            region=self.region,
        )
        url = (f"{parsed.scheme}://{parsed.netloc}"
               + urllib.parse.quote(path, safe="/"))
        if query:
            url += "?" + canonical_query(query)
        req = urllib.request.Request(url, data=payload or None, method=method)
        for k, v in signed.items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            body = e.read()
            if e.code in ok:
                return e.code, body
            raise BackendError(
                f"S3 {method} {path}: HTTP {e.code} "
                f"{body[:200].decode(errors='replace')}"
            ) from e
        except urllib.error.URLError as e:
            raise BackendError(f"S3 {method} {path}: {e}") from e

    def get(self, key: str) -> bytes | None:
        status, body = self._request("GET", key, ok=(200, 404))
        return None if status == 404 else body

    def put(self, key: str, data: bytes) -> None:
        self._request("PUT", key, payload=data)

    _NO_CONDITIONAL = (
        "this S3 endpoint does not honor conditional writes "
        "(If-None-Match) — state locking cannot be guaranteed; use the gcs "
        "or local backend, or an S3 service with conditional-write support"
    )

    def _verify_conditional_writes(self) -> None:
        """Once per store: prove the endpoint actually ENFORCES
        If-None-Match. Endpoints that predate conditional writes often
        ignore the unknown header and answer 200 — both lock contenders
        would then 'win', which is precisely the Manta no-locking gap
        (reference: backend/manta/backend.go:32) this backend closes."""
        # unique per process and under our prefix (ADVICE r03): a shared
        # fixed key let two CLIs verifying concurrently interleave (A's
        # DELETE between B's two PUTs → spurious 'endpoint does not honor
        # conditional writes'); a per-process key races only against itself.
        # try/finally: a transient error on the conditional PUT must not
        # orphan the probe object in the state prefix
        import uuid

        from tpu_kubernetes.backend.objectstore import PREFIX

        probe = f"{PREFIX}/.conditional-write-probe-{uuid.uuid4().hex}"
        self._request("PUT", probe, payload=b"probe")
        try:
            status, _ = self._request(
                "PUT", probe, payload=b"probe2",
                headers={"If-None-Match": "*"}, ok=(200, 409, 412, 501),
            )
        finally:
            self._request("DELETE", probe, ok=(200, 204, 404))
        if status not in (409, 412):
            raise BackendError(self._NO_CONDITIONAL)
        self._conditional_verified = True

    def put_if_absent(self, key: str, data: bytes) -> bool:
        if not self._conditional_verified:
            self._verify_conditional_writes()
        # 412 = key exists; 409 = ConditionalRequestConflict, AWS's answer
        # to SIMULTANEOUS If-None-Match writes — the loser of a lock race,
        # i.e. contention, not an infrastructure error
        status, _ = self._request(
            "PUT", key, payload=data,
            headers={"If-None-Match": "*"}, ok=(200, 409, 412, 501),
        )
        if status == 501:
            raise BackendError(self._NO_CONDITIONAL)
        return status == 200

    def delete(self, key: str) -> None:
        self._request("DELETE", key, ok=(200, 204, 404))

    def list(self, prefix: str) -> list[str]:
        names: list[str] = []
        token = ""
        while True:
            query = {"list-type": "2", "prefix": prefix}
            if token:
                query["continuation-token"] = token
            _, body = self._request("GET", query=query)
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            names.extend(
                el.text or ""
                for el in root.iter(f"{ns}Key")
            )
            truncated = root.findtext(f"{ns}IsTruncated") == "true"
            token = root.findtext(f"{ns}NextContinuationToken") or ""
            if not truncated or not token:
                return sorted(names)


class S3Backend(ObjectStoreBackend):
    """State backend over S3, injecting a ``terraform.backend.s3`` block so
    terraform's own tfstate is co-located (reference contract:
    backend/backend.go:24-26; Manta analog backend/manta/backend.go:196-205).
    """

    name = "s3"

    def __init__(self, store: ObjectStore, bucket: str, region: str,
                 lock_ttl_s: float = 3600.0):
        super().__init__(store, bucket=bucket, lock_ttl_s=lock_ttl_s)
        self.region = region

    def state_terraform_config(self, name: str):
        from tpu_kubernetes.backend.objectstore import PREFIX

        cfg = {
            "bucket": self.bucket,
            "key": f"{PREFIX}/{name}/terraform.tfstate",
            "region": self.region,
        }
        store = self.store
        if isinstance(store, S3Store):
            default = f"https://s3.{store.region}.amazonaws.com"
            if store.base != default:
                # S3-compatible endpoint: terraform must target the SAME
                # store the documents live in — otherwise tfstate silently
                # lands on real AWS. Credentials are deliberately NOT
                # embedded (the document is persisted to the shared state
                # bucket in plaintext): terraform's s3 backend reads the
                # standard AWS env chain — export AWS_ACCESS_KEY_ID /
                # AWS_SECRET_ACCESS_KEY before apply. Argument names are
                # the terraform ≥1.6 forms (endpoints.s3 / use_path_style).
                cfg.update({
                    "endpoints": {"s3": store.base},
                    "use_path_style": True,
                    "skip_credentials_validation": True,
                    "skip_metadata_api_check": True,
                    "skip_requesting_account_id": True,
                })
        return "terraform.backend.s3", cfg


def new_s3_backend(
    bucket: str,
    access_key: str,
    secret_key: str,
    region: str = "us-east-1",
    endpoint: str = "",
) -> S3Backend:
    store = S3Store(
        bucket, access_key=access_key, secret_key=secret_key,
        region=region, endpoint=endpoint,
    )
    return S3Backend(store, bucket=bucket, region=region)
