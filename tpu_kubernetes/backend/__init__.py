from tpu_kubernetes.backend.base import Backend, BackendError, LockError  # noqa: F401
from tpu_kubernetes.backend.local import LocalBackend  # noqa: F401
from tpu_kubernetes.backend.objectstore import (  # noqa: F401
    GCSStore,
    MemoryStore,
    ObjectStore,
    ObjectStoreBackend,
    new_gcs_backend,
)
from tpu_kubernetes.backend.s3 import (  # noqa: F401
    S3Backend,
    S3Store,
    new_s3_backend,
)
