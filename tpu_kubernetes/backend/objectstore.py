"""Object-store-backed state backend (the reference's Manta backend analog).

The reference persists state documents in Joyent Manta under
``/stor/triton-kubernetes/<name>/`` (reference: backend/manta/backend.go:18-31)
and injects a ``terraform.backend.manta`` block (:196-205) so terraform's
tfstate lives next to the document. Our analog is **GCS** (the natural home for
a GCP-TPU-first framework), expressed against a minimal ``ObjectStore``
protocol so the backend logic is hermetic: production uses :class:`GCSStore`
(JSON API over ``google.auth`` when available), tests use
:class:`MemoryStore`.

Unlike the reference's Manta backend — which has a known no-locking TODO
(reference: backend/manta/backend.go:32) — this backend takes a best-effort
advisory lock object per manager before persisting.
"""

from __future__ import annotations

import abc
import json
import time
from typing import Any

from tpu_kubernetes.backend.base import Backend, BackendError, LockError
from tpu_kubernetes.state import State

PREFIX = "tpu-kubernetes"
STATE_FILE = "main.tf.json"
LOCK_FILE = ".lock"


class ObjectStore(abc.ABC):
    """Minimal blob interface: get/put/delete/list under a bucket."""

    @abc.abstractmethod
    def get(self, key: str) -> bytes | None: ...

    @abc.abstractmethod
    def put(self, key: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def put_if_absent(self, key: str, data: bytes) -> bool:
        """Create-only put; returns False if the key already exists."""

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    def list(self, prefix: str) -> list[str]: ...


class MemoryStore(ObjectStore):
    """In-memory store for tests (the reference's backend/mocks analog)."""

    def __init__(self) -> None:
        self.blobs: dict[str, bytes] = {}

    def get(self, key: str) -> bytes | None:
        return self.blobs.get(key)

    def put(self, key: str, data: bytes) -> None:
        self.blobs[key] = data

    def put_if_absent(self, key: str, data: bytes) -> bool:
        if key in self.blobs:
            return False
        self.blobs[key] = data
        return True

    def delete(self, key: str) -> None:
        self.blobs.pop(key, None)

    def list(self, prefix: str) -> list[str]:
        return sorted(k for k in self.blobs if k.startswith(prefix))


class GCSStore(ObjectStore):
    """GCS JSON-API store. Constructed lazily so the framework works without
    GCP credentials; any use without them raises a clear error."""

    def __init__(self, bucket: str):
        self.bucket = bucket
        try:
            import google.auth  # type: ignore
            import google.auth.transport.requests  # type: ignore

            self._creds, _ = google.auth.default(
                scopes=["https://www.googleapis.com/auth/devstorage.read_write"]
            )
            self._authed_session = google.auth.transport.requests.AuthorizedSession(
                self._creds
            )
        except Exception as e:  # pragma: no cover - needs real GCP env
            raise BackendError(
                f"GCS backend requires Google Cloud credentials: {e}"
            ) from e

    def _url(self, key: str, upload: bool = False) -> str:  # pragma: no cover
        import urllib.parse

        quoted = urllib.parse.quote(key, safe="")
        if upload:
            return (
                f"https://storage.googleapis.com/upload/storage/v1/b/{self.bucket}"
                f"/o?uploadType=media&name={quoted}"
            )
        return f"https://storage.googleapis.com/storage/v1/b/{self.bucket}/o/{quoted}"

    def get(self, key: str) -> bytes | None:  # pragma: no cover
        r = self._authed_session.get(self._url(key) + "?alt=media")
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return r.content

    def put(self, key: str, data: bytes) -> None:  # pragma: no cover
        r = self._authed_session.post(self._url(key, upload=True), data=data)
        r.raise_for_status()

    def put_if_absent(self, key: str, data: bytes) -> bool:  # pragma: no cover
        r = self._authed_session.post(
            self._url(key, upload=True) + "&ifGenerationMatch=0", data=data
        )
        if r.status_code == 412:
            return False
        r.raise_for_status()
        return True

    def delete(self, key: str) -> None:  # pragma: no cover
        r = self._authed_session.delete(self._url(key))
        if r.status_code not in (204, 404):
            r.raise_for_status()

    def list(self, prefix: str) -> list[str]:  # pragma: no cover
        import urllib.parse

        base = (
            f"https://storage.googleapis.com/storage/v1/b/{self.bucket}/o"
            f"?prefix={urllib.parse.quote(prefix)}"
        )
        names: list[str] = []
        page_token = None
        while True:
            url = base + (f"&pageToken={page_token}" if page_token else "")
            r = self._authed_session.get(url)
            r.raise_for_status()
            body = r.json()
            names.extend(item["name"] for item in body.get("items", []))
            page_token = body.get("nextPageToken")
            if not page_token:
                return sorted(names)


class ObjectStoreBackend(Backend):
    """State backend over any :class:`ObjectStore`.

    Key layout (reference: backend/manta/backend.go:18-31):
      {PREFIX}/{manager}/main.tf.json
      {PREFIX}/{manager}/.lock
    """

    name = "gcs"

    def __init__(self, store: ObjectStore, bucket: str = "", lock_ttl_s: float = 3600.0):
        self.store = store
        self.bucket = bucket
        # TTL must exceed the longest gap between lock refreshes; the lock is
        # refreshed on every persist (i.e. right before and after apply), so
        # it bounds one terraform apply / one interactive prompt session
        self.lock_ttl_s = lock_ttl_s
        self._held: dict[str, str] = {}  # name → owner id, THIS instance's locks

    def _key(self, name: str, filename: str = STATE_FILE) -> str:
        return f"{PREFIX}/{name}/{filename}"

    def states(self) -> list[str]:
        names = set()
        for key in self.store.list(PREFIX + "/"):
            rest = key[len(PREFIX) + 1:]
            if rest.endswith("/" + STATE_FILE):
                names.add(rest.rsplit("/", 1)[0])
        return sorted(names)

    def state(self, name: str) -> State:
        data = self.store.get(self._key(name))
        return State(name, data)

    def persist_state(self, state: State) -> None:
        owner = self._held.get(state.name)
        if owner is not None:  # workflow already holds the lock
            # verify we STILL hold it (a slow apply can overrun the TTL and be
            # stale-broken by a contender; silently clobbering its document
            # would be worse than failing loudly) — then refresh the TTL clock
            key = self._key(state.name, LOCK_FILE)
            current = self.store.get(key)
            current_owner = None
            if current is not None:
                try:
                    current_owner = json.loads(current).get("owner")
                except (ValueError, AttributeError):
                    pass
            if current_owner != owner:
                raise LockError(
                    f"lock on state {state.name!r} was lost mid-workflow "
                    "(broken as stale by another process?) — NOT persisting"
                )
            self.store.put(
                key, json.dumps({"acquired_at": time.time(), "owner": owner}).encode()
            )
            self.store.put(self._key(state.name), state.to_bytes())
            return
        with self.lock(state.name):
            self.store.put(self._key(state.name), state.to_bytes())

    def delete_state(self, name: str) -> None:
        for key in self.store.list(f"{PREFIX}/{name}/"):
            self.store.delete(key)

    def state_terraform_config(self, name: str) -> tuple[str, Any]:
        return "terraform.backend.gcs", {
            "bucket": self.bucket,
            "prefix": f"{PREFIX}/{name}",
        }

    # {PREFIX}/{manager}/runs/{ns-timestamp}.json (SURVEY §5.1 gap: per-run phase
    # timings persisted next to the document, mirroring LocalBackend).
    # Retention is capped so a long-lived manager doesn't accumulate forever;
    # TPU_K8S_RUNS_KEEP overrides (util/runlog.py — one policy per backend).
    MAX_RUN_REPORTS = 50

    def persist_run_report(self, name: str, report: dict[str, Any]) -> None:
        from tpu_kubernetes.util.runlog import runs_keep

        ts = time.time_ns()
        self.store.put(
            self._key(name, f"runs/{ts}.json"),
            json.dumps(report, indent=2, sort_keys=True).encode(),
        )
        keys = sorted(self.store.list(self._key(name, "runs/")))
        for key in keys[:-runs_keep(self.MAX_RUN_REPORTS)]:
            self.store.delete(key)

    def run_reports(self, name: str) -> list[dict[str, Any]]:
        out = []
        for key in sorted(self.store.list(self._key(name, "runs/"))):
            data = self.store.get(key)
            if data is None:
                continue
            try:
                out.append(json.loads(data))
            except ValueError:
                continue
        return out

    def last_run_report(self, name: str) -> dict[str, Any] | None:
        # one LIST + one GET, not one GET per historical report
        for key in sorted(self.store.list(self._key(name, "runs/")),
                          reverse=True):
            data = self.store.get(key)
            if data is None:
                continue
            try:
                return json.loads(data)
            except ValueError:
                continue
        return None

    # -- advisory locking (fixes reference TODO backend/manta/backend.go:32).
    # Best-effort: stale-lock breaking is not atomic (two breakers can race),
    # but each lock carries an owner id and release only deletes a lock this
    # process still owns — a slow holder cannot delete a successor's lock.
    def lock(self, name: str):
        backend = self

        class _Lock:
            def __enter__(self_inner):
                import uuid

                self_inner.owner = uuid.uuid4().hex
                key = backend._key(name, LOCK_FILE)
                payload = json.dumps(
                    {"acquired_at": time.time(), "owner": self_inner.owner}
                ).encode()
                for _ in range(2):  # one retry: holder may release mid-probe
                    if backend.store.put_if_absent(key, payload):
                        backend._held[name] = self_inner.owner
                        return self_inner
                    existing = backend.store.get(key)
                    if existing is None:
                        continue  # released between probe and read — retry
                    try:
                        acquired = json.loads(existing).get("acquired_at", 0)
                    except (ValueError, AttributeError):
                        acquired = 0
                    if time.time() - acquired > backend.lock_ttl_s:
                        # stale lock: break it (best-effort, see note above)
                        backend.store.put(key, payload)
                        backend._held[name] = self_inner.owner
                        return self_inner
                    break
                raise LockError(
                    f"state {name!r} is locked by another process "
                    f"(delete {backend._key(name, LOCK_FILE)} to force)"
                )

            def __exit__(self_inner, *exc):
                backend._held.pop(name, None)
                key = backend._key(name, LOCK_FILE)
                current = backend.store.get(key)
                if current is not None:
                    try:
                        owner = json.loads(current).get("owner")
                    except (ValueError, AttributeError):
                        owner = None
                    if owner == self_inner.owner:
                        backend.store.delete(key)
                return False

        return _Lock()


def new_gcs_backend(bucket: str) -> ObjectStoreBackend:  # pragma: no cover
    return ObjectStoreBackend(GCSStore(bucket), bucket=bucket)
