"""Local-filesystem state backend.

Layout (mirrors reference backend/local/backend.go:14-19):

  ~/.tpu-kubernetes/<manager-name>/main.tf.json      — the state document
  ~/.tpu-kubernetes/<manager-name>/terraform.tfstate — terraform's own state

The root is overridable via the ``TPU_K8S_HOME`` environment variable or the
constructor, which is what the tests use for hermeticity.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import socket
import time
import uuid
from pathlib import Path
from typing import Any

from tpu_kubernetes.backend.base import Backend, LockError
from tpu_kubernetes.state import State

STATE_FILE = "main.tf.json"
TFSTATE_FILE = "terraform.tfstate"
LOCK_FILE = ".lock"


def default_root() -> Path:
    env = os.environ.get("TPU_K8S_HOME")
    if env:
        return Path(env)
    return Path.home() / ".tpu-kubernetes"


class LocalBackend(Backend):
    """reference: backend/local/backend.go (New :28, terraform backend config :123-132)."""

    name = "local"

    def __init__(self, root: str | Path | None = None, lock_ttl_s: float = 3600.0):
        self.root = Path(root) if root is not None else default_root()
        # TTL bounds one terraform apply / one interactive prompt session:
        # the lock's clock is refreshed on every persist (right before and
        # after apply), so only the gap between refreshes must fit in it
        self.lock_ttl_s = lock_ttl_s
        self._held: dict[str, str] = {}  # name → owner id, THIS instance's locks

    def _dir(self, name: str) -> Path:
        return self.root / name

    def states(self) -> list[str]:
        if not self.root.is_dir():
            return []
        return sorted(
            p.name for p in self.root.iterdir() if (p / STATE_FILE).is_file()
        )

    def state(self, name: str) -> State:
        path = self._dir(name) / STATE_FILE
        if path.is_file():
            return State(name, path.read_bytes())
        return State(name)

    def persist_state(self, state: State) -> None:
        self._refresh_held_lock(state.name)
        d = self._dir(state.name)
        d.mkdir(parents=True, exist_ok=True)
        tmp = d / (STATE_FILE + ".tmp")
        tmp.write_bytes(state.to_bytes())
        tmp.replace(d / STATE_FILE)

    @contextlib.contextmanager
    def _flock(self, name: str):
        """Native flock(2) serializing this state's lockfile critical
        sections between same-host processes (no-op context when the
        native runtime isn't built); TimeoutError → LockError so callers
        see the documented Backend error surface."""
        from tpu_kubernetes.native import FileLock

        try:
            with FileLock(self._dir(name) / (LOCK_FILE + ".flock")):
                yield
        except TimeoutError as e:
            raise LockError(
                f"could not serialize lockfile access for state {name!r}: {e}"
            ) from e

    def _refresh_held_lock(self, name: str) -> None:
        """If this instance holds ``name``'s lock, verify it wasn't stale-
        broken by a contender (fail loudly rather than clobber their work)
        and reset its TTL clock. The read-check-write is serialized under
        the same flock as acquisition — otherwise a refresh racing a
        contender's stale-break could clobber the contender's fresh lock
        and leave two processes believing they hold the state."""
        owner = self._held.get(name)
        if owner is None:
            return
        path = self._dir(name) / LOCK_FILE
        with self._flock(name):
            try:
                current = json.loads(path.read_bytes())
            except (ValueError, OSError):
                current = {}
            if current.get("owner") != owner:
                raise LockError(
                    f"lock on state {name!r} was lost mid-workflow "
                    "(broken as stale by another process?) — NOT persisting"
                )
            current["acquired_at"] = time.time()
            path.write_bytes(json.dumps(current).encode())

    def delete_state(self, name: str) -> None:
        d = self._dir(name)
        if d.is_dir():
            shutil.rmtree(d)

    def state_terraform_config(self, name: str) -> tuple[str, Any]:
        tfstate = self._dir(name) / TFSTATE_FILE
        return "terraform.backend.local", {"path": str(tfstate)}

    # runs/<ns-timestamp>.json next to main.tf.json (SURVEY §5.1: the reference has
    # no observability at all; the north-star latency must be readable here).
    # Retention is capped so a long-lived manager doesn't accumulate forever;
    # TPU_K8S_RUNS_KEEP overrides (util/runlog.py — one policy per backend).
    MAX_RUN_REPORTS = 50

    def persist_run_report(self, name: str, report: dict[str, Any]) -> None:
        from tpu_kubernetes.util.runlog import runs_keep

        d = self._dir(name) / "runs"
        d.mkdir(parents=True, exist_ok=True)
        ts = time.time_ns()
        tmp = d / f"{ts}.json.tmp"
        tmp.write_bytes(json.dumps(report, indent=2, sort_keys=True).encode())
        tmp.replace(d / f"{ts}.json")
        stale = sorted(d.glob("*.json"))[:-runs_keep(self.MAX_RUN_REPORTS)]
        for p in stale:
            p.unlink(missing_ok=True)

    def run_reports(self, name: str) -> list[dict[str, Any]]:
        d = self._dir(name) / "runs"
        if not d.is_dir():
            return []
        out = []
        for p in sorted(d.glob("*.json")):
            try:
                out.append(json.loads(p.read_bytes()))
            except ValueError:
                continue  # a torn write must not break `get manager`
        return out

    def last_run_report(self, name: str) -> dict[str, Any] | None:
        d = self._dir(name) / "runs"
        if not d.is_dir():
            return None
        for p in sorted(d.glob("*.json"), reverse=True):
            try:
                return json.loads(p.read_bytes())
            except ValueError:
                continue
        return None

    @contextlib.contextmanager
    def lock(self, name: str):
        """Lockfile with O_EXCL creation; stale locks (older than
        ``lock_ttl_s``, e.g. a crashed apply) are broken. Release only deletes
        a lock this context still owns, so a slow holder cannot delete its
        successor's lock.

        The acquire/stale-break and release read-check-delete critical
        sections additionally serialize on a native flock(2) when the C++
        runtime is built (tpu_kubernetes/native): two same-host contenders
        can otherwise both judge a lock stale and both break it. The flock
        guards only these short sections, not the whole workflow — the JSON
        file carries the cross-host ownership semantics."""
        path = self._dir(name) / LOCK_FILE
        path.parent.mkdir(parents=True, exist_ok=True)
        owner = uuid.uuid4().hex
        payload = json.dumps(
            {
                "owner": owner,
                "pid": os.getpid(),
                "host": socket.gethostname(),
                "acquired_at": time.time(),
            }
        ).encode()

        # write-then-link so the lockfile is never visible without its payload
        # (a contender reading a half-written lock must see it as HELD, not
        # stale, or two holders could both enter)
        tmp = path.with_name(f"{LOCK_FILE}.{owner}")
        tmp.write_bytes(payload)
        try:
            with self._flock(name):
                try:
                    os.link(tmp, path)  # atomic create; FileExistsError if held
                except FileExistsError:
                    info: dict = {}
                    try:
                        info = json.loads(path.read_bytes())
                    except (ValueError, OSError):
                        info = {"acquired_at": time.time()}  # unreadable ⇒ assume held
                    if time.time() - info.get("acquired_at", time.time()) > self.lock_ttl_s:
                        path.write_bytes(payload)  # stale: break it
                    else:
                        raise LockError(
                            f"state {name!r} is locked by pid {info.get('pid', '?')} on "
                            f"{info.get('host', '?')} (delete {path} to force)"
                        ) from None
        finally:
            tmp.unlink(missing_ok=True)
        self._held[name] = owner
        try:
            yield
        finally:
            self._held.pop(name, None)
            try:
                with self._flock(name):
                    if json.loads(path.read_bytes()).get("owner") == owner:
                        path.unlink()
            except LockError:
                # flock acquisition timed out during a clean unlock — still
                # delete our own lock unguarded (the pre-native behavior)
                # rather than strand contenders until the TTL expires
                try:
                    if json.loads(path.read_bytes()).get("owner") == owner:
                        path.unlink()
                except (ValueError, OSError):
                    pass
            except (ValueError, OSError):
                pass

    def __repr__(self) -> str:
        return f"LocalBackend(root={str(self.root)!r})"
