"""Local-filesystem state backend.

Layout (mirrors reference backend/local/backend.go:14-19):

  ~/.tpu-kubernetes/<manager-name>/main.tf.json      — the state document
  ~/.tpu-kubernetes/<manager-name>/terraform.tfstate — terraform's own state

The root is overridable via the ``TPU_K8S_HOME`` environment variable or the
constructor, which is what the tests use for hermeticity.
"""

from __future__ import annotations

import os
import shutil
from pathlib import Path
from typing import Any

from tpu_kubernetes.backend.base import Backend, BackendError
from tpu_kubernetes.state import State

STATE_FILE = "main.tf.json"
TFSTATE_FILE = "terraform.tfstate"


def default_root() -> Path:
    env = os.environ.get("TPU_K8S_HOME")
    if env:
        return Path(env)
    return Path.home() / ".tpu-kubernetes"


class LocalBackend(Backend):
    """reference: backend/local/backend.go (New :28, terraform backend config :123-132)."""

    name = "local"

    def __init__(self, root: str | Path | None = None):
        self.root = Path(root) if root is not None else default_root()

    def _dir(self, name: str) -> Path:
        return self.root / name

    def states(self) -> list[str]:
        if not self.root.is_dir():
            return []
        return sorted(
            p.name for p in self.root.iterdir() if (p / STATE_FILE).is_file()
        )

    def state(self, name: str) -> State:
        path = self._dir(name) / STATE_FILE
        if path.is_file():
            return State(name, path.read_bytes())
        return State(name)

    def persist_state(self, state: State) -> None:
        d = self._dir(state.name)
        d.mkdir(parents=True, exist_ok=True)
        tmp = d / (STATE_FILE + ".tmp")
        tmp.write_bytes(state.to_bytes())
        tmp.replace(d / STATE_FILE)

    def delete_state(self, name: str) -> None:
        d = self._dir(name)
        if d.is_dir():
            shutil.rmtree(d)

    def state_terraform_config(self, name: str) -> tuple[str, Any]:
        tfstate = self._dir(name) / TFSTATE_FILE
        return "terraform.backend.local", {"path": str(tfstate)}

    def __repr__(self) -> str:
        return f"LocalBackend(root={str(self.root)!r})"
