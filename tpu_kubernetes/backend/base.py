"""State persistence backend contract.

reference: backend/backend.go:7-27 — the ``Backend`` interface with
``State``, ``DeleteState``, ``PersistState``, ``States``, and
``StateTerraformConfig`` (the last returns the ``terraform.backend.*`` block
to inject into the document so terraform's own tfstate is co-located with the
framework's config).
"""

from __future__ import annotations

import abc
import contextlib
from typing import Any

from tpu_kubernetes.state import State


class BackendError(Exception):
    pass


class LockError(BackendError):
    """Raised when a state document's advisory lock is held elsewhere."""


class Backend(abc.ABC):
    """Persistence contract for named state documents."""

    @abc.abstractmethod
    def states(self) -> list[str]:
        """Names of all persisted cluster managers. reference: backend/backend.go:13."""

    @abc.abstractmethod
    def state(self, name: str) -> State:
        """Load (or create empty) the document for ``name``.
        reference: backend/backend.go:9."""

    @abc.abstractmethod
    def persist_state(self, state: State) -> None:
        """Write the document back. reference: backend/backend.go:11."""

    @abc.abstractmethod
    def delete_state(self, name: str) -> None:
        """Remove all storage for ``name``. reference: backend/backend.go:10."""

    @abc.abstractmethod
    def state_terraform_config(self, name: str) -> tuple[str, Any]:
        """Return ``(document_path, config_obj)`` for the ``terraform.backend.*``
        block that co-locates terraform's tfstate with this backend.
        reference: backend/backend.go:24-26."""

    def lock(self, name: str) -> contextlib.AbstractContextManager:
        """Advisory per-manager lock, held by workflows across the whole
        mutate → apply → persist window so two concurrent CLIs cannot
        interleave edits to one document. The reference has no locking at all
        (known TODO at backend/manta/backend.go:32); subclasses override.
        Raises :class:`LockError` if held elsewhere and not stale."""
        return contextlib.nullcontext()

    # -- per-run observability (no reference analog: SURVEY §5.1 gap) ------
    # The north-star metric is create→first-step latency; every workflow
    # persists its phase-timing breakdown next to the state document so the
    # latency is readable from the tool itself, not just a --timing stderr
    # dump. Default no-ops keep minimal/mock backends working.

    def persist_run_report(self, name: str, report: dict[str, Any]) -> None:
        """Store one workflow run's timing/status report under ``name``."""

    def run_reports(self, name: str) -> list[dict[str, Any]]:
        """All stored reports for ``name``, oldest first."""
        return []

    def last_run_report(self, name: str) -> dict[str, Any] | None:
        reports = self.run_reports(name)
        return reports[-1] if reports else None
