"""Precedence config system (the reference's viper analog).

Sources, highest precedence first (reference: cmd/root.go:39-40,48-67):

  1. explicit sets (CLI flags, YAML ``nodes:`` fan-out overrides)
  2. ``--config`` YAML file
  3. ``$TPU_K8S_HOME/config.yaml`` (analog of ``$HOME/.triton-kubernetes.yaml``)
  4. environment variables ``TPU_K8S_<UPPER_SNAKE_KEY>``
  5. interactive prompt — unless ``non_interactive``, in which case a missing
     key is a hard error (the universal reference idiom at e.g.
     create/manager.go:33-55: ``if viper.IsSet(k) {take} else if
     nonInteractive {error} else {prompt}``).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Callable, Sequence

import yaml

from tpu_kubernetes.util.prompts import Prompter

ENV_PREFIX = "TPU_K8S_"

_UNSET = object()


class ConfigError(Exception):
    pass


class Config:
    def __init__(
        self,
        values: dict[str, Any] | None = None,
        non_interactive: bool = False,
        prompter: Prompter | None = None,
        env: dict[str, str] | None = None,
    ):
        self._overrides: dict[str, Any] = {}
        self._values: dict[str, Any] = dict(values or {})
        # answers given at a prompt — cached separately from explicit
        # overrides so scoped/fresh child configs can drop ONLY these
        self._prompt_cache: dict[str, Any] = {}
        self.non_interactive = non_interactive
        self.prompter = prompter or Prompter()
        self._env = env if env is not None else os.environ  # type: ignore[assignment]

    @classmethod
    def load(
        cls,
        config_file: str | None = None,
        non_interactive: bool = False,
        prompter: Prompter | None = None,
    ) -> "Config":
        values: dict[str, Any] = {}
        home_cfg = Path(os.environ.get("TPU_K8S_HOME", str(Path.home() / ".tpu-kubernetes"))) / "config.yaml"
        if home_cfg.is_file():
            values.update(_load_yaml(home_cfg))
        if config_file:
            values.update(_load_yaml(Path(config_file)))
        return cls(values, non_interactive=non_interactive, prompter=prompter)

    # -- mutation ----------------------------------------------------------
    def set(self, key: str, value: Any) -> None:
        """Highest-precedence programmatic set (the reference's ``viper.Set``
        used by the YAML ``nodes:`` fan-out, create/cluster.go:165-217)."""
        self._overrides[key] = value

    def unset(self, key: str) -> None:
        self._overrides.pop(key, None)

    # -- lookup ------------------------------------------------------------
    def is_set(self, key: str) -> bool:
        return (
            key in self._overrides
            or key in self._values
            or (ENV_PREFIX + key.upper()) in self._env
            or key in self._prompt_cache
        )

    def peek(self, key: str, default: Any = None) -> Any:
        if key in self._overrides:
            return self._overrides[key]
        if key in self._values:
            return self._values[key]
        env_key = ENV_PREFIX + key.upper()
        if env_key in self._env:
            return self._env[env_key]
        if key in self._prompt_cache:
            return self._prompt_cache[key]
        return default

    def get(
        self,
        key: str,
        prompt: str | None = None,
        default: Any = _UNSET,
        choices: Sequence[str] | None = None,
        validate: Callable[[str], str | None] | None = None,
        secret: bool = False,
    ) -> Any:
        """The universal idiom: set → take; else non-interactive → error;
        else prompt (select if ``choices`` given, text otherwise)."""
        if self.is_set(key):
            value = self.peek(key)
            if choices is not None and value not in choices:
                raise ConfigError(
                    f"{key} must be one of {list(choices)}, got {value!r}"
                )
            if validate is not None:
                err = validate(str(value))
                if err:
                    raise ConfigError(f"invalid {key}: {err}")
            return value
        if self.non_interactive or (default is not _UNSET and prompt is None):
            # a default with no prompt label means "optional, just take it";
            # fields that should be offered interactively pass prompt=.
            if default is not _UNSET:
                return default
            raise ConfigError(f"{key} must be specified")
        label = prompt or key.replace("_", " ")
        if choices is not None:
            value = self.prompter.select(label, list(choices))
        else:
            value = self.prompter.text(
                label,
                default=None if default is _UNSET else str(default),
                validate=validate,
                secret=secret,
            )
        # cache so repeated gets (and the nodes: fan-out) see one answer
        self._prompt_cache[key] = value
        return value

    def get_bool(self, key: str, prompt: str | None = None, default: Any = _UNSET) -> bool:
        value = self.get(key, prompt=prompt, default=default)
        if isinstance(value, bool):
            return value
        return str(value).strip().lower() in ("1", "true", "yes", "y")

    def get_int(self, key: str, prompt: str | None = None, default: Any = _UNSET) -> int:
        def _check(s: str) -> str | None:
            try:
                int(s)
                return None
            except (TypeError, ValueError):
                return "must be an integer"

        value = self.get(key, prompt=prompt, default=default, validate=_check)
        return int(value)

    def confirm(self, label: str, force_key: str = "force") -> bool:
        """Destructive-action gate: ``force``/non-interactive skips the prompt.
        reference: cmd/destroy.go + util/confirm_prompt.go:10-35."""
        if self.get_bool(force_key, default=False):
            return True
        if self.non_interactive:
            return True
        return self.prompter.confirm(label)


def _load_yaml(path: Path) -> dict[str, Any]:
    try:
        data = yaml.safe_load(path.read_text())
    except yaml.YAMLError as e:
        raise ConfigError(f"invalid YAML in {path}: {e}") from e
    if data is None:
        return {}
    if not isinstance(data, dict):
        raise ConfigError(f"config file {path} must be a YAML mapping")
    return data
