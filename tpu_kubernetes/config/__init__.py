from tpu_kubernetes.config.config import Config, ConfigError  # noqa: F401
