"""Minimal fleet (kube) API client for the manager's control plane.

stdlib-only (urllib), bearer-token auth, CA pinned via the trust-bootstrap
helper (util/bootstrap_tls.py). This is deliberately not a kubernetes
client library: the framework's control-plane needs are a handful of
GET/PATCH/DELETE calls on Nodes/Pods/ConfigMaps/Secrets, and every caller
sits on a best-effort path (destroy/repair/get) where a tight, predictable
surface beats a dependency.

The reference has no analog — its control-plane API client is a bash
script calling Rancher REST (gcp-rancher-k8s/files/rancher_cluster.sh);
cluster/node teardown never talks to the control plane at all
(destroy/node.go:167-177), the leak the fleet.nodes module closes.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any

from tpu_kubernetes.util.bootstrap_tls import pinned_urlopen_kwargs


class FleetAPIError(Exception):
    pass


class FleetAPI:
    """One manager's kube API endpoint + fleet-admin bearer token.

    ``ca_checksum`` (recorded at cluster registration) pins the CA fetched
    from /cacerts; without it the CA is still trusted-on-first-use for the
    session — credentials never ride a fully-unverified connection."""

    def __init__(
        self,
        api_url: str,
        token: str,
        ca_checksum: str | None = None,
        timeout_s: float = 10.0,
    ) -> None:
        self.base = api_url.rstrip("/")
        self.token = token
        self.ca_checksum = ca_checksum
        self.timeout_s = timeout_s
        self._urlopen_kwargs: dict[str, Any] | None = None

    def _kwargs(self) -> dict[str, Any]:
        if self._urlopen_kwargs is None:
            self._urlopen_kwargs = pinned_urlopen_kwargs(
                self.base, self.ca_checksum, timeout_s=self.timeout_s
            )
        return self._urlopen_kwargs

    def request(
        self,
        method: str,
        path: str,
        body: Any = None,
        content_type: str = "application/json",
    ) -> tuple[int, Any]:
        """→ (status, parsed-JSON-or-None). 4xx/5xx come back as the status
        (no exception); transport errors raise — callers on best-effort
        paths catch broadly and warn."""
        from tpu_kubernetes.util import log

        log.debug(f"fleet API: {method} {path}")
        data = None
        req = urllib.request.Request(self.base + path, method=method)
        req.add_header("Authorization", f"Bearer {self.token}")
        if body is not None:
            data = json.dumps(body).encode()
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(
                req, data=data, timeout=self.timeout_s, **self._kwargs()
            ) as resp:
                raw = resp.read()
                status = resp.status
        except urllib.error.HTTPError as e:
            raw = e.read()
            status = e.code
        try:
            return status, json.loads(raw) if raw else None
        except ValueError:
            return status, None

    def get(self, path: str) -> tuple[int, Any]:
        return self.request("GET", path)

    def delete(self, path: str) -> tuple[int, Any]:
        return self.request("DELETE", path)

    def patch_strategic(self, path: str, body: Any) -> tuple[int, Any]:
        return self.request(
            "PATCH", path, body,
            content_type="application/strategic-merge-patch+json",
        )
