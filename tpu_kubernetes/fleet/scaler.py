"""Fleet actuators: the two hands of the observability-driven controller.

The FleetController (obs/controller.py) decides *what* to do from the
alert stream; this module is *how* it touches the world, kept thin and
separately testable:

* :class:`FleetScaler` — renders a replica-count Terraform-JSON state
  document (state/document.py) and applies it through an
  :class:`~tpu_kubernetes.shell.executor.Executor`. With a
  :class:`~tpu_kubernetes.shell.executor.FakeExecutor` the whole
  scale-up path runs end-to-end on CPU, and every apply is a recorded
  call the tests (and the action ledger) can count.
* :class:`HTTPDrainer` — ``POST /drain`` against a serving instance
  (the Kubernetes preStop contract in serve/server.py): admission stops,
  resident work finishes, and only then may Terraform reap the node —
  scale-down never drops resident tokens.

Both carry W3C trace context on outbound calls where applicable, so an
actuation shows up in the same distributed trace as the alert that
caused it.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Callable

from tpu_kubernetes.obs import tracing
from tpu_kubernetes.shell.executor import Executor
from tpu_kubernetes.state.document import State


def default_render(replicas: int) -> State:
    """The minimal replica-count document: one ``fleet`` module whose
    only knob is ``replicas``. Real deployments pass their own render
    callable that folds the count into the full cluster document."""
    return State("fleet", {"module": {"fleet": {"replicas": int(replicas)}}})


def _module_key(instance: str) -> str:
    """A ``host:port`` instance label as a Terraform module address
    component (dots and colons are not address characters)."""
    return instance.replace(":", "-").replace(".", "-")


class FleetScaler:
    """Terraform-path actuator: ``scale_to(n)`` renders the document for
    ``n`` replicas and applies it; ``replace(instance)`` re-applies the
    current count targeted at one worker's module (Terraform recreates
    just that node). ``replicas`` tracks the last applied count — the
    controller's notion of current fleet size."""

    def __init__(self, executor: Executor,
                 render: Callable[[int], State] | None = None,
                 replicas: int = 1):
        self.executor = executor
        self.render = render or default_render
        self.replicas = int(replicas)

    def scale_to(self, replicas: int, targets: tuple[str, ...] = ()) -> None:
        n = int(replicas)
        self.executor.apply(self.render(n), targets=tuple(targets))
        self.replicas = n

    def replace(self, instance: str) -> None:
        self.executor.apply(
            self.render(self.replicas),
            targets=(f"module.{_module_key(instance)}",),
        )


class HTTPDrainer:
    """``POST /drain`` to a ``host:port`` instance and return the
    server's JSON (``{"status": ..., "accepted": ...}``, HTTP 202 — the
    drain completes after the response). Failures raise; the controller
    records them as a failed action and backs off."""

    def __init__(self, timeout_s: float = 5.0, scheme: str = "http"):
        self.timeout_s = float(timeout_s)
        self.scheme = scheme

    def drain(self, instance: str) -> dict:
        req = urllib.request.Request(
            f"{self.scheme}://{instance}/drain", data=b"", method="POST",
            headers=tracing.outbound_headers({
                "Accept": "application/json",
                "User-Agent": "tpu-k8s-controller",
            }),
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            body = resp.read().decode("utf-8", "replace")
        try:
            out = json.loads(body)
        except ValueError:
            out = {}
        return out if isinstance(out, dict) else {}
