"""Kube Node lifecycle + health for destroyed/preempted machines.

Under the shared-control-plane topology (docs/design/topology.md: clusters
are node pools of ONE fleet control plane), destroying a node's machine
does not remove its ``Node`` object from the manager's kube API — it stays
behind permanently-NotReady, the scheduler keeps seeing it, and a repaired
cluster accumulates ghosts. The reference sidesteps this only because its
clusters are separate control planes destroyed whole; its node destroy
tells nobody (reference: destroy/node.go:167-177 — the VM dies and the
Rancher Node object leaks). This module closes that for every teardown
path (``destroy node``, ``destroy cluster``, ``repair --replace_nodes``):

    best-effort cordon → eviction-free drain → DELETE /api/v1/nodes/<name>

Eviction-free on purpose: the machine is gone (or about to be destroyed by
the same workflow), so the eviction API's PDB ceremony would only stall on
a kubelet that will never answer; deleting the pods lets controllers
(JobSet included) reschedule immediately.

Same never-fail-the-destroy contract as destroy/deregister.py: the
infrastructure is already gone, so every failure here degrades to a
warning, never an exception.

A destroyed module maps to kube Node objects two ways:
* plain node — the Node named exactly ``hostname``
  (install_node_agent.sh.tpl sets the hostname before joining);
* TPU pod slice — one Node per slice host, named ``<hostname>-host-<i>``
  and labeled ``tpu-kubernetes/slice=<hostname>``
  (install_tpu_agent.sh.tpl); resolved by label so partial joins and
  future naming changes still match.
"""

from __future__ import annotations

import urllib.parse

from tpu_kubernetes.fleet.api import FleetAPI
from tpu_kubernetes.util.log import warn as _warn

_OK = (200, 202)
_OK_OR_GONE = (200, 202, 404)


def list_nodes(api: FleetAPI, selector: str | None = None) -> list[dict]:
    """Node items, optionally filtered by a label selector. Raises
    FleetAPIError-shaped trouble as plain exceptions — callers on
    best-effort paths catch broadly."""
    path = "/api/v1/nodes"
    if selector:
        path += "?labelSelector=" + urllib.parse.quote(selector)
    status, doc = api.get(path)
    if status != 200 or not isinstance(doc, dict):
        raise RuntimeError(f"list nodes (HTTP {status})")
    return list(doc.get("items") or [])


def node_names_for_host(api: FleetAPI, hostname: str) -> list[str]:
    """The kube Node names a state-document host resolves to (see module
    docstring): the Node named ``hostname`` if it exists, plus every Node
    labeled as a host of slice ``hostname``."""
    names = []
    status, _ = api.get(f"/api/v1/nodes/{hostname}")
    if status == 200:
        names.append(hostname)
    for item in list_nodes(api, f"tpu-kubernetes/slice={hostname}"):
        name = ((item.get("metadata") or {}).get("name")) or ""
        if name and name not in names:
            names.append(name)
    return names


def node_ready(item: dict) -> bool:
    """kube Node item → is its Ready condition True."""
    conditions = ((item.get("status") or {}).get("conditions")) or []
    for cond in conditions:
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


def cordon(api: FleetAPI, name: str) -> bool:
    status, _ = api.patch_strategic(
        f"/api/v1/nodes/{name}", {"spec": {"unschedulable": True}}
    )
    return status in _OK


def _pods_bound_to(api: FleetAPI, name: str) -> list[dict]:
    """Pod items with spec.nodeName == ``name``. Raises on API trouble —
    callers decide whether that is fatal (drain: no) or advisory (count)."""
    selector = urllib.parse.quote(f"spec.nodeName={name}")
    status, doc = api.get(f"/api/v1/pods?fieldSelector={selector}")
    if status != 200 or not isinstance(doc, dict):
        raise RuntimeError(f"list pods on {name} (HTTP {status})")
    return list(doc.get("items") or [])


def count_running_pods_on(api: FleetAPI, name: str) -> int | None:
    """How many RUNNING pods are bound to Node ``name``; None when the
    answer could not be obtained (callers must not read None as zero —
    'could not check' and 'verified idle' are different messages)."""
    try:
        pods = _pods_bound_to(api, name)
    except Exception:  # noqa: BLE001 — advisory only
        return None
    return sum(
        1 for p in pods
        if ((p.get("status") or {}).get("phase", "Running")) == "Running"
    )


def delete_pods_on(api: FleetAPI, name: str) -> int:
    """Eviction-free drain: delete every pod bound to ``name`` with grace 0
    (the kubelet is dead — graceful termination has no executor). Returns
    how many deletes were issued; failures are counted, not raised."""
    try:
        pods = _pods_bound_to(api, name)
    except Exception:  # noqa: BLE001 — drain is best-effort
        return 0
    issued = 0
    for pod in pods:
        meta = pod.get("metadata") or {}
        ns, pod_name = meta.get("namespace"), meta.get("name")
        if not (ns and pod_name):
            continue
        api.delete(
            f"/api/v1/namespaces/{ns}/pods/{pod_name}?gracePeriodSeconds=0"
        )
        issued += 1
    return issued


def drain_and_delete(api: FleetAPI, hostnames: list[str]) -> bool:
    """cordon → drain → DELETE the Node objects for every hostname.
    True when every resolved Node was deleted (404 counts as done); False
    (with warnings) otherwise. Never raises."""
    failures: list[str] = []
    try:
        for hostname in hostnames:
            names = node_names_for_host(api, hostname)
            for name in names:
                cordon(api, name)          # best-effort, dead-node PATCH may 404
                delete_pods_on(api, name)
                status, _ = api.delete(f"/api/v1/nodes/{name}")
                if status not in _OK_OR_GONE:
                    failures.append(f"{name} (HTTP {status})")
    except Exception as e:  # noqa: BLE001 — must never fail a finished destroy
        _warn(
            f"kube Node cleanup skipped ({e}) — manager unreachable? "
            f"Stale Node objects may remain for: {', '.join(hostnames)}"
        )
        return False
    if failures:
        _warn(
            "could not delete kube Node object(s) "
            f"{', '.join(failures)} — delete them by hand "
            "(kubectl delete node <name>) or the scheduler keeps seeing "
            "machines that no longer exist"
        )
    return not failures


def expected_node_names(state, cluster_key: str) -> dict[str, list[str]]:
    """hostname (state-document host) → the kube Node names it should have
    joined as: a plain node joins as ``hostname``; a TPU pod slice (its
    module config carries ``tpu_hosts``) joins one Node per host named
    ``<hostname>-host-<i>`` (install_tpu_agent.sh.tpl)."""
    out: dict[str, list[str]] = {}
    for hostname, key in state.nodes(cluster_key).items():
        module = state.module(key) or {}
        n_hosts = module.get("tpu_hosts")
        try:
            n_hosts = int(n_hosts)
        except (TypeError, ValueError):
            n_hosts = 0
        if n_hosts > 0:
            out[hostname] = [f"{hostname}-host-{i}" for i in range(n_hosts)]
        else:
            out[hostname] = [hostname]
    return out


def diagnose_nodes(
    api: FleetAPI, expected: dict[str, list[str]]
) -> dict[str, dict[str, str]]:
    """Preemption/failure detection: ask the manager about every expected
    Node. → hostname → {node_name: "Ready" | "NotReady" | "missing"}.

    "missing" = the machine never joined or its Node was deleted (a
    preempted-and-GC'd slice host); "NotReady" = joined but the kubelet
    stopped answering (a preempted machine whose Node object lingers —
    under this repo's topology the usual signature, see module docstring).
    Raises when the manager itself can't answer — detection must fail
    loudly rather than report a healthy-looking empty fleet."""
    out: dict[str, dict[str, str]] = {}
    for hostname, names in expected.items():
        report: dict[str, str] = {}
        for name in names:
            status, item = api.get(f"/api/v1/nodes/{name}")
            if status == 404:
                report[name] = "missing"
            elif status == 200 and isinstance(item, dict):
                report[name] = "Ready" if node_ready(item) else "NotReady"
            else:
                raise RuntimeError(f"GET node {name}: HTTP {status}")
        out[hostname] = report
    return out


def unhealthy_hosts(diagnosis: dict[str, dict[str, str]]) -> list[str]:
    """Hosts with any non-Ready member — the replace-target set for
    ``repair --auto`` (a slice is one schedulable unit: one dead host
    means the whole slice module gets recreated)."""
    return sorted(
        hostname
        for hostname, report in diagnosis.items()
        if any(status != "Ready" for status in report.values())
    )


def resolve_fleet_api(executor, state, cluster_key: str) -> FleetAPI | None:
    """Build a FleetAPI from the manager's live outputs (+ the cluster's
    recorded ca_checksum for CA pinning, while its module still has
    outputs). Returns None — with a warning — when the outputs aren't
    available; callers treat that as 'skip the best-effort cleanup'.

    Call BEFORE destroying modules: afterwards the cluster's outputs (and
    on full destroys the manager's) are gone."""
    from tpu_kubernetes.state import MANAGER_KEY

    try:
        outputs = executor.output(state, MANAGER_KEY)
    except Exception as e:  # noqa: BLE001
        _warn(f"could not read manager outputs ({e})")
        return None
    api_url = outputs.get("api_url")
    secret_key = outputs.get("secret_key")
    if not (api_url and secret_key):
        return None
    ca_checksum = None
    try:
        ca_checksum = executor.output(state, cluster_key).get("ca_checksum")
    except Exception:  # noqa: BLE001 — pinning is best-available, not required
        pass
    return FleetAPI(
        str(api_url), str(secret_key),
        ca_checksum=str(ca_checksum) if ca_checksum else None,
    )
