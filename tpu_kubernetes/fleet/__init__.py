"""Fleet control-plane client: the framework's window into the manager's
kube API (node lifecycle on destroy/repair, health for preemption
detection, registration records), plus the actuators the
observability-driven controller (obs/controller.py) drives — a
Terraform-path scaler and an HTTP drainer. See fleet/api.py,
fleet/nodes.py, and fleet/scaler.py."""

from tpu_kubernetes.fleet.api import FleetAPI, FleetAPIError  # noqa: F401
from tpu_kubernetes.fleet.nodes import (  # noqa: F401
    drain_and_delete,
    list_nodes,
    node_names_for_host,
    node_ready,
    resolve_fleet_api,
)
from tpu_kubernetes.fleet.scaler import (  # noqa: F401
    FleetScaler,
    HTTPDrainer,
    default_render,
)
