"""Fleet control-plane client: the framework's window into the manager's
kube API (node lifecycle on destroy/repair, health for preemption
detection, registration records). See fleet/api.py and fleet/nodes.py."""

from tpu_kubernetes.fleet.api import FleetAPI, FleetAPIError  # noqa: F401
from tpu_kubernetes.fleet.nodes import (  # noqa: F401
    drain_and_delete,
    list_nodes,
    node_names_for_host,
    node_ready,
    resolve_fleet_api,
)
