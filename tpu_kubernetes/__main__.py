import sys

from tpu_kubernetes.cli import main

sys.exit(main())
