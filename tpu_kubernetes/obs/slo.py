"""Sliding-window SLOs with multi-window burn-rate alerting.

The fleet aggregator (obs/aggregate.py) answers "what are the numbers";
this module answers "is the fleet keeping its promises". Each
:class:`SLOTracker` reduces a :class:`~tpu_kubernetes.obs.aggregate.
FleetSnapshot` to a good/total event pair (availability from status
codes, latency and TTFT from histogram buckets vs a threshold), records
the pair as counter series in a history store (obs/tsdb.py — the same
store the monitor trends and ``get history`` read, one source of
truth), and evaluates the multi-window burn-rate rule from the SRE
workbook:

* **fast** — burn ≥ 14.4× over BOTH the 5m and 1h windows (budget gone
  in hours → page);
* **slow** — burn ≥ 6× over BOTH the 30m and 6h windows (budget gone in
  days → ticket).

Burn rate = (bad events / total events over the window) / (1 − target).
Requiring both windows makes alerts resolve quickly once the bleeding
stops (the short window goes clean first) without flapping on blips.

Alerts move ``ok → pending → firing``: a breach must hold for ``for_s``
seconds before it pages, and — symmetrically — a firing alert must stay
clean for ``resolve_for_s`` seconds before it resolves, so burn
hovering at the threshold cannot strobe firing/resolved at the pager
(``resolve_for_s=0``, the default, keeps the historical
instant-resolve). Clocks are injectable (``now=``) so tests can drive
hours of window arithmetic in milliseconds; production callers just
omit it.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable

from tpu_kubernetes.obs.aggregate import FleetSnapshot
from tpu_kubernetes.obs.tsdb import TSDB

# (windows that must BOTH breach, burn multiple, severity)
FAST_WINDOWS = (300.0, 3600.0)
FAST_BURN = 14.4
SLOW_WINDOWS = (1800.0, 21600.0)
SLOW_BURN = 6.0

# the series an objective writes into its history store — one pair per
# tracker, distinguished by the ``slo`` label, so many objectives can
# share one store with the fleet scrape series
GOOD_SERIES = "slo_good_total"
TOTAL_SERIES = "slo_events_total"

OK = "ok"
PENDING = "pending"
FIRING = "firing"


@dataclass
class Alert:
    """One SLO's evaluated state at a point in time."""

    slo: str
    state: str
    target: float
    severity: str = ""            # "page" (fast) / "ticket" (slow) when breaching
    since: float | None = None    # when the current pending/firing began
    age_s: float | None = None    # seconds in the current pending/firing state
    burn_fast: float = 0.0        # min burn over the fast window pair
    burn_slow: float = 0.0        # min burn over the slow window pair
    description: str = ""

    def to_dict(self) -> dict:
        """The pager-facing shape: ``since``/``age_s`` let receivers
        dedupe re-notifications of one incident and age it; the burn
        multiples (with their firing thresholds) say how fast the error
        budget is going."""
        return {
            "slo": self.slo,
            "state": self.state,
            "target": self.target,
            "severity": self.severity,
            "since": self.since,
            "age_s": None if self.age_s is None else round(self.age_s, 3),
            "burn_fast": round(self.burn_fast, 3),
            "burn_slow": round(self.burn_slow, 3),
            "burn_fast_threshold": FAST_BURN,
            "burn_slow_threshold": SLOW_BURN,
            "description": self.description,
        }


class SLOTracker:
    """One objective: a good/total reduction over snapshots plus the
    burn-rate state machine. Thread-safe (the monitor loop observes
    while a CLI/status thread may evaluate). Readings live in a
    :class:`~tpu_kubernetes.obs.tsdb.TSDB` — pass ``store=`` to share
    the monitor's fleet store (the burn windows then read from the same
    retained history as every trend column), or omit it for a private
    one."""

    def __init__(self, name: str, target: float,
                 source: Callable[[FleetSnapshot], tuple[float, float]],
                 for_s: float = 60.0, description: str = "",
                 store: TSDB | None = None, resolve_for_s: float = 0.0):
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), got {target}")
        self.name = name
        self.target = target
        self.for_s = for_s
        self.resolve_for_s = max(0.0, float(resolve_for_s))
        self.description = description
        self._source = source
        self.store = store if store is not None else TSDB(max_bytes=1 << 20)
        self._labels = (("slo", name),)
        self._state = OK
        self._since: float | None = None
        self._clear_since: float | None = None  # resolve hold-down anchor
        self._last_severity = ""                # shown while holding clean
        self._lock = threading.Lock()

    def observe(self, snapshot: FleetSnapshot,
                now: float | None = None) -> None:
        """Record one aggregated cycle's good/total reading."""
        now = time.time() if now is None else now
        good, total = self._source(snapshot)
        with self._lock:
            self.store.append(GOOD_SERIES, float(good), self._labels,
                              ts=now, kind="counter")
            self.store.append(TOTAL_SERIES, float(total), self._labels,
                              ts=now, kind="counter")

    def _reading(self, ts: float) -> tuple[float, float] | None:
        """The (good, total) pair at the newest sample with timestamp
        ≤ ts, falling back to the oldest retained reading — with history
        shorter than the window the oldest reading is the baseline (rate
        over the data we have; cold starts must not divide by fiction)."""
        total = (self.store.sample_at_or_before(TOTAL_SERIES, self._labels, ts)
                 or self.store.first_sample(TOTAL_SERIES, self._labels))
        if total is None:
            return None
        good = (self.store.sample_at_or_before(GOOD_SERIES, self._labels, ts)
                or self.store.first_sample(GOOD_SERIES, self._labels))
        return (good[1] if good is not None else 0.0, total[1])

    def _burn(self, window: float, now: float) -> float:
        """Burn multiple over [now - window, now], read from the store."""
        latest = self._reading(float("inf"))
        if latest is None:
            return 0.0
        baseline = self._reading(now - window)
        if baseline is None:
            return 0.0
        delta_total = latest[1] - baseline[1]
        if delta_total <= 0:
            return 0.0
        delta_bad = delta_total - (latest[0] - baseline[0])
        ratio = min(1.0, max(0.0, delta_bad) / delta_total)
        return ratio / (1.0 - self.target)

    def evaluate(self, now: float | None = None) -> Alert:
        """Advance the state machine against the current history and
        return this objective's alert state."""
        now = time.time() if now is None else now
        with self._lock:
            burn_fast = min(self._burn(w, now) for w in FAST_WINDOWS)
            burn_slow = min(self._burn(w, now) for w in SLOW_WINDOWS)
            if burn_fast >= FAST_BURN:
                severity = "page"
            elif burn_slow >= SLOW_BURN:
                severity = "ticket"
            else:
                severity = ""
            if severity:
                self._clear_since = None
                self._last_severity = severity
                if self._state == OK:
                    self._state, self._since = PENDING, now
                elif (self._state == PENDING and self._since is not None
                        and now - self._since >= self.for_s):
                    self._state = FIRING
            elif self._state == FIRING and self.resolve_for_s > 0:
                # symmetric hold-down: a firing alert must stay clean
                # resolve_for_s before resolving, so burn hovering at
                # the threshold doesn't strobe firing/resolved
                if self._clear_since is None:
                    self._clear_since = now
                if now - self._clear_since >= self.resolve_for_s:
                    self._state, self._since = OK, None
                    self._clear_since = None
                    self._last_severity = ""
            else:
                self._state, self._since = OK, None
                self._clear_since = None
                self._last_severity = ""
            shown = severity or (
                self._last_severity if self._state == FIRING else ""
            )
            return Alert(
                slo=self.name, state=self._state, target=self.target,
                severity=shown if self._state != OK else "",
                since=self._since,
                age_s=None if self._since is None else max(0.0, now - self._since),
                burn_fast=burn_fast,
                burn_slow=burn_slow, description=self.description,
            )


# -- the serving fleet's standard objectives --------------------------------


def availability_source(snapshot: FleetSnapshot) -> tuple[float, float]:
    """good = responses that were not 5xx (client errors are the
    client's problem, not an availability burn)."""
    total = snapshot.value_sum("tpu_serve_requests_total")
    bad = snapshot.value_sum(
        "tpu_serve_requests_total",
        where=lambda labels: labels.get("code", "").startswith("5"),
    )
    return total - bad, total


def threshold_source(histogram: str, threshold_s: float,
                     ) -> Callable[[FleetSnapshot], tuple[float, float]]:
    """good = observations at or under ``threshold_s``, read from the
    cumulative bucket whose bound is the smallest ``le ≥ threshold``
    (the p99-style latency SLO: X% of requests under Y seconds)."""

    def source(snapshot: FleetSnapshot) -> tuple[float, float]:
        buckets = snapshot.histogram_buckets(histogram)
        total = snapshot.histogram_count(histogram)
        good = total
        for le, count in buckets:  # sorted ascending
            if le >= threshold_s:
                good = count
                break
        return good, total

    return source


def default_slos(availability_target: float = 0.999,
                 latency_threshold_s: float = 1.0,
                 latency_target: float = 0.99,
                 ttft_threshold_s: float = 2.5,
                 ttft_target: float = 0.95,
                 for_s: float = 60.0,
                 store: TSDB | None = None,
                 resolve_for_s: float = 60.0) -> list[SLOTracker]:
    """The serving fleet's standard objectives — what the ``monitor``
    CLI evaluates unless handed something else. ``store`` shares one
    history store across the objectives (and with the fleet scrape
    series when the monitor passes its own)."""
    return [
        SLOTracker(
            "availability", availability_target, availability_source,
            for_s=for_s, store=store, resolve_for_s=resolve_for_s,
            description="non-5xx responses / all responses",
        ),
        SLOTracker(
            "latency", latency_target,
            threshold_source("tpu_serve_request_seconds",
                             latency_threshold_s),
            for_s=for_s, store=store, resolve_for_s=resolve_for_s,
            description=(
                f"requests served within {latency_threshold_s:g}s"
            ),
        ),
        SLOTracker(
            "ttft", ttft_target,
            threshold_source("tpu_serve_time_to_first_token_seconds",
                             ttft_threshold_s),
            for_s=for_s, store=store, resolve_for_s=resolve_for_s,
            description=(
                f"streams first token within {ttft_threshold_s:g}s"
            ),
        ),
    ]
