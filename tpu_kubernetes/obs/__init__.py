"""Unified observability: metrics registry + structured run events.

One subsystem, two channels (SURVEY §5.1/§5.5 — the reference has
neither):

* :mod:`tpu_kubernetes.obs.metrics` — Prometheus-style ``Counter`` /
  ``Gauge`` / ``Histogram`` families in a process-wide :data:`REGISTRY`,
  scrape-ready via text exposition (``GET /metrics`` on the serve
  server, ``tpu-k8s get metrics`` everywhere else).
* :mod:`tpu_kubernetes.obs.events` — JSONL structured events with
  run/correlation ids and nested parent spans (``TPU_K8S_EVENTS=<path>``
  to enable), which util/trace.py phases feed.

Fleet-scope layers on top (imported lazily — none are needed for the
single-process path):

* :mod:`tpu_kubernetes.obs.expfmt` — parser/renderer for the text
  exposition REGISTRY emits, round-trip safe.
* :mod:`tpu_kubernetes.obs.aggregate` — concurrent multi-target
  ``/metrics`` scraper merging workers into one fleet snapshot with
  ``instance`` labels and per-target ``up`` health.
* :mod:`tpu_kubernetes.obs.tsdb` — bounded in-memory time-series store
  (raw → 10s → 1m downsample tiers under a hard memory cap) with
  reset-aware ``rate/avg/max/quantile_over_time`` — the retained
  history every fleet scrape feeds and the SLO burn windows read.
* :mod:`tpu_kubernetes.obs.slo` — sliding-window SLOs with
  multi-window burn-rate alerting, windows read from the tsdb store.
* :mod:`tpu_kubernetes.obs.monitor` — the ``tpu-kubernetes monitor``
  fleet table / JSON renderer with sparkline trend columns, plus the
  ``get history`` renderer.
* :mod:`tpu_kubernetes.obs.flightrec` — the serve engine's flight
  recorder: per-segment snapshot ring + redacted JSON postmortems
  dumped atomically on restart/hard-fail/drain (``GET
  /debug/flightrec``, ``get flightrec``).

Performance attribution (also lazy — profile needs no jax at import,
perfbench imports jax only when benches run):

* :mod:`tpu_kubernetes.obs.profile` — device-synced phase profiler
  separating compile (a program's first call: jit trace + XLA compile)
  from steady-state execute time, with HBM watermarks where the backend
  reports them; feeds ``GET /debug/profile`` and the span tree.
* :mod:`tpu_kubernetes.obs.perfbench` — deterministic CPU-runnable
  microbench registry with JSONL history under ``benchmarks/history/``
  and a rolling-baseline regression gate (``bench run --check``).
"""

from tpu_kubernetes.obs.metrics import (  # noqa: F401
    CONTENT_TYPE,
    DEFAULT_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricError,
    Registry,
)
from tpu_kubernetes.obs import events  # noqa: F401
