"""In-tree microbenchmark registry + perf history + regression detection.

``bench.py`` at the repo root is the *driver's* benchmark — it runs on
real accelerators and its numbers live outside the tree. This module is
the opposite contract: small, deterministic, CPU-runnable microbenches
over the hot paths (ops/ kernels' reference lanes, serve prefill +
decode-step, the train step), whose median-of-N results append to a
JSONL history under ``benchmarks/history/`` so every future perf PR is
self-verifying: ``bench run --check`` compares the newest run against a
rolling baseline and exits nonzero on regression.

Methodology (why these choices):

* **warmup then median**: jax's first call pays trace+compile; warmup
  iterations absorb it, and the median of the timed iterations is
  robust to a single GC pause or scheduler hiccup where a mean is not.
* **device-synced**: every iteration blocks on the thunk's output —
  async dispatch otherwise makes the numbers dispatch time.
* **rolling baseline**: the per-metric median of the last K history
  entries, so the baseline tracks the machine instead of a single
  (possibly lucky) run.
* **noise threshold**: a regression is ``current > threshold ×
  baseline`` AND above an absolute floor — microsecond-scale metrics
  jitter by ratios that mean nothing.

``PERFBENCH_SLOWDOWN=name:factor`` multiplies a measured median — the
self-test knob proving the detector trips (a synthetic 2× slowdown must
make ``bench run --check`` exit nonzero).
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

# serve.sharded_continuous_decode runs on a 2-device host mesh; the
# device-count flag is only consulted at jax's first backend init, so
# claim it here when this module loads before jax (the `bench run` CLI
# path — `make perf-check` also exports it so the sitecustomize-imports-
# jax-first case is covered). When jax is already up (pytest under
# tests/conftest.py's forced-8 pool) the environment is left alone.
if "jax" not in sys.modules and (
        "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()

SUITES = ("ops", "serve", "train")
DEFAULT_HISTORY_DIR = "benchmarks/history"
DEFAULT_THRESHOLD = 1.5
DEFAULT_WINDOW = 5
# metrics where BOTH sides sit under this floor never regress: at that
# scale the ratio is pure timer/scheduler noise
MIN_SECONDS = 1e-4
EXIT_REGRESSION = 3


# --------------------------------------------------------------------------
# registry

@dataclass(frozen=True)
class Bench:
    name: str
    suite: str
    # factory builds inputs + jitted program and returns a zero-arg
    # thunk for ONE iteration; the runner device-syncs its return value
    make: Callable[[], Callable[[], Any]]


BENCHES: dict[str, Bench] = {}


def register(name: str, suite: str):
    if suite not in SUITES:
        raise ValueError(f"unknown suite {suite!r} (expected one of {SUITES})")

    def deco(make: Callable[[], Callable[[], Any]]):
        if name in BENCHES:
            raise ValueError(f"duplicate bench {name!r}")
        BENCHES[name] = Bench(name=name, suite=suite, make=make)
        return make
    return deco


def benches_for(suite: str, only: str | None = None) -> list[Bench]:
    picked = [
        b for b in BENCHES.values()
        if (suite == "all" or b.suite == suite)
        and (only is None or only in b.name)
    ]
    return sorted(picked, key=lambda b: (b.suite, b.name))


# --------------------------------------------------------------------------
# bench definitions (jax imported lazily — registry must import anywhere)

_TEST_MODEL = "llama-test"


@register("ops.rms_norm", "ops")
def _bench_rms_norm():
    import jax
    import jax.numpy as jnp

    from tpu_kubernetes.ops import rms_norm

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (64, 512), jnp.float32)
    w = jnp.ones((512,), jnp.float32)
    fn = jax.jit(rms_norm)
    return lambda: fn(x, w)


@register("ops.flash_attention", "ops")
def _bench_flash_attention():
    import jax
    import jax.numpy as jnp

    from tpu_kubernetes.ops import flash_attention

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    shape = (1, 4, 128, 64)  # (batch, heads, seq, head_dim)
    q = jax.random.normal(k1, shape, jnp.float32)
    k = jax.random.normal(k2, shape, jnp.float32)
    v = jax.random.normal(k3, shape, jnp.float32)
    # reference lane: the XLA path every backend has — what CPU CI can
    # hold steady; the Pallas lane is bench.py's (driver) job
    fn = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, use_pallas=False))
    return lambda: fn(q, k, v)


@register("ops.grouped_matmul", "ops")
def _bench_grouped_matmul():
    import jax
    import jax.numpy as jnp

    from tpu_kubernetes.ops import grouped_matmul

    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    lhs = jax.random.normal(k1, (256, 128), jnp.float32)
    rhs = jax.random.normal(k2, (4, 128, 128), jnp.float32)
    group_sizes = jnp.array([64, 64, 64, 64], jnp.int32)
    fn = jax.jit(lambda l, r, g: grouped_matmul(l, r, g, use_pallas=False))
    return lambda: fn(lhs, rhs, group_sizes)


@register("serve.prefill", "serve")
def _bench_serve_prefill():
    import jax
    import jax.numpy as jnp

    from tpu_kubernetes.models import CONFIGS, init_params
    from tpu_kubernetes.models.decode import prefill

    cfg = CONFIGS[_TEST_MODEL]
    params = init_params(jax.random.PRNGKey(3), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (2, 16), 0, cfg.vocab_size, jnp.int32)
    fn = jax.jit(lambda p, t: prefill(p, t, cfg, max_seq=64)[0])
    return lambda: fn(params, tokens)


@register("serve.decode_step", "serve")
def _bench_serve_decode_step():
    import jax
    import jax.numpy as jnp

    from tpu_kubernetes.models import CONFIGS, init_params
    from tpu_kubernetes.models.decode import decode_step, prefill

    cfg = CONFIGS[_TEST_MODEL]
    params = init_params(jax.random.PRNGKey(3), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (2, 16), 0, cfg.vocab_size, jnp.int32)
    _, cache = prefill(params, tokens, cfg, max_seq=64)
    tok = jnp.array([1, 2], jnp.int32)
    fn = jax.jit(lambda p, c, t: decode_step(p, c, t, cfg)[0])
    return lambda: fn(params, cache, tok)


@register("serve.prefill_warm", "serve")
def _bench_serve_prefill_warm():
    """Warm-prefix prefill at a 75% shared-prefix ratio: 96 of 128
    prompt tokens come from a cached segment, so the thunk forwards
    only the 32-token suffix (prefill_resume). The ISSUE-4 acceptance
    test compares this against a cold 128-token prefill and asserts
    the >= 2x win."""
    import jax
    import jax.numpy as jnp

    from tpu_kubernetes.models import CONFIGS, init_params
    from tpu_kubernetes.models.decode import prefill, prefill_resume

    cfg = CONFIGS[_TEST_MODEL]
    params = init_params(jax.random.PRNGKey(3), cfg)
    span = cfg.max_seq                      # 128-token prompt fills it
    prompt = jax.random.randint(
        jax.random.PRNGKey(6), (1, span), 0, cfg.vocab_size, jnp.int32)
    shared = (span * 3) // 4                # 75% shared prefix
    _, base = prefill(params, prompt[:, :shared], cfg, max_seq=span)
    suffix = prompt[:, shared:]
    fn = jax.jit(lambda p, t, c: prefill_resume(p, t, cfg, c)[0])
    return lambda: fn(params, suffix, base)


def _early_exit_case(budget: int):
    """Factory behind serve.decode_early_exit, parameterized by the
    per-row budget so the scaling test can build the run-to-max case
    (budget = the full bucketed run) with the same machinery: batch 4,
    run_max_new 64, jitted 8-step decode_segment programs with the
    host-side liveness check between segments — the server's segmented
    loop in miniature."""
    def make():
        import functools

        import jax
        import jax.numpy as jnp

        from tpu_kubernetes.models import CONFIGS, init_params
        from tpu_kubernetes.models.decode import decode_segment, prefill

        cfg = CONFIGS[_TEST_MODEL]
        params = init_params(jax.random.PRNGKey(3), cfg)
        b, width, run_max = 4, 16, 64
        span = width + run_max
        tokens = jax.random.randint(
            jax.random.PRNGKey(7), (b, width), 0, cfg.vocab_size,
            jnp.int32)
        logits, cache = prefill(params, tokens, cfg, max_seq=span)
        first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        done0 = jnp.zeros((b,), bool)
        k_steps = 8
        total = run_max - 1
        programs: dict[int, Any] = {}

        def segment(steps):
            if steps not in programs:
                programs[steps] = jax.jit(functools.partial(
                    decode_segment, cfg=cfg, steps=steps))
            return programs[steps]

        def thunk():
            tok, done, c = first, done0, cache
            emitted = 1
            run = 0
            while run < total and emitted < budget:
                steps = min(k_steps, total - run)
                _, tok, done, c = segment(steps)(params, c, tok, done)
                emitted += steps
                run += steps
            return tok

        return thunk
    return make


# all four rows want only 8 tokens: wall time should track the longest
# LIVE row (~8 steps), not the bucketed max (63) — the scaling test
# rebuilds this with budget=64 and asserts the gap
register("serve.decode_early_exit", "serve")(_early_exit_case(8))


def _continuous_case(continuous: bool):
    """Factory behind serve.continuous_decode, parameterized so the
    acceptance test can build BOTH schedulers over the same staggered
    trace and assert the tokens/sec ratio. The trace: 4 slots, 12
    requests in waves of one long (budget 48) + three short (budget 4)
    — the round-based dispatcher (FIFO rounds of 4, early-exit
    segments, the server's _decode_masked in miniature) holds every
    short row's slot hostage until its wave's long row drains (~47
    steps/round x 3 rounds), while the continuous engine recycles the
    short rows' slots for the next wave between 4-step segments (~56
    steps total for the same 180 tokens). Prefill is setup, not
    measured — the benched quantity is pure decode scheduling (segments
    + slot inserts + the per-segment liveness readback the scheduler
    pays, which the round path's async pipeline does not)."""
    def make():
        import functools

        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models import CONFIGS, init_params
        from tpu_kubernetes.models.decode import (
            SlotState,
            cache_insert_row,
            decode_segment,
            decode_segment_slots,
            init_cache,
            prefill,
        )

        cfg = CONFIGS[_TEST_MODEL]
        params = init_params(jax.random.PRNGKey(3), cfg)
        slots, width, span, k_steps = 4, 16, 64, 4
        budgets = [48, 4, 4, 4] * 3                  # 12 requests, FIFO
        n_req = len(budgets)
        prompts = jax.random.randint(
            jax.random.PRNGKey(8), (n_req, width), 0, cfg.vocab_size,
            jnp.int32)
        lengths = jnp.full((1,), width, jnp.int32)

        if not continuous:
            # round-based reference: 3 prefilled batch-4 round caches
            rounds = []
            for r in range(0, n_req, slots):
                logits, cache = prefill(
                    params, prompts[r:r + slots], cfg, max_seq=span,
                    lengths=jnp.full((slots,), width, jnp.int32))
                first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                rounds.append((cache, first))
            done0 = jnp.zeros((slots,), bool)
            seg = {}

            def segment(steps):
                if steps not in seg:
                    seg[steps] = jax.jit(functools.partial(
                        decode_segment, cfg=cfg, steps=steps))
                return seg[steps]

            def thunk():
                out = None
                for ri, (cache, first) in enumerate(rounds):
                    buds = budgets[ri * slots:(ri + 1) * slots]
                    total = max(buds) - 1
                    tok, done, c = first, done0, cache
                    emitted, run = 1, 0
                    while run < total:
                        if not any(b > emitted for b in buds):
                            break
                        steps = min(k_steps, total - run)
                        _, tok, done, c = segment(steps)(
                            params, c, tok, done)
                        emitted += steps
                        run += steps
                    out = tok
                return out
            return thunk

        # continuous engine in miniature: per-request row caches +
        # firsts (setup), one shared slot cache; the thunk replays
        # admission (jitted inserts + device-side state pokes) +
        # mixed-position segments, slots recycling the moment a row's
        # budget drains. The state stays device-resident — the
        # scheduler reads back ONE array per segment (remaining, its
        # liveness authority), the only sync the loop needs.
        rows, firsts = [], []
        for r in range(n_req):
            logits, rc = prefill(
                params, prompts[r:r + 1], cfg, max_seq=width,
                lengths=lengths)
            rows.append(rc)
            firsts.append(int(np.argmax(np.asarray(logits)[0])))
        cache0 = init_cache(cfg, slots, span)
        w = jnp.full((slots,), width, jnp.int32)
        st0 = SlotState(
            tok=jnp.zeros((slots,), jnp.int32), pos=w,
            remaining=jnp.zeros((slots,), jnp.int32),
            prompt_lengths=w, prompt_slots=w)
        ins = jax.jit(cache_insert_row)
        seg4 = jax.jit(functools.partial(
            decode_segment_slots, cfg=cfg, steps=k_steps))

        @jax.jit
        def admit(st, s, first, budget):
            return st._replace(
                tok=st.tok.at[s].set(first),
                pos=st.pos.at[s].set(width),
                remaining=st.remaining.at[s].set(budget - 1))

        def thunk():
            queue = list(range(n_req))
            occupied: list[int | None] = [None] * slots
            st, cache = st0, cache0
            while queue or any(o is not None for o in occupied):
                for s in range(slots):
                    if occupied[s] is None and queue:
                        r = queue.pop(0)
                        cache = ins(cache, rows[r], s)
                        st = admit(st, s, firsts[r], budgets[r])
                        occupied[s] = r
                _, st, cache = seg4(params, cache, st)
                rem = np.asarray(st.remaining)
                for s in range(slots):
                    if occupied[s] is not None and rem[s] <= 0:
                        occupied[s] = None
            return cache.k
        return thunk
    return make


# the registered metric is the continuous engine's wall time over the
# staggered trace; the acceptance test rebuilds the round-based twin
# via the factory and asserts continuous is >= 1.5x tokens/sec
register("serve.continuous_decode", "serve")(_continuous_case(True))


def _sharded_continuous_case():
    """Factory behind serve.sharded_continuous_decode: the continuous
    engine's staggered trace (same 12-request, budgets-48/4/4/4 waves
    as serve.continuous_decode) run on a 2-device ``tensor`` host mesh
    through the SAME program builders the serve engine uses
    (parallel/serving.py): kv_shard_map for the jitted slot inserts —
    bitwise data movement over KV sharded on the kv-heads axis — and
    kv_jit for the mixed-position decode segments, with SlotState,
    params, and the scheduler's one-array liveness readback replicated.
    Arrivals stay staggered (slots recycle mid-trace), so the benched
    quantity is the sharded scheduling path end to end: segment
    collectives + insert resharding on top of the dense case's loop.
    The acceptance test bounds the ratio against the dense twin."""
    def make():
        import functools

        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models import CONFIGS, init_params
        from tpu_kubernetes.models.decode import (
            SlotState,
            cache_insert_row,
            decode_segment_slots,
            init_cache,
            prefill,
        )
        from tpu_kubernetes.parallel import create_mesh
        from tpu_kubernetes.parallel.serving import (
            kv_jit,
            kv_shard_map,
            kv_tree_shardings,
        )

        devs = jax.devices()
        if len(devs) < 2:
            raise RuntimeError(
                "serve.sharded_continuous_decode needs >= 2 devices; set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=2 before "
                "jax imports (make perf-check and tests/conftest.py do)")
        mesh = create_mesh({"tensor": 2}, devices=devs[:2])

        cfg = CONFIGS[_TEST_MODEL]
        params = init_params(jax.random.PRNGKey(3), cfg)
        slots, width, span, k_steps = 4, 16, 64, 4
        budgets = [48, 4, 4, 4] * 3                  # 12 requests, FIFO
        n_req = len(budgets)
        prompts = jax.random.randint(
            jax.random.PRNGKey(8), (n_req, width), 0, cfg.vocab_size,
            jnp.int32)
        lengths = jnp.full((1,), width, jnp.int32)

        # setup (unmeasured): per-request row caches + first tokens,
        # then the shared slot cache placed under the kv shardings
        rows, firsts = [], []
        for r in range(n_req):
            logits, rc = prefill(
                params, prompts[r:r + 1], cfg, max_seq=width,
                lengths=lengths)
            rows.append(rc)
            firsts.append(int(np.argmax(np.asarray(logits)[0])))
        cache0 = jax.device_put(
            init_cache(cfg, slots, span),
            kv_tree_shardings(init_cache(cfg, slots, span), mesh))
        w = jnp.full((slots,), width, jnp.int32)
        st0 = SlotState(
            tok=jnp.zeros((slots,), jnp.int32), pos=w,
            remaining=jnp.zeros((slots,), jnp.int32),
            prompt_lengths=w, prompt_slots=w)
        ins = kv_shard_map(cache_insert_row, mesh, (cache0, rows[0], 0))
        seg4 = kv_jit(
            functools.partial(decode_segment_slots, cfg=cfg,
                              steps=k_steps),
            mesh, (params, cache0, st0))

        def _admit(st, s, first, budget):
            return st._replace(
                tok=st.tok.at[s].set(first),
                pos=st.pos.at[s].set(width),
                remaining=st.remaining.at[s].set(budget - 1))

        admit = kv_jit(_admit, mesh, (st0, 0, firsts[0], budgets[0]))

        def thunk():
            queue = list(range(n_req))
            occupied: list[int | None] = [None] * slots
            st, cache = st0, cache0
            while queue or any(o is not None for o in occupied):
                for s in range(slots):
                    if occupied[s] is None and queue:
                        r = queue.pop(0)
                        cache = ins(cache, rows[r], s)
                        st = admit(st, s, firsts[r], budgets[r])
                        occupied[s] = r
                _, st, cache = seg4(params, cache, st)
                rem = np.asarray(st.remaining)
                for s in range(slots):
                    if occupied[s] is not None and rem[s] <= 0:
                        occupied[s] = None
            return cache.k
        return thunk
    return make


# the registered metric is the sharded engine's wall time over the same
# staggered trace as serve.continuous_decode; the acceptance test
# (slow-marked, `make sharded-check`) bounds sharded/dense wall time
register("serve.sharded_continuous_decode", "serve")(
    _sharded_continuous_case())


def _paged_case():
    """Factory behind serve.paged_decode: the paged slot engine in
    miniature at FOUR TIMES serve.continuous_decode's slot count inside
    the SAME KV byte budget. The dense engine spends
    slots x max_seq worst-case bytes per row whether or not the row
    ever grows that long; the paged pool spends bytes on LIVE tokens
    only, so the identical 64 KiB that backs 4 dense slots (4 x 64
    positions) backs a 32-page pool (page_size 8 → 256 positions) that
    16 short-lived rows occupy concurrently. The trace: 32 staggered
    requests (width 8, budgets 8/4/4/4 waves — every row fits 2 pages:
    prompt page + decode page), admission gated on FREE PAGES, a
    host-side table feeding decode_segment_paged, pages released +
    wiped as rows drain. Returns per-request token lists plus the peak
    resident-slot count so the acceptance test asserts the 4x
    concurrency AND token identity against the dense engine's trace."""
    def make():
        import functools

        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models import CONFIGS, init_params
        from tpu_kubernetes.models.decode import (
            SlotState,
            decode_segment_paged,
            init_paged_pool,
            paged_clear_pages,
            paged_insert_row,
            prefill,
        )

        cfg = CONFIGS[_TEST_MODEL]
        params = init_params(jax.random.PRNGKey(3), cfg)
        # 4x the dense case's 4 slots; virtual span stays 64 (table of
        # 8 pages x page_size 8) so attention shapes — and tokens —
        # match the dense engine exactly
        slots, width, span, k_steps, ps = 16, 8, 64, 4, 8
        num_pages = 32           # 32 x page_bytes(cfg, 8) == the dense
        max_pages = span // ps   # case's 4 x 64-position cache bytes
        budgets = [8, 4, 4, 4] * 8                   # 32 requests, FIFO
        n_req = len(budgets)
        # every row needs exactly 2 pages for its whole life (prompt
        # positions 0..7, decode writes 8..14), so 16 rows x 2 == the
        # pool — full 4x occupancy is reachable and sustained
        row_need = 2
        prompts = jax.random.randint(
            jax.random.PRNGKey(8), (n_req, width), 0, cfg.vocab_size,
            jnp.int32)
        lengths = jnp.full((1,), width, jnp.int32)

        rows, firsts = [], []
        for r in range(n_req):
            logits, rc = prefill(
                params, prompts[r:r + 1], cfg, max_seq=width,
                lengths=lengths)
            rows.append(rc)
            firsts.append(int(np.argmax(np.asarray(logits)[0])))
        pool0 = init_paged_pool(cfg, num_pages, ps)
        w = jnp.full((slots,), width, jnp.int32)
        st0 = SlotState(
            tok=jnp.zeros((slots,), jnp.int32), pos=w,
            remaining=jnp.zeros((slots,), jnp.int32),
            prompt_lengths=w, prompt_slots=w)
        ins = jax.jit(paged_insert_row)
        segp = jax.jit(functools.partial(
            decode_segment_paged, cfg=cfg, steps=k_steps))
        clr = jax.jit(paged_clear_pages)

        @jax.jit
        def admit(st, s, first, budget):
            return st._replace(
                tok=st.tok.at[s].set(first),
                pos=st.pos.at[s].set(width),
                remaining=st.remaining.at[s].set(budget - 1))

        def thunk():
            from tpu_kubernetes.serve.pages import PagePool

            pages = PagePool(num_pages)
            queue = list(range(n_req))
            occupied: list[int | None] = [None] * slots
            held: list[list[int]] = [[] for _ in range(slots)]
            table = np.zeros((slots, max_pages), np.int32)
            collected: list[list[int]] = [[] for _ in range(n_req)]
            st, pool = st0, pool0
            peak = 0
            while queue or any(o is not None for o in occupied):
                for s in range(slots):
                    if occupied[s] is None and queue \
                            and pages.free_count() >= row_need:
                        r = queue.pop(0)
                        got = pages.allocate(row_need)
                        held[s] = got
                        table[s, :row_need] = got
                        pool = ins(pool, rows[r],
                                   jnp.asarray(got[:1], jnp.int32))
                        st = admit(st, s, firsts[r], budgets[r])
                        occupied[s] = r
                        collected[r].append(firsts[r])
                peak = max(peak, sum(o is not None for o in occupied))
                old_pos = np.asarray(st.pos)
                toks, st, pool = segp(params, pool,
                                      jnp.asarray(table), st)
                toks = np.asarray(toks)
                new_pos = np.asarray(st.pos)
                rem = np.asarray(st.remaining)
                freed: list[int] = []
                for s in range(slots):
                    if occupied[s] is None:
                        continue
                    emitted = int(new_pos[s] - old_pos[s])
                    collected[occupied[s]].extend(
                        toks[s][:emitted].tolist())
                    if rem[s] <= 0:
                        table[s, :] = 0
                        freed += pages.release(held[s])
                        held[s] = []
                        occupied[s] = None
                # same discipline as the engine: freed pages come back
                # bitwise-cold through ONE padded program per chunk
                for o in range(0, len(freed), max_pages):
                    chunk = np.full(max_pages, num_pages + 1, np.int32)
                    part = freed[o:o + max_pages]
                    chunk[:len(part)] = part
                    pool = clr(pool, jnp.asarray(chunk))
            jax.block_until_ready(pool.k)
            return collected, peak
        return thunk
    return make


# the registered metric is the paged engine's wall time over its 4x-
# concurrency trace; the acceptance test asserts byte parity with the
# dense case, the 4x peak occupancy, and per-request token identity
# against a dense slot engine run of the same trace
register("serve.paged_decode", "serve")(_paged_case())


def _speculative_case(speculative: bool):
    """Factory behind serve.speculative_continuous_decode, parameterized
    so the acceptance test can build BOTH decoders over the same trace
    and assert token identity plus the tokens-per-target-pass ratio.
    The trace: 2 slots with repetitive-suffix prompts (fixed, fp32,
    params from PRNGKey(6) — a seed whose greedy continuations revisit
    earlier n-grams), budget 56, host n-gram proposer (n=2, k=4)
    feeding ``decode_verify_slots``. The plain twin runs
    ``decode_segment_slots`` over the identical trace: one token per
    row per target pass, the quantity speculation amortizes. Counters,
    not wall-clock, carry the acceptance criterion — on CPU the k+1
    verify window costs nearly the same FLOPs as one decode step, so
    emitted-tokens-per-verify-round is the deterministic proxy for the
    speedup a real accelerator realizes; the measured workload sustains
    ~2.2 tokens per row per round against the 1.5 gate. Both thunks return
    ``(collected, rounds)``: per-row token lists (first token included,
    prefill-emitted in both variants) and the number of target passes
    the loop issued."""
    def make():
        import functools
        from dataclasses import replace

        import jax
        import jax.numpy as jnp
        import numpy as np

        from tpu_kubernetes.models import CONFIGS, init_params
        from tpu_kubernetes.models.decode import (
            SlotState,
            cache_insert_row,
            decode_segment_slots,
            decode_verify_slots,
            init_cache,
            prefill,
        )
        from tpu_kubernetes.models.speculative import ngram_propose_host

        cfg = replace(CONFIGS[_TEST_MODEL], dtype=jnp.float32)
        slots, width, budget, k, ngram = 2, 8, 56, 4, 2
        span = width + budget + k       # verify windows write pos..pos+k
        params = init_params(jax.random.PRNGKey(6), cfg)
        prompts = [[17] * 8, [100, 30] * 4]

        rows, firsts = [], []
        for ids in prompts:
            logits, rc = prefill(
                params, jnp.asarray([ids], jnp.int32), cfg,
                max_seq=width)
            rows.append(rc)
            firsts.append(int(np.argmax(np.asarray(logits)[0])))
        cache0 = init_cache(cfg, slots, span)
        for s, rc in enumerate(rows):
            cache0 = cache_insert_row(cache0, rc, s)
        w = jnp.full((slots,), width, jnp.int32)
        st0 = SlotState(
            tok=jnp.asarray(firsts, jnp.int32), pos=w,
            remaining=jnp.full((slots,), budget - 1, jnp.int32),
            prompt_lengths=w, prompt_slots=w)

        if not speculative:
            seg = jax.jit(functools.partial(
                decode_segment_slots, cfg=cfg, steps=4))

            def thunk():
                st, cache = st0, cache0
                collected = [[f] for f in firsts]
                pos_h = np.asarray(st.pos).copy()
                rounds = 0
                while int(np.asarray(st.remaining).sum()) > 0:
                    toks, st, cache = seg(params, cache, st)
                    toks = np.asarray(toks)
                    new_pos = np.asarray(st.pos)
                    for i in range(slots):
                        got = int(new_pos[i] - pos_h[i])
                        collected[i].extend(toks[i][:got].tolist())
                    pos_h = new_pos.copy()
                    rounds += 4          # 4 target passes per segment
                jax.block_until_ready(cache.k)
                return collected, rounds
            return thunk

        ver = jax.jit(functools.partial(
            decode_verify_slots, cfg=cfg, eos_id=None, pad_id=0))

        def thunk():
            st, cache = st0, cache0
            collected = [[f] for f in firsts]
            pos_h = np.asarray(st.pos).copy()
            rounds = 0
            while int(np.asarray(st.remaining).sum()) > 0:
                drafts = np.stack([
                    np.asarray(ngram_propose_host(
                        prompts[i] + collected[i], ngram, k,
                        collected[i][-1]), np.int32)
                    for i in range(slots)])
                toks, st, cache = ver(
                    params, cache, st, jnp.asarray(drafts))
                toks = np.asarray(toks)
                new_pos = np.asarray(st.pos)
                for i in range(slots):
                    got = int(new_pos[i] - pos_h[i])
                    collected[i].extend(toks[i][:got].tolist())
                pos_h = new_pos.copy()
                rounds += 1
            jax.block_until_ready(cache.k)
            return collected, rounds
        return thunk
    return make


# the registered metric is the speculative verify loop's wall time over
# the repetitive-suffix trace; the acceptance test (slow-marked,
# `make spec-check`) rebuilds the plain twin via the factory and
# asserts token identity plus >= 1.5 emitted tokens per verify round
register("serve.speculative_continuous_decode", "serve")(
    _speculative_case(True))


@register("train.step", "train")
def _bench_train_step():
    import functools

    import jax

    from tpu_kubernetes.models import CONFIGS
    from tpu_kubernetes.train.trainer import (
        TrainConfig,
        init_state,
        synthetic_batches,
        train_step,
    )

    cfg = CONFIGS[_TEST_MODEL]
    tc = TrainConfig(warmup_steps=2)
    state = init_state(jax.random.PRNGKey(5), cfg, tc)
    batch = next(synthetic_batches(cfg.vocab_size, 2, 32))
    # no donation: the same state feeds every iteration, so each run
    # performs the identical step (medians compare like with like)
    fn = jax.jit(functools.partial(train_step, cfg=cfg, tc=tc))
    return lambda: fn(state, batch)[1]


# --------------------------------------------------------------------------
# runner

@dataclass
class BenchResult:
    name: str
    suite: str
    median_seconds: float
    n: int
    warmup: int
    times: list[float] = field(default_factory=list)
    injected: float | None = None  # PERFBENCH_SLOWDOWN factor, if applied


def _slowdowns() -> dict[str, float]:
    raw = os.environ.get("PERFBENCH_SLOWDOWN", "").strip()
    out: dict[str, float] = {}
    if not raw:
        return out
    for part in raw.split(","):
        if ":" not in part:
            continue
        name, _, factor = part.partition(":")
        try:
            out[name.strip()] = float(factor)
        except ValueError:
            continue
    return out


def run_bench(b: Bench, n: int = 5, warmup: int = 2) -> BenchResult:
    import jax

    thunk = b.make()
    for _ in range(max(0, warmup)):
        jax.block_until_ready(thunk())
    times: list[float] = []
    for _ in range(max(1, n)):
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        times.append(time.perf_counter() - t0)
    median = sorted(times)[len(times) // 2]
    injected = None
    for name, factor in _slowdowns().items():
        if name == b.name or name in b.name:
            median *= factor
            injected = factor
            break
    return BenchResult(
        name=b.name, suite=b.suite, median_seconds=median,
        n=len(times), warmup=warmup, times=[round(t, 6) for t in times],
        injected=injected,
    )


def run_suite(suite: str, n: int = 5, warmup: int = 2,
              only: str | None = None,
              progress: Callable[[str], None] | None = None,
              ) -> dict[str, BenchResult]:
    results: dict[str, BenchResult] = {}
    for b in benches_for(suite, only=only):
        if progress:
            progress(f"bench {b.name} ...")
        results[b.name] = run_bench(b, n=n, warmup=warmup)
    return results


# --------------------------------------------------------------------------
# history

def history_path(history_dir: str | Path, suite: str) -> Path:
    return Path(history_dir) / f"{suite}.jsonl"


def load_history(path: str | Path) -> list[dict]:
    """History entries, oldest first. Missing file → empty; malformed
    lines are skipped (a truncated append must not break all checks)."""
    p = Path(path)
    if not p.exists():
        return []
    entries: list[dict] = []
    for line in p.read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            entry = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(entry, dict) and isinstance(entry.get("results"), dict):
            entries.append(entry)
    return entries


def make_entry(suite: str, results: dict[str, BenchResult],
               n: int) -> dict:
    import tpu_kubernetes
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    return {
        "ts": round(time.time(), 3),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "suite": suite,
        "version": tpu_kubernetes.__version__,
        "backend": backend,
        "n": n,
        "results": {
            name: round(r.median_seconds, 6) for name, r in results.items()
        },
    }


def append_history(path: str | Path, entry: dict) -> Path:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with p.open("a", encoding="utf-8") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")
    return p


def rolling_baseline(entries: list[dict],
                     window: int = DEFAULT_WINDOW) -> dict[str, float]:
    """Per-metric median over each metric's last ``window`` observations.

    Window applies per metric, not per entry: a metric added recently
    still gets a baseline from however many observations it has (one
    observation → that value IS the baseline; zero variance is fine —
    the median of identical values is that value)."""
    series: dict[str, list[float]] = {}
    for entry in entries:  # oldest → newest
        for name, value in entry.get("results", {}).items():
            try:
                series.setdefault(name, []).append(float(value))
            except (TypeError, ValueError):
                continue
    out: dict[str, float] = {}
    for name, values in series.items():
        tail = values[-max(1, window):]
        tail = sorted(tail)
        mid = len(tail) // 2
        out[name] = (tail[mid] if len(tail) % 2
                     else (tail[mid - 1] + tail[mid]) / 2.0)
    return out


# --------------------------------------------------------------------------
# regression detection

@dataclass(frozen=True)
class Check:
    name: str
    status: str  # "ok" | "regression" | "new" | "missing"
    current: float | None
    baseline: float | None
    ratio: float | None

    def as_dict(self) -> dict:
        return {
            "name": self.name, "status": self.status,
            "current_seconds": self.current, "baseline_seconds": self.baseline,
            "ratio": self.ratio,
        }


@dataclass
class Report:
    checks: list[Check]
    threshold: float

    @property
    def regressions(self) -> list[Check]:
        return [c for c in self.checks if c.status == "regression"]

    @property
    def ok(self) -> bool:
        return not self.regressions

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "threshold": self.threshold,
            "checks": [c.as_dict() for c in self.checks],
        }


def detect(current: dict[str, float], baseline: dict[str, float],
           threshold: float = DEFAULT_THRESHOLD,
           min_seconds: float = MIN_SECONDS) -> Report:
    """Compare a run against a baseline.

    * metric in both → ``ok`` or ``regression`` (ratio > threshold and
      at least one side above the noise floor);
    * metric only in the run → ``new`` (a first observation cannot
      regress — this is also the whole-history-empty case);
    * metric only in the baseline → ``missing`` (reported, not failing:
      benches get renamed/retired and a perf gate must not fossilize
      the metric set — ``run(require_baseline=True)`` opts the CI gate
      into failing on it).
    """
    checks: list[Check] = []
    for name in sorted(set(current) | set(baseline)):
        cur = current.get(name)
        base = baseline.get(name)
        if base is None:
            checks.append(Check(name, "new", cur, None, None))
            continue
        if cur is None:
            checks.append(Check(name, "missing", None, base, None))
            continue
        ratio = (cur / base) if base > 0 else float("inf")
        noise_floor = max(cur, base) < min_seconds
        status = ("regression"
                  if ratio > threshold and not noise_floor else "ok")
        checks.append(Check(name, status, cur, base, round(ratio, 3)))
    return Report(checks=checks, threshold=threshold)


# --------------------------------------------------------------------------
# CLI entry (wired as `tpu-kubernetes bench run`)

def _fmt_s(v: float | None) -> str:
    return f"{v * 1e3:9.3f}ms" if v is not None else "        — "


def _goodput_info(results: dict[str, BenchResult]) -> dict[str, Any] | None:
    """Report-only goodput/MFU attribution for serve runs: FLOPs and
    bytes per token from compiled-program ``cost_analysis`` over the
    same prefill/decode-step programs the serve benches time, MFU =
    flops / (measured median × backend peak) — ``None`` on CPU while
    FLOPs/token stays exact — plus the in-process token ledger's
    goodput counters (obs/ledger.py). Deliberately NEVER part of
    ``results``/history: these are attributions, not timings, so
    baselines and ``--require-baseline`` are unaffected."""
    serve = {n: r for n, r in results.items() if r.suite == "serve"}
    if not serve:
        return None
    try:
        import jax
        import jax.numpy as jnp

        from tpu_kubernetes.models import CONFIGS, init_params
        from tpu_kubernetes.models.decode import decode_step, prefill
        from tpu_kubernetes.obs.ledger import LEDGER
        from tpu_kubernetes.obs.profile import (
            backend_peak_flops,
            device_kind,
            program_cost,
        )

        cfg = CONFIGS[_TEST_MODEL]
        params = init_params(jax.random.PRNGKey(3), cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(4), (2, 16), 0, cfg.vocab_size, jnp.int32)
        pf = jax.jit(lambda p, t: prefill(p, t, cfg, max_seq=64)[0])
        _, cache = prefill(params, tokens, cfg, max_seq=64)
        tok = jnp.array([1, 2], jnp.int32)
        ds = jax.jit(lambda p, c, t: decode_step(p, c, t, cfg)[0])
        costs = {
            "serve.prefill": (program_cost(pf, params, tokens),
                              int(tokens.size)),
            "serve.decode_step": (program_cost(ds, params, cache, tok),
                                  int(tok.size)),
        }
        snap = LEDGER.snapshot(timeline=0)
    except Exception:  # noqa: BLE001 — report-only: never fail the run
        return None
    kind = device_kind()
    peak = backend_peak_flops(kind)
    programs: dict[str, Any] = {}
    for name, (cost, n_tok) in costs.items():
        if cost is None or not n_tok:
            continue
        flops, nbytes = cost.get("flops"), cost.get("bytes")
        median = serve[name].median_seconds if name in serve else None
        programs[name] = {
            "flops_per_token": round(flops / n_tok, 3) if flops else None,
            "bytes_per_token": round(nbytes / n_tok, 3) if nbytes else None,
            "arithmetic_intensity": (round(flops / nbytes, 3)
                                     if flops and nbytes else None),
            "mfu": (round(flops / (median * peak), 6)
                    if flops and median and peak else None),
        }
    if not programs:
        return None
    return {
        "device_kind": kind,
        "peak_flops": peak,
        "programs": programs,
        "ledger": {
            "emitted": snap["emitted"],
            "goodput": snap["goodput"],
            "bubble_fraction": snap["slot_engine"]["bubble_fraction"],
        },
    }


def run(suite: str = "all", *, check: bool = False, as_json: bool = False,
        history_dir: str = DEFAULT_HISTORY_DIR, baseline: str | None = None,
        threshold: float = DEFAULT_THRESHOLD, n: int = 5, warmup: int = 2,
        only: str | None = None, window: int = DEFAULT_WINDOW,
        require_baseline: bool = False, out=None) -> int:
    """Run a suite (or all), append history, optionally gate on
    regressions. Returns the process exit code (0 ok, 3 regression).

    ``require_baseline`` (with ``check``) also fails the gate when a
    BASELINED metric is absent from the run — by default missing
    metrics only report (benches get renamed/retired), but the CI gate
    (``make perf-check``) runs full suites against the committed
    baseline, where a hole means a silently-deleted bench. Scoped to
    the suites actually run (a ``--suite serve`` run is not failed for
    the train metric it never attempted); don't combine with ``only``,
    which makes every unselected bench a hole."""
    out = out if out is not None else sys.stdout
    suites = list(SUITES) if suite == "all" else [suite]

    picked = {s: benches_for(s, only=only) for s in suites}
    picked = {s: bs for s, bs in picked.items() if bs}
    if not picked:
        print(f"no benches match suite={suite!r} only={only!r}",
              file=sys.stderr)
        return 2

    all_results: dict[str, BenchResult] = {}
    reports: list[Report] = []
    payload: dict[str, Any] = {"suites": {}, "threshold": threshold}

    # an explicit --baseline file (the committed cross-machine baseline)
    # replaces the per-suite local history as the comparison point
    shared_baseline = (rolling_baseline(load_history(baseline), window)
                      if baseline else None)

    for s, bs in sorted(picked.items()):
        results: dict[str, BenchResult] = {}
        for b in bs:
            results[b.name] = run_bench(b, n=n, warmup=warmup)
        all_results.update(results)

        hpath = history_path(history_dir, s)
        base = (shared_baseline if shared_baseline is not None
                else rolling_baseline(load_history(hpath), window))
        current = {name: r.median_seconds for name, r in results.items()}
        if shared_baseline is not None:
            # scope the shared baseline to this suite's metrics so the
            # other suites' metrics don't show up as "missing" here;
            # under require_baseline keep this suite's baselined names
            # (by their "<suite>." prefix) even when absent from the
            # run — those ARE the holes the strict gate must see
            base = {k: v for k, v in base.items()
                    if k in current
                    or (require_baseline
                        and k.split(".", 1)[0] == s)}
        report = detect(current, base, threshold=threshold) if check else None

        entry = make_entry(s, results, n)
        append_history(hpath, entry)

        payload["suites"][s] = {
            "results": entry["results"],
            "history": str(hpath),
            **({"check": report.as_dict()} if report else {}),
        }
        if report:
            reports.append(report)

    goodput = _goodput_info(all_results)
    if goodput is not None:
        payload["goodput"] = goodput

    missing = [c for r in reports for c in r.checks
               if c.status == "missing"]
    rc = 0
    if check and any(not r.ok for r in reports):
        rc = EXIT_REGRESSION
    if check and require_baseline and missing:
        rc = EXIT_REGRESSION

    if as_json:
        payload["ok"] = rc == 0
        print(json.dumps(payload, sort_keys=True), file=out)
        return rc

    checks_by_name = {
        c.name: c for r in reports for c in r.checks
    }
    for name, r in sorted(all_results.items()):
        line = f"{name:<24} {_fmt_s(r.median_seconds)}  (median of {r.n})"
        if r.injected:
            line += f"  [injected x{r.injected:g}]"
        c = checks_by_name.get(name)
        if c and c.baseline is not None:
            line += f"  baseline {_fmt_s(c.baseline).strip()} x{c.ratio:g} {c.status}"
        elif c:
            line += f"  {c.status}"
        print(line, file=out)
    if goodput is not None:
        peak = goodput["peak_flops"]
        print(f"goodput/MFU (report-only)   device "
              f"{goodput['device_kind'] or 'unknown'}  peak-flops "
              f"{'null' if peak is None else f'{peak:.3g}'}", file=out)
        for name, prog in sorted(goodput["programs"].items()):
            ft, mfu = prog["flops_per_token"], prog["mfu"]
            print(f"  {name:<22} flops/tok "
                  f"{'—' if ft is None else f'{ft:.4g}'}"
                  f"  intensity {prog['arithmetic_intensity']}"
                  f"  mfu {'null' if mfu is None else mfu}", file=out)
        led = goodput["ledger"]
        gp, bf = led["goodput"], led["bubble_fraction"]
        print(f"  ledger: emitted {led['emitted']}"
              f"  goodput {'—' if gp is None else gp}"
              f"  bubble {'—' if bf is None else bf}", file=out)
    for c in checks_by_name.values():
        if c.status == "missing":
            print(f"{c.name:<24} missing from this run "
                  f"(baseline {_fmt_s(c.baseline).strip()})", file=out)
    if check:
        bad = [c for r in reports for c in r.regressions]
        for c in bad:
            print(
                f"REGRESSION: {c.name} x{c.ratio:g} over baseline "
                f"({_fmt_s(c.baseline).strip()} -> "
                f"{_fmt_s(c.current).strip()}, threshold "
                f"x{threshold:g})", file=out)
        if require_baseline:
            for c in missing:
                print(f"MISSING: {c.name} is baselined but absent from "
                      "this run (--require-baseline)", file=out)
        if rc == 0:
            print(f"perf check ok (threshold x{threshold:g})", file=out)
    return rc
