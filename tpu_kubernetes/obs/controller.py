"""The closed loop: an observability-driven fleet controller.

PRs 12–17 built the sensing half of the "self-driving fleet" ROADMAP
item — rule alerts, incident bundles, per-role saturation, the goodput
ledger, distributed tracing. This module is the acting half, with one
governing rule: **every decision is itself observed**. An action that
isn't recorded, metered, and traceable didn't happen.

Three cooperating pieces:

* :class:`FleetRouter` — placement. Routes a request to the least
  loaded *eligible* instance, scored by the aggregator's
  ``tpu_serve_saturation`` gauge plus per-cycle
  ``tpu_serve_kv_page_stalls_total`` deltas (page pressure), skipping
  draining/failed/down instances. Prefix-sticky: the same prompt prefix
  re-routes to the instance whose prefix KV cache is already warm, so
  warm-prefill wins multiply fleet-wide — stickiness yields only when
  the pinned instance saturates.
* :class:`FleetController` — remediation. Subscribes to the
  AlertManager stream (``manager.listeners``) and maps rule kinds to a
  **closed action vocabulary** (:data:`ACTION_KINDS`, statically
  checked by ``tpu-kubernetes analyze`` exactly like fault sites and
  alert kinds): ``queue_runaway``/``slo_burn`` → ``scale_up`` through
  the Terraform executor path; ``engine_restart`` loops →
  ``drain_replace``; sustained idle with a low
  ``tpu_serve_slot_bubble_fraction`` → ``scale_down`` via
  ``POST /drain`` so resident work never drops. **Goodput, not raw
  RPS, is the scaling signal**: decisions read saturation, queue
  pressure, and the ledger's useful-token share — request rate is
  never consulted — and a degraded goodput vetoes scale-down.
* :class:`ActionLedger` — the audit trail. Every action — proposed,
  executed, failed, or suppressed by dry-run/cooldown — is appended to
  a bounded ring and an optional JSONL sink, counted by
  ``tpu_fleet_actions_total{kind,outcome}``, written into the incident
  bundle that triggered it, and stamped with the triggering alert
  fingerprint and a trace id — an incident reads as detect → decide →
  actuate → resolve.

Safety is structural, not aspirational: actions default to dry-run
(``TPU_K8S_CONTROLLER_DRY_RUN``), a per-kind cooldown and a
max-actions-per-cycle cap bound the blast radius, each alert
fingerprint gets at most one actuation (no duplicate Terraform
invocations per alert), failures retry with bounded exponential
backoff, and the whole actuation path runs through the
``fleet.remediate`` fault site so the loop is chaos-testable on CPU.

Knobs (all through util/envparse.py, documented in
docs/guide/observability.md):

* ``TPU_K8S_CONTROLLER_DRY_RUN`` — record-don't-act (default on).
* ``TPU_K8S_CONTROLLER_COOLDOWN_S`` — per-action-kind hold-down.
* ``TPU_K8S_CONTROLLER_MAX_ACTIONS`` — actuations per cycle cap.
* ``TPU_K8S_CONTROLLER_MIN_REPLICAS`` / ``_MAX_REPLICAS`` — scale
  clamps.
* ``TPU_K8S_ACTIONS_FILE`` / ``TPU_K8S_ACTIONS_KEEP`` — JSONL sink
  path and ring size for the action ledger.
"""

from __future__ import annotations

import collections
import json
import sys
import threading
import time
from pathlib import Path
from typing import Any, Callable, TextIO

from tpu_kubernetes.obs import REGISTRY, tracing
from tpu_kubernetes.obs.aggregate import FleetSnapshot
from tpu_kubernetes.obs.faults import FAULTS
from tpu_kubernetes.util.envparse import (
    env_bool,
    env_float,
    env_int,
    env_str,
)

# the closed action-kind vocabulary. Adding a kind = add it here AND
# document it in the docs/guide/observability.md action table; the
# static contracts pass (`tpu-kubernetes analyze`) fails CI on a
# new_action() literal that exists only in code (action-kind-unknown)
# or a registered kind missing from the guide (action-kind-undocumented)
# — the same two-way check fault sites and alert kinds get.
ACTION_KINDS = frozenset({
    "scale_up",       # add a replica via the Terraform executor path
    "scale_down",     # drain one instance (POST /drain), then shrink
    "drain_replace",  # drain a sick instance and re-apply its module
})

# every action record lands in exactly one of these
OUTCOMES = ("proposed", "executed", "failed", "suppressed")

ACTIONS_TOTAL = REGISTRY.counter(
    "tpu_fleet_actions_total",
    "fleet controller actions by kind and outcome (proposed/executed/"
    "failed/suppressed) — executed>0 with dry-run intended means the "
    "controller is live",
    labelnames=("kind", "outcome"),
)

ENV_DRY_RUN = "TPU_K8S_CONTROLLER_DRY_RUN"
ENV_COOLDOWN_S = "TPU_K8S_CONTROLLER_COOLDOWN_S"
ENV_MAX_ACTIONS = "TPU_K8S_CONTROLLER_MAX_ACTIONS"
ENV_MIN_REPLICAS = "TPU_K8S_CONTROLLER_MIN_REPLICAS"
ENV_MAX_REPLICAS = "TPU_K8S_CONTROLLER_MAX_REPLICAS"
ENV_ACTIONS_FILE = "TPU_K8S_ACTIONS_FILE"
ENV_ACTIONS_KEEP = "TPU_K8S_ACTIONS_KEEP"

ACTION_SCHEMA = "tpu-k8s-action/1"

# metric families the router/controller read from a FleetSnapshot
_SATURATION = "tpu_serve_saturation"
_PAGE_STALLS = "tpu_serve_kv_page_stalls_total"
_BUBBLE = "tpu_serve_slot_bubble_fraction"
_TOKENS_EMITTED = "tpu_serve_tokens_emitted_total"
_TOKENS_CLASS = "tpu_serve_tokens_total"


def new_action(kind: str, **fields: Any) -> dict[str, Any]:
    """The one choke point that mints action records — the analyzer
    checks every literal first argument against :data:`ACTION_KINDS`
    (action-kind-unknown), and the runtime enforces the same contract
    for dynamic callers."""
    if kind not in ACTION_KINDS:
        raise ValueError(
            f"unknown action kind {kind!r} (registered: "
            f"{sorted(ACTION_KINDS)})"
        )
    action: dict[str, Any] = {
        "schema": ACTION_SCHEMA,
        "id": "",
        "ts": 0.0,
        "kind": kind,
        "outcome": "proposed",
        "rule": "",
        "alert_fingerprint": "",
        "trace_id": "",
        "incident_id": "",
        "target": "",
        "reason": "",
        "error": "",
        "attempt": 0,
        "signal": {},
    }
    action.update(fields)
    if action["outcome"] not in OUTCOMES:
        raise ValueError(f"unknown action outcome {action['outcome']!r}")
    return action


class ActionLedger:
    """Bounded in-memory ring of action records plus an optional
    append-only JSONL sink — the `get actions` CLI reads the file, the
    tests read the ring, and ``tpu_fleet_actions_total`` counts every
    record either way."""

    def __init__(self, path: str | Path | None = None, keep: int = 256):
        self.path = Path(path) if path else None
        self.keep = max(1, int(keep))
        self._lock = threading.Lock()
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=self.keep
        )

    @classmethod
    def from_env(cls, env: dict | None = None) -> "ActionLedger":
        return cls(
            path=env_str(ENV_ACTIONS_FILE, "", env=env) or None,
            keep=env_int(ENV_ACTIONS_KEEP, 256, env=env),
        )

    def record(self, action: dict[str, Any]) -> dict[str, Any]:
        ACTIONS_TOTAL.labels(action["kind"], action["outcome"]).inc()
        with self._lock:
            self._ring.append(action)
            if self.path is not None:
                try:
                    self.path.parent.mkdir(parents=True, exist_ok=True)
                    with self.path.open("a", encoding="utf-8") as f:
                        f.write(json.dumps(action, sort_keys=True) + "\n")
                except OSError:
                    pass  # the ring (and the metric) still have it
        return action

    def actions(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._ring)[-max(0, int(n)):]


def list_actions(path: str | Path) -> list[dict[str, Any]]:
    """Read a JSONL action ledger tolerantly (half-written tail lines
    are skipped — the sink appends live)."""
    out: list[dict[str, Any]] = []
    try:
        text = Path(path).read_text(encoding="utf-8")
    except OSError:
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def render_actions(actions: list[dict[str, Any]]) -> str:
    """The human rendering for ``get actions`` — one line per record,
    newest last, audit-trail columns first."""
    if not actions:
        return "no recorded actions\n"
    lines = [
        f"{'TS':<19} {'KIND':<13} {'OUTCOME':<10} {'RULE':<18} "
        f"{'FPRINT':<12} {'TARGET':<21} REASON"
    ]
    for a in actions:
        ts = a.get("ts") or 0
        when = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts)) \
            if ts else "-"
        reason = a.get("reason") or ""
        if a.get("error"):
            reason = f"{reason} [{a['error']}]".strip()
        lines.append(
            f"{when:<19} {a.get('kind', '-'):<13}"
            f" {a.get('outcome', '-'):<10}"
            f" {(a.get('rule') or '-'):<18}"
            f" {(a.get('alert_fingerprint') or '-'):<12}"
            f" {(a.get('target') or '-'):<21}"
            f" {reason}"
        )
    return "\n".join(lines) + "\n"


def _squash(x: float, half: float) -> float:
    return x / (x + half) if x > 0 else 0.0


class FleetRouter:
    """Saturation-, page-pressure-, and prefix-affinity-aware placement
    over the latest :class:`FleetSnapshot`. ``update`` digests a scrape
    cycle; ``route`` picks an instance for a prompt."""

    # page-stall delta at which the pressure component scores 0.5
    STALL_HALF = 4.0
    # weight of page pressure relative to the saturation score
    STALL_WEIGHT = 0.5

    def __init__(self, prefix_chars: int = 64, sticky_max: int = 512,
                 sticky_ceiling: float = 0.9):
        self.prefix_chars = int(prefix_chars)
        self.sticky_max = int(sticky_max)
        self.sticky_ceiling = float(sticky_ceiling)
        self._lock = threading.Lock()
        self._sticky: collections.OrderedDict[int, str] = \
            collections.OrderedDict()
        self._score: dict[str, float] = {}
        self._sat: dict[str, float] = {}
        self._eligible: list[str] = []
        self._stall_prev: dict[str, float] = {}

    def update(self, snapshot: FleetSnapshot) -> None:
        score: dict[str, float] = {}
        sat: dict[str, float] = {}
        eligible: list[str] = []
        for instance in snapshot.instances():
            mine = (lambda i: lambda labels:
                    labels.get("instance") == i)(instance)
            s = max((smp.value for smp in snapshot._samples(
                _SATURATION, _SATURATION, mine)), default=0.0)
            stalls = snapshot.value_sum(_PAGE_STALLS, mine)
            prev = self._stall_prev.get(instance, stalls)
            delta = stalls - prev
            if delta < 0:  # counter reset (worker restart)
                delta = stalls
            self._stall_prev[instance] = stalls
            sat[instance] = s
            score[instance] = s + self.STALL_WEIGHT * _squash(
                delta, self.STALL_HALF
            )
            health = snapshot.health[instance]
            if health.up and health.lifecycle not in ("draining", "failed"):
                eligible.append(instance)
        with self._lock:
            self._score, self._sat = score, sat
            self._eligible = eligible

    def eligible(self) -> list[str]:
        with self._lock:
            return list(self._eligible)

    def saturation(self, instance: str) -> float:
        with self._lock:
            return self._sat.get(instance, 0.0)

    def route(self, prompt: str = "") -> str | None:
        """Pick an instance: prefix-sticky while the pinned instance is
        eligible and below the sticky ceiling, else the lowest combined
        saturation + page-pressure score (and re-pin the prefix there —
        its prefill warms that instance's prefix cache)."""
        key = hash(prompt[: self.prefix_chars]) if prompt else None
        with self._lock:
            if not self._eligible:
                return None
            if key is not None:
                pinned = self._sticky.get(key)
                if (pinned in self._eligible
                        and self._score.get(pinned, 0.0)
                        < self.sticky_ceiling):
                    self._sticky.move_to_end(key)
                    return pinned
            best = min(self._eligible,
                       key=lambda i: (self._score.get(i, 0.0), i))
            if key is not None:
                self._sticky[key] = best
                self._sticky.move_to_end(key)
                while len(self._sticky) > self.sticky_max:
                    self._sticky.popitem(last=False)
            return best


def fleet_goodput(snapshot: FleetSnapshot | None) -> float | None:
    """The ledger's useful share of every emitted token, fleet-wide —
    None until any worker has emitted anything."""
    if snapshot is None:
        return None
    emitted = snapshot.value_sum(_TOKENS_EMITTED)
    if not emitted:
        return None
    useful = snapshot.value_sum(
        _TOKENS_CLASS, lambda labels: labels.get("class") == "useful"
    )
    return round(useful / emitted, 4)


class FleetController:
    """Maps the alert stream to remediations under hard guards; every
    decision lands in the action ledger and the triggering incident
    bundle. Register on a manager with
    ``manager.listeners.append(controller)`` — ``observe`` runs inside
    the evaluate cycle with the same snapshot the rules saw."""

    def __init__(self, *, executor=None, scaler=None, drainer=None,
                 incidents=None, ledger: ActionLedger | None = None,
                 router: FleetRouter | None = None,
                 dry_run: bool | None = None,
                 cooldown_s: float | None = None,
                 max_actions: int | None = None,
                 min_replicas: int | None = None,
                 max_replicas: int | None = None,
                 idle_saturation: float = 0.05,
                 idle_hold_s: float = 120.0,
                 bubble_ceiling: float = 0.25,
                 goodput_floor: float = 0.9,
                 retry_backoff_s: float = 30.0,
                 max_retries: int = 2,
                 clock: Callable[[], float] = time.time,
                 env: dict | None = None):
        self.dry_run = env_bool(ENV_DRY_RUN, True, env=env) \
            if dry_run is None else bool(dry_run)
        self.cooldown_s = env_float(ENV_COOLDOWN_S, 300.0, env=env) \
            if cooldown_s is None else float(cooldown_s)
        self.max_actions = env_int(ENV_MAX_ACTIONS, 1, env=env) \
            if max_actions is None else int(max_actions)
        self.min_replicas = env_int(ENV_MIN_REPLICAS, 1, env=env) \
            if min_replicas is None else int(min_replicas)
        self.max_replicas = env_int(ENV_MAX_REPLICAS, 8, env=env) \
            if max_replicas is None else int(max_replicas)
        if scaler is None and executor is not None:
            from tpu_kubernetes.fleet.scaler import FleetScaler
            scaler = FleetScaler(executor, replicas=self.min_replicas)
        if drainer is None:
            from tpu_kubernetes.fleet.scaler import HTTPDrainer
            drainer = HTTPDrainer()
        self.scaler = scaler
        self.drainer = drainer
        self.incidents = incidents
        self.ledger = ledger if ledger is not None \
            else ActionLedger.from_env(env=env)
        self.router = router if router is not None else FleetRouter()
        self.idle_saturation = float(idle_saturation)
        self.idle_hold_s = float(idle_hold_s)
        self.bubble_ceiling = float(bubble_ceiling)
        self.goodput_floor = float(goodput_floor)
        self.retry_backoff_s = float(retry_backoff_s)
        self.max_retries = max(0, int(max_retries))
        self._clock = clock
        self._lock = threading.Lock()
        # per-fingerprint actuation state: at most one executed action
        # per alert episode, bounded retries with backoff in between
        self._handled: dict[str, dict[str, Any]] = {}
        self._last_kind_ts: dict[str, float] = {}
        self._idle_since: float | None = None
        self._seq = 0

    # -- placement ---------------------------------------------------------

    def route(self, prompt: str = "") -> str | None:
        return self.router.route(prompt)

    # -- remediation -------------------------------------------------------

    def replicas(self) -> int:
        if self.scaler is not None:
            return int(self.scaler.replicas)
        return self.min_replicas

    def actions(self) -> list[dict[str, Any]]:
        return self.ledger.actions()

    def observe(self, alerts: list[dict[str, Any]],
                now: float | None = None,
                snapshot: FleetSnapshot | None = None) -> list[dict]:
        """One controller cycle: digest the snapshot for routing, map
        firing alerts (plus sustained idleness) to decisions, and run
        each through the guard gauntlet. Returns the action records
        emitted this cycle. Never raises — this runs inside the alert
        evaluate loop."""
        now = float(self._clock() if now is None else now)
        if snapshot is not None:
            try:
                self.router.update(snapshot)
            except Exception:  # noqa: BLE001 — routing must not stop acting
                pass
        emitted: list[dict] = []
        with self._lock:
            self._started = 0  # the per-cycle actuation cap
            try:
                decisions = self._decide(alerts or [], snapshot, now)
                for decision in decisions:
                    emitted.extend(self._act(decision, now))
            except Exception:  # noqa: BLE001 — a controller bug must not
                pass           # take the monitor loop down with it
        return emitted

    def _decide(self, alerts: list[dict[str, Any]],
                snapshot: FleetSnapshot | None,
                now: float) -> list[dict[str, Any]]:
        decisions: list[dict[str, Any]] = []
        goodput = fleet_goodput(snapshot)
        firing = False
        for a in alerts:
            fp = a.get("fingerprint", "")
            state = a.get("state")
            if state == "resolved":
                # episode over: a future re-fire is a new decision
                self._handled.pop(fp, None)
                continue
            if state != "firing" or a.get("silenced"):
                continue
            firing = True
            kind = a.get("kind", "")
            inst = (a.get("labels") or {}).get("instance", "")
            base = {
                "fingerprint": fp,
                "rule": a.get("rule", ""),
                "target": inst,
                "signal": {"goodput": goodput,
                           "alert_kind": kind,
                           "value": a.get("value")},
            }
            if kind in ("queue_runaway", "slo_burn"):
                decisions.append(dict(
                    base, kind="scale_up",
                    reason=a.get("summary") or f"{kind} firing",
                ))
            elif kind == "engine_restart":
                decisions.append(dict(
                    base, kind="drain_replace",
                    reason=a.get("summary") or "engine restart loop",
                ))
        # scale-down is snapshot-driven, not alert-driven: sustained
        # idleness + low slot-bubble fraction + healthy goodput (a fleet
        # that is shedding or wasting work is not safe to shrink)
        if firing or snapshot is None:
            self._idle_since = None
            return decisions
        eligible = self.router.eligible()
        sats = [self.router.saturation(i) for i in eligible]
        n = max(1, len(snapshot.instances()))
        bubble = snapshot.value_sum(_BUBBLE) / n
        idle = (
            bool(eligible)
            and max(sats) < self.idle_saturation
            and bubble <= self.bubble_ceiling
            and (goodput is None or goodput >= self.goodput_floor)
            and self.replicas() > self.min_replicas
        )
        if not idle:
            self._idle_since = None
            return decisions
        if self._idle_since is None:
            self._idle_since = now
        if now - self._idle_since >= self.idle_hold_s:
            target = min(eligible, key=lambda i: (
                self.router.saturation(i), i))
            decisions.append({
                "kind": "scale_down",
                "fingerprint": f"idle:{target}",
                "rule": "",
                "target": target,
                "reason": (
                    f"fleet idle {now - self._idle_since:.0f}s "
                    f"(max saturation {max(sats):.3f}, "
                    f"bubble {bubble:.3f})"
                ),
                "signal": {"goodput": goodput,
                           "max_saturation": max(sats),
                           "bubble_fraction": round(bubble, 4)},
            })
        return decisions

    def _act(self, decision: dict[str, Any], now: float) -> list[dict]:
        fp = decision["fingerprint"]
        kind = decision["kind"]
        ent = self._handled.get(fp)
        if ent is not None and (ent["done"] or now < ent["next_ts"]):
            return []  # already acted, or holding down before a retry
        if self._started >= self.max_actions:
            return []  # per-cycle cap: remaining decisions wait a cycle
        emitted: list[dict] = []
        if ent is None:
            ent = self._handled[fp] = {
                "kind": kind, "attempts": 0, "done": False,
                "next_ts": 0.0,
                "trace_id": tracing.current_trace_id()
                or tracing.new_trace_id(),
            }
            emitted.append(self._emit(decision, "proposed", ent, now))
            if self.dry_run:
                ent["done"] = True
                emitted.append(self._emit(
                    decision, "suppressed", ent, now,
                    reason=f"dry-run ({ENV_DRY_RUN}=1)",
                ))
                return emitted
            last = self._last_kind_ts.get(kind)
            if last is not None and now - last < self.cooldown_s:
                ent["done"] = True
                emitted.append(self._emit(
                    decision, "suppressed", ent, now,
                    reason=(f"cooldown: {kind} acted "
                            f"{now - last:.0f}s ago "
                            f"(hold-down {self.cooldown_s:g}s)"),
                ))
                return emitted
            clamp = self._clamp_reason(kind)
            if clamp:
                ent["done"] = True
                emitted.append(self._emit(
                    decision, "suppressed", ent, now, reason=clamp,
                ))
                return emitted
        self._started += 1
        try:
            FAULTS.fire("fleet.remediate")
            detail = self._actuate(decision)
        except Exception as exc:  # noqa: BLE001 — any actuation failure
            ent["attempts"] += 1   # rides the same bounded-retry path
            error = f"{type(exc).__name__}: {exc}"[:200]
            if ent["attempts"] > self.max_retries:
                ent["done"] = True
                error += " (retries exhausted)"
            else:
                ent["next_ts"] = now + self.retry_backoff_s * (
                    2.0 ** (ent["attempts"] - 1)
                )
            emitted.append(self._emit(
                decision, "failed", ent, now, error=error,
            ))
        else:
            ent["done"] = True
            self._last_kind_ts[kind] = now
            if kind == "scale_down":
                self._idle_since = None
            emitted.append(self._emit(
                decision, "executed", ent, now, **detail,
            ))
        return emitted

    # per-cycle actuation counter (reset at the top of observe())
    _started = 0

    def _clamp_reason(self, kind: str) -> str:
        if kind == "scale_up" and self.replicas() >= self.max_replicas:
            return (f"at max replicas ({self.max_replicas}, "
                    f"{ENV_MAX_REPLICAS})")
        if kind == "scale_down" and self.replicas() <= self.min_replicas:
            return (f"at min replicas ({self.min_replicas}, "
                    f"{ENV_MIN_REPLICAS})")
        return ""

    def _actuate(self, decision: dict[str, Any]) -> dict[str, Any]:
        kind = decision["kind"]
        target = decision.get("target", "")
        if kind == "scale_up":
            if self.scaler is None:
                raise RuntimeError("scale_up without a scaler/executor")
            n = min(self.max_replicas, self.replicas() + 1)
            self.scaler.scale_to(n)
            return {"replicas": n}
        if kind == "scale_down":
            if not target:
                raise RuntimeError("scale_down without a target instance")
            drain = self.drainer.drain(target)
            n = self.replicas()
            if self.scaler is not None:
                n = max(self.min_replicas, n - 1)
                self.scaler.scale_to(n)
            return {"replicas": n,
                    "drain": {"status": drain.get("status"),
                              "accepted": drain.get("accepted")}}
        if kind == "drain_replace":
            detail: dict[str, Any] = {}
            if target:
                try:
                    detail["drain"] = {
                        "status": self.drainer.drain(target).get("status")
                    }
                except Exception as exc:  # noqa: BLE001 — a sick instance
                    # may not answer its drain; replacement still proceeds
                    detail["drain"] = {
                        "error": f"{type(exc).__name__}: {exc}"[:120]
                    }
            if self.scaler is None:
                raise RuntimeError("drain_replace without a scaler/executor")
            self.scaler.replace(target or "fleet")
            return detail
        raise RuntimeError(f"unmapped action kind {kind!r}")

    def _emit(self, decision: dict[str, Any], outcome: str,
              ent: dict[str, Any], now: float, *,
              error: str = "", reason: str | None = None,
              **extra: Any) -> dict[str, Any]:
        self._seq += 1
        incident_id = ""
        if self.incidents is not None:
            try:
                incident_id = self.incidents.current_incident_id() or ""
            except Exception:  # noqa: BLE001 — audit trail is best-effort
                pass
        action = new_action(
            decision["kind"],
            id=f"act-{self._seq}",
            ts=round(now, 3),
            outcome=outcome,
            rule=decision.get("rule", ""),
            alert_fingerprint=decision.get("fingerprint", ""),
            trace_id=ent["trace_id"],
            incident_id=incident_id,
            target=decision.get("target", ""),
            reason=reason if reason is not None
            else decision.get("reason", ""),
            error=error,
            attempt=ent["attempts"],
            signal=dict(decision.get("signal") or {}),
        )
        if extra:
            action["signal"].update(extra)
        self.ledger.record(action)
        if self.incidents is not None:
            try:
                self.incidents.note_action(action, now=now)
            except Exception:  # noqa: BLE001 — bundle write must not
                pass            # block the actuation path
        return action


def run_controller(targets: list[str], interval: float = 5.0,
                   once: bool = False, as_json: bool = False,
                   out: TextIO | None = None,
                   max_cycles: int | None = None,
                   timeout_s: float = 2.0,
                   dry_run: bool | None = None,
                   executor=None) -> int:
    """The ``fleet control`` CLI loop: scrape the fleet, evaluate the
    standard rules, and let the controller remediate — dry-run unless
    ``--apply`` (or ``TPU_K8S_CONTROLLER_DRY_RUN=0``) says otherwise.
    One status line (or JSON object) per cycle."""
    import os

    from tpu_kubernetes.obs import alerts as alerts_mod
    from tpu_kubernetes.obs.aggregate import FleetAggregator
    from tpu_kubernetes.obs.incidents import IncidentCorrelator
    from tpu_kubernetes.obs.tsdb import TSDB
    from tpu_kubernetes.shell.executor import default_executor

    out = sys.stdout if out is None else out
    store = TSDB()
    rules = alerts_mod.default_fleet_rules()
    rules_d = os.environ.get("TPU_K8S_ALERTS_D", "")
    if rules_d:
        try:
            rules += alerts_mod.load_rules(rules_d)
        except Exception as e:  # noqa: BLE001 — operator error, not a crash
            print(f"warning: TPU_K8S_ALERTS_D: {e}", file=sys.stderr)
    incidents = IncidentCorrelator.from_env(dict(os.environ))
    manager = alerts_mod.AlertManager(
        rules, sinks=alerts_mod.sinks_from_env(), incidents=incidents,
    )
    controller = FleetController(
        executor=executor if executor is not None else default_executor(),
        incidents=incidents, dry_run=dry_run,
    )
    manager.listeners.append(controller)
    aggregator = FleetAggregator(
        targets, timeout_s=timeout_s,
        backoff_base_s=0.0 if once else interval,
        tsdb=store, alerts=manager, probe_health=True,
    )
    cycles = 0
    try:
        while True:
            seen = len(controller.ledger.actions())
            snapshot = aggregator.scrape_once()
            fresh = controller.ledger.actions()[seen:]
            firing = [
                a for a in manager.active()
                if a.get("state") in ("pending", "firing")
            ]
            if as_json:
                print(json.dumps({
                    "ts": snapshot.ts,
                    "dry_run": controller.dry_run,
                    "replicas": controller.replicas(),
                    "instances": {
                        i: {"up": h.up, "state": h.lifecycle or None}
                        for i, h in snapshot.health.items()
                    },
                    "alerts": firing,
                    "actions": fresh,
                }, sort_keys=True), file=out, flush=True)
            else:
                up = sum(h.up for h in snapshot.health.values())
                line = (
                    f"fleet-control up={up}/{len(snapshot.health)}"
                    f" replicas={controller.replicas()}"
                    f" firing={len(firing)}"
                    f"{' [dry-run]' if controller.dry_run else ''}"
                )
                for a in fresh:
                    line += (f"\n  action {a['id']}: {a['kind']}"
                             f" -> {a['outcome']}"
                             f"{' — ' + a['reason'] if a['reason'] else ''}")
                print(line, file=out, flush=True)
            cycles += 1
            if once or (max_cycles is not None and cycles >= max_cycles):
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
    finally:
        manager.close()
