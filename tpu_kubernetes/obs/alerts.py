"""Rule-driven alerting: the layer that *acts on* what the obs stack sees.

The metrics registry, fleet aggregator, TSDB, and SLO burn windows can
observe everything; this module turns those observations into a
deduplicated, machine-consumable stream of firing signals — the input
the ROADMAP's fleet controller will eventually scale on, and the input
the incident correlator (obs/incidents.py) groups into postmortem
bundles today.

An :class:`AlertManager` evaluates a declarative registry of
:class:`Rule` objects against an :class:`EvalContext` (a fleet
snapshot, a history store, and/or engine-local callables) and runs each
breach through one state machine::

    ok ──breach──▶ pending ──held for_s──▶ firing
                      │                       │
                   clean                 clean held
                   (drop)                resolve_for_s
                      ▼                       ▼
                     ok ◀────retention──── resolved

Hold-downs apply in BOTH directions: a breach must persist ``for_s``
seconds before it fires (no paging on a blip) and a firing alert must
stay clean ``resolve_for_s`` seconds before it resolves (no strobing
when a signal hovers at its threshold). Alerts are deduplicated by
**fingerprint** (a stable hash of rule name + labels), grouped for
notification (one webhook POST per fingerprint per group interval, not
one per evaluation), silenceable by label matchers, and every state
transition emits a structured event (obs/events.py) carrying the
fingerprint as the correlation id. Clocks are injectable (``now=``)
throughout — the whole lifecycle is testable without a single sleep.

The rule vocabulary covers three families:

* **SLO burn** — :class:`SLOBurnRule` wraps an existing
  :class:`~tpu_kubernetes.obs.slo.SLOTracker`; the tracker keeps owning
  the burn-window state machine and the manager adds fingerprints,
  dedup, notifications, and incident correlation on top.
* **Invariant tripwires** — conditions that must NEVER be true: the
  page-pool partition drifting from ``free+live+pinned == total``, the
  token ledger failing ``sum(classes) == emitted``, a scrape target
  down, the engine-restart counter increasing, any fault injected.
* **Anomaly detectors** — EWMA/z-score latency drift, counter-stall
  (tokens-emitted flat while work is in flight), queue-depth runaway.

Notification sinks (JSONL file, webhook HTTP with bounded
retry/backoff) deliver from a dedicated daemon thread with a bounded
queue, so a dead webhook endpoint can NEVER block the scrape or
scheduler loop that evaluated the alert. Every delivery attempt runs
behind the ``obs.alert_sink`` fault site and lands in
``tpu_alert_notifications_total{sink,status}``; the current firing
count rides ``tpu_alerts_firing{severity}``.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from tpu_kubernetes.obs import REGISTRY
from tpu_kubernetes.obs.faults import FAULTS

SCHEMA = "tpu-k8s-alerts/1"

OK = "ok"
PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"

# how long a resolved alert stays listed (snapshot/`get alerts`) before
# the manager forgets its fingerprint
RESOLVED_RETENTION_S = 600.0

# -- self-metrics ------------------------------------------------------------

ALERTS_FIRING = REGISTRY.gauge(
    "tpu_alerts_firing",
    "alerts currently firing, by severity",
    labelnames=("severity",),
)
NOTIFICATIONS_TOTAL = REGISTRY.counter(
    "tpu_alert_notifications_total",
    "alert notification deliveries by sink and outcome "
    '(status="error" means the sink exhausted its bounded retries)',
    labelnames=("sink", "status"),
)


def fingerprint(rule: str, labels: dict[str, str] | None = None) -> str:
    """The stable dedup key of one (rule, labels) alert identity."""
    blob = rule + "|" + ",".join(
        f"{k}={v}" for k, v in sorted((labels or {}).items())
    )
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12]


@dataclass
class EvalContext:
    """What rules evaluate against. Fleet-side evaluation carries a
    :class:`~tpu_kubernetes.obs.aggregate.FleetSnapshot` and the shared
    history store; engine-local tripwires carry callables in ``local``
    (engine stats, the ledger, registry counter readers). Rules that
    need a part the context lacks report nothing — one rule file can
    serve both sides."""

    now: float
    snapshot: Any = None          # FleetSnapshot | None
    store: Any = None             # TSDB | None
    local: dict[str, Any] = field(default_factory=dict)

    def local_value(self, key: str) -> Any:
        v = self.local.get(key)
        return v() if callable(v) else v


@dataclass
class Reading:
    """One rule's verdict for one labeled identity this evaluation."""

    breached: bool
    value: float = 0.0
    labels: dict[str, str] = field(default_factory=dict)
    summary: str = ""
    severity: str = ""            # overrides the rule's severity when set
    state: str | None = None      # externally-owned lifecycle (SLO rules)
    since: float | None = None


class Rule:
    """One named alerting rule. Subclasses implement :meth:`evaluate`
    returning zero or more :class:`Reading` s (one per labeled identity,
    e.g. per instance). ``series`` names the TSDB series this rule
    reads — the incident correlator embeds their recent samples in the
    bundle so the postmortem shows the data the alert fired on."""

    kind = "rule"

    def __init__(self, name: str, severity: str = "ticket",
                 for_s: float = 0.0, resolve_for_s: float = 0.0,
                 group: str | None = None, description: str = "",
                 series: tuple[str, ...] | list[str] = ()):
        self.name = name
        self.severity = severity
        self.for_s = max(0.0, float(for_s))
        self.resolve_for_s = max(0.0, float(resolve_for_s))
        self.group = group or name
        self.description = description
        self.series = tuple(series)

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        raise NotImplementedError


# -- the declarative rule registry -------------------------------------------

RULE_KINDS: dict[str, Callable[..., Rule]] = {}


def rule_kind(name: str):
    """Register a rule constructor under a spec-file ``kind`` name."""

    def deco(fn):
        RULE_KINDS[name] = fn
        fn.kind = name
        return fn

    return deco


def build_rule(spec: dict) -> Rule:
    """One ``{"kind": ..., ...params}`` spec → a Rule. Unknown kinds and
    bad params are loud errors — a rule file that silently arms nothing
    is worse than no rule file (the obs/faults.py stance)."""
    spec = dict(spec)
    kind = spec.pop("kind", None)
    if not kind or kind not in RULE_KINDS:
        raise ValueError(
            f"alert rule kind {kind!r} is not registered "
            f"(known: {sorted(RULE_KINDS)})"
        )
    rule = RULE_KINDS[kind](**spec)
    rule.kind = kind
    return rule


def load_rules(path: str) -> list[Rule]:
    """Load rules from one JSON file or an ``alerts.d`` directory of
    ``*.json`` files. Each file is ``{"rules": [spec, ...]}`` (or a bare
    list). Missing path is a loud error; an empty directory is fine."""
    paths: list[str]
    if os.path.isdir(path):
        paths = sorted(
            os.path.join(path, n) for n in os.listdir(path)
            if n.endswith(".json")
        )
    elif os.path.isfile(path):
        paths = [path]
    else:
        raise FileNotFoundError(f"alert rule path {path!r} does not exist")
    rules: list[Rule] = []
    for p in paths:
        with open(p, encoding="utf-8") as f:
            doc = json.load(f)
        specs = doc.get("rules", []) if isinstance(doc, dict) else doc
        for spec in specs:
            rules.append(build_rule(spec))
    return rules


# -- SLO burn (the migrated obs/slo.py alerts) --------------------------------


@rule_kind("slo_burn")
class SLOBurnRule(Rule):
    """Wraps one :class:`~tpu_kubernetes.obs.slo.SLOTracker`: the
    tracker keeps owning the burn-window state machine (including its
    own pending/resolve hold-downs), and this rule surfaces its state
    into the manager — fingerprints, dedup, silences, notifications,
    events, and incident correlation come for free."""

    def __init__(self, tracker, **kw):
        from tpu_kubernetes.obs.slo import GOOD_SERIES, TOTAL_SERIES

        kw.setdefault("series", (GOOD_SERIES, TOTAL_SERIES))
        kw.setdefault("group", "slo")
        kw.setdefault("description", tracker.description)
        super().__init__(name=f"slo-{tracker.name}", **kw)
        self.tracker = tracker

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        alert = self.tracker.evaluate(now=ctx.now)
        return [Reading(
            breached=alert.state != OK,
            value=max(alert.burn_fast, alert.burn_slow),
            summary=(
                f"burn fast={alert.burn_fast:.1f}x slow="
                f"{alert.burn_slow:.1f}x (target {self.tracker.target:g})"
            ),
            severity=alert.severity or self.severity,
            state=alert.state,
            since=alert.since,
        )]


# -- invariant tripwires ------------------------------------------------------


class InvariantRule(Rule):
    """A condition that must never be true, checked by a callable
    ``check(ctx) -> Reading | list[Reading] | None``."""

    kind = "invariant"

    def __init__(self, name: str, check: Callable[[EvalContext], Any],
                 **kw):
        kw.setdefault("severity", "page")
        super().__init__(name=name, **kw)
        self._check = check

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        out = self._check(ctx)
        if out is None:
            return []
        return out if isinstance(out, list) else [out]


@rule_kind("page_partition")
def page_partition_rule(name: str = "page-partition-leak", **kw) -> Rule:
    """``free + live + pinned == total`` must hold for the KV page pool
    — a drift IS a page leak (serve/pages.py recomputes the partition
    from ground truth exactly so this check is meaningful). Engine-local
    evaluation reads ``local["pages"]``; fleet-side evaluation is a
    no-op (the gauge's free/live/pinned samples are scraped at different
    instants, so a cross-scrape sum would false-positive)."""
    kw.setdefault("series", ("tpu_serve_kv_pages",))
    kw.setdefault("description",
                  "KV page-pool partition free+live+pinned == total")

    def check(ctx: EvalContext):
        pages = ctx.local_value("pages")
        if not pages:
            return None
        parts = sum(
            pages.get(k, 0) for k in ("free", "live", "pinned")
        )
        total = pages.get("total", 0)
        return Reading(
            breached=parts != total,
            value=float(parts - total),
            summary=(
                f"free={pages.get('free')}+live={pages.get('live')}"
                f"+pinned={pages.get('pinned')} != total={total}"
            ),
        )

    return InvariantRule(name, check, **kw)


@rule_kind("ledger_conservation")
def ledger_conservation_rule(name: str = "ledger-conservation",
                             **kw) -> Rule:
    """The token ledger must settle: ``sum(classes) == emitted`` at
    quiescence. Tokens are legitimately unsettled while requests are in
    flight, which is exactly what the ``for_s`` pending hold absorbs —
    only a SUSTAINED imbalance fires. Reads ``local["ledger"]`` (the
    LEDGER singleton or anything with ``snapshot()``)."""
    kw.setdefault("for_s", 30.0)
    kw.setdefault("series", ("tpu_serve_tokens_emitted_total",
                             "tpu_serve_tokens_total"))
    kw.setdefault("description",
                  "token-ledger conservation sum(classes) == emitted")

    def check(ctx: EvalContext):
        ledger = ctx.local_value("ledger")
        if ledger is None:
            return None
        snap = ledger.snapshot() if hasattr(ledger, "snapshot") else ledger
        emitted = snap.get("emitted", 0)
        settled = sum(snap.get("classes", {}).values())
        unsettled = emitted - settled
        return Reading(
            breached=unsettled != 0,
            value=float(unsettled),
            summary=(
                f"emitted={emitted} settled={settled} "
                f"unsettled={unsettled}"
            ),
        )

    return InvariantRule(name, check, **kw)


@rule_kind("target_down")
def target_down_rule(name: str = "scrape-target-down", **kw) -> Rule:
    """A fleet scrape target with ``up == 0`` — one reading per dead
    instance, so two dead workers are two fingerprints (and one
    recovering doesn't resolve the other)."""
    kw.setdefault("severity", "page")
    kw.setdefault("series", ("up",))
    kw.setdefault("description", "scrape target down (up == 0)")

    def check(ctx: EvalContext):
        if ctx.snapshot is None:
            return None
        out = []
        for instance, h in sorted(ctx.snapshot.health.items()):
            out.append(Reading(
                breached=h.up == 0,
                value=float(h.consecutive_failures),
                labels={"instance": instance},
                summary=(
                    f"{h.consecutive_failures} consecutive scrape "
                    f"failures: {h.last_error}" if h.up == 0 else "up"
                ),
            ))
        return out

    return InvariantRule(name, check, **kw)


class CounterDeltaRule(Rule):
    """Fires when a cumulative counter increases by more than
    ``threshold`` between evaluations — the tripwire shape for "this
    number must never move" counters (engine restarts, injected
    faults, 5xx responses). ``value_fn(ctx)`` returns the current
    cumulative reading (float, or ``{label_key: float}`` for per-label
    identities, or None to skip). Reset-aware: a shrinking counter
    (process restart) re-baselines instead of alerting on the wrap."""

    kind = "counter_delta"

    def __init__(self, name: str,
                 value_fn: Callable[[EvalContext], Any],
                 threshold: float = 0.0, label: str = "", **kw):
        super().__init__(name=name, **kw)
        self._value_fn = value_fn
        self.threshold = float(threshold)
        self._label = label
        self._prev: dict[str, float] = {}

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        current = self._value_fn(ctx)
        if current is None:
            return []
        if not isinstance(current, dict):
            current = {"": float(current)}
        out = []
        for key, value in sorted(current.items()):
            prev = self._prev.get(key)
            self._prev[key] = value
            if prev is None:         # first sight: baseline, never alert
                continue
            delta = value - prev
            if delta < 0:            # counter reset — re-baseline
                delta = 0.0
            labels = {self._label: key} if self._label and key else {}
            out.append(Reading(
                breached=delta > self.threshold,
                value=delta,
                labels=labels,
                summary=f"+{delta:g} since last evaluation "
                        f"(cumulative {value:g})",
            ))
        return out


def _snapshot_sum(series: str, pred=None):
    """A per-instance counter reader over the fleet snapshot."""

    def value_fn(ctx: EvalContext):
        if ctx.snapshot is None:
            return None
        out = {}
        for instance in ctx.snapshot.instances():
            out[instance] = ctx.snapshot.value_sum(
                series,
                lambda labels, i=instance: (
                    labels.get("instance") == i
                    and (pred is None or pred(labels))
                ),
            )
        return out

    return value_fn


@rule_kind("counter_delta")
def counter_delta_rule(name: str, series: str, threshold: float = 0.0,
                       **kw) -> Rule:
    """Spec-file face of :class:`CounterDeltaRule`: per-instance deltas
    of one fleet-scraped counter family."""
    kw.setdefault("series", (series,))
    kw.setdefault("description", f"increase of {series}")
    return CounterDeltaRule(
        name, _snapshot_sum(series), threshold=threshold,
        label="instance", **kw,
    )


@rule_kind("engine_restart")
def engine_restart_rule(name: str = "engine-restarts", **kw) -> Rule:
    """The engine-restart counter moved — locally from
    ``local["restarts"]`` when present, else the scraped family."""
    kw.setdefault("severity", "page")
    kw.setdefault("series", ("tpu_serve_engine_restarts_total",))
    kw.setdefault("description", "slot-engine watchdog restart")
    fleet = _snapshot_sum("tpu_serve_engine_restarts_total")

    def value_fn(ctx: EvalContext):
        local = ctx.local_value("restarts")
        if local is not None:
            return float(local)
        return fleet(ctx)

    return CounterDeltaRule(name, value_fn, label="instance", **kw)


@rule_kind("fault_injection")
def fault_injection_rule(name: str = "fault-injected", **kw) -> Rule:
    """Any injected-fault counter increase: nonzero outside a chaos run
    means ``TPU_K8S_FAULTS`` leaked into prod (the obs/faults.py
    warning, promoted to a tripwire). In a chaos run this is the
    universal canary — every armed site that fires trips it."""
    kw.setdefault("severity", "warn")
    kw.setdefault("series", ("tpu_k8s_faults_injected_total",))
    kw.setdefault("description", "faults injected (TPU_K8S_FAULTS armed)")
    fleet = _snapshot_sum("tpu_k8s_faults_injected_total")

    def value_fn(ctx: EvalContext):
        local = ctx.local_value("faults_total")
        if local is not None:
            return float(local)
        return fleet(ctx)

    return CounterDeltaRule(name, value_fn, label="instance", **kw)


# -- anomaly detectors --------------------------------------------------------


@rule_kind("counter_stall")
class CounterStallRule(Rule):
    """Tokens-emitted flat while work is in flight: the engine is wedged
    (a hung device call, a dead scheduler) even though every gauge looks
    "busy". Breached when the emitted counter's delta is 0 while the
    inflight reading is > 0 — sustained for ``for_s`` before firing, so
    a long prefill between segments doesn't page."""

    def __init__(self, name: str = "token-counter-stall",
                 counter: str = "tpu_serve_tokens_emitted_total",
                 inflight: str = "tpu_serve_inflight_requests", **kw):
        kw.setdefault("for_s", 30.0)
        kw.setdefault("severity", "page")
        kw.setdefault("series", (counter, inflight))
        kw.setdefault("description",
                      f"{counter} flat while {inflight} > 0")
        super().__init__(name=name, **kw)
        self.counter = counter
        self.inflight = inflight
        self._prev: dict[str, float] = {}

    def _pairs(self, ctx: EvalContext):
        emitted = ctx.local_value("emitted")
        inflight = ctx.local_value("inflight")
        if emitted is not None and inflight is not None:
            yield "", float(emitted), float(inflight)
            return
        if ctx.snapshot is None:
            return
        for instance in ctx.snapshot.instances():
            mine = (lambda i: lambda labels:
                    labels.get("instance") == i)(instance)
            yield (instance,
                   ctx.snapshot.value_sum(self.counter, mine),
                   ctx.snapshot.value_sum(self.inflight, mine))

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        out = []
        for key, emitted, inflight in self._pairs(ctx):
            prev = self._prev.get(key)
            self._prev[key] = emitted
            if prev is None:
                continue
            delta = emitted - prev
            out.append(Reading(
                breached=delta <= 0 and inflight > 0,
                value=inflight,
                labels={"instance": key} if key else {},
                summary=(
                    f"emitted +{max(0.0, delta):g} with "
                    f"{inflight:g} in flight"
                ),
            ))
        return out


@rule_kind("queue_runaway")
class QueueRunawayRule(Rule):
    """Queue depth at or beyond ``max_depth`` — sustained (``for_s``)
    so an admission burst that drains doesn't page. Engine-local
    evaluation reads ``local["queued"]``; fleet-side the inflight
    gauge per instance."""

    def __init__(self, name: str = "queue-runaway",
                 series: str = "tpu_serve_inflight_requests",
                 max_depth: float = 64.0, **kw):
        kw.setdefault("for_s", 30.0)
        kw.setdefault("series", (series,))
        kw.setdefault("description",
                      f"{series} >= {max_depth:g} sustained")
        super().__init__(name=name, **kw)
        self.gauge = series
        self.max_depth = float(max_depth)

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        local = ctx.local_value("queued")
        if local is not None:
            depth = float(local)
            return [Reading(
                breached=depth >= self.max_depth, value=depth,
                summary=f"queue depth {depth:g} >= {self.max_depth:g}",
            )]
        if ctx.snapshot is None:
            return []
        out = []
        for instance in ctx.snapshot.instances():
            mine = (lambda i: lambda labels:
                    labels.get("instance") == i)(instance)
            depth = ctx.snapshot.value_sum(self.gauge, mine)
            out.append(Reading(
                breached=depth >= self.max_depth, value=depth,
                labels={"instance": instance},
                summary=f"queue depth {depth:g} >= {self.max_depth:g}",
            ))
        return out


@rule_kind("latency_drift")
class EWMADriftRule(Rule):
    """EWMA/z-score drift detection on a latency quantile: keeps an
    exponentially-weighted mean and variance per instance and fires
    when the current reading sits more than ``z`` standard deviations
    above the learned mean (one-sided — getting faster is not an
    incident). Needs ``min_samples`` readings before it can alert, so a
    cold start never pages on its own warm-up."""

    def __init__(self, name: str = "latency-drift",
                 histogram: str = "tpu_serve_request_seconds",
                 q: float = 0.99, alpha: float = 0.3, z: float = 4.0,
                 min_samples: int = 8, min_sigma_s: float = 1e-3, **kw):
        kw.setdefault("series", (histogram,))
        kw.setdefault("description",
                      f"p{int(q * 100)} {histogram} z-score > {z:g}")
        super().__init__(name=name, **kw)
        self.histogram = histogram
        self.q = q
        self.alpha = float(alpha)
        self.z = float(z)
        self.min_samples = int(min_samples)
        self.min_sigma_s = float(min_sigma_s)
        # per-key (mean, variance, count)
        self._ewma: dict[str, tuple[float, float, int]] = {}

    def _readings(self, ctx: EvalContext):
        local = ctx.local_value("latency_q")
        if local is not None:
            yield "", float(local)
            return
        if ctx.snapshot is None:
            return
        for instance in ctx.snapshot.instances():
            mine = (lambda i: lambda labels:
                    labels.get("instance") == i)(instance)
            v = ctx.snapshot.quantile(self.histogram, self.q, mine)
            if v is not None:
                yield instance, float(v)

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        out = []
        for key, v in self._readings(ctx):
            mean, var, n = self._ewma.get(key, (v, 0.0, 0))
            sigma = max(math.sqrt(max(0.0, var)), self.min_sigma_s)
            score = (v - mean) / sigma
            breached = n >= self.min_samples and score > self.z
            if not breached:
                # the baseline only learns non-anomalous readings — an
                # outage must not teach the detector that slow is normal
                d = v - mean
                mean += self.alpha * d
                var = (1 - self.alpha) * (var + self.alpha * d * d)
                n += 1
            self._ewma[key] = (mean, var, n)
            out.append(Reading(
                breached=breached, value=round(score, 3),
                labels={"instance": key} if key else {},
                summary=(
                    f"p{int(self.q * 100)}={v:.3f}s z={score:.1f} "
                    f"(mean {mean:.3f}s ± {sigma:.3f}s over {n})"
                ),
            ))
        return out


@rule_kind("gauge_threshold")
class GaugeThresholdRule(Rule):
    """A plain threshold on one gauge/counter family: breached when the
    per-instance sum compares true against ``threshold`` under ``op``
    (``>=``, ``>``, ``<=``, ``<``)."""

    _OPS = {">=": lambda a, b: a >= b, ">": lambda a, b: a > b,
            "<=": lambda a, b: a <= b, "<": lambda a, b: a < b}

    def __init__(self, name: str, series: str, threshold: float,
                 op: str = ">=", **kw):
        if op not in self._OPS:
            raise ValueError(f"gauge_threshold op {op!r} not in "
                             f"{sorted(self._OPS)}")
        kw.setdefault("series", (series,))
        kw.setdefault("description", f"{series} {op} {threshold:g}")
        super().__init__(name=name, **kw)
        self.series_name = series
        self.threshold = float(threshold)
        self.op = op

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        cmp = self._OPS[self.op]
        local = ctx.local_value(self.series_name)
        if local is not None:
            v = float(local)
            return [Reading(
                breached=cmp(v, self.threshold), value=v,
                summary=f"{self.series_name}={v:g} {self.op} "
                        f"{self.threshold:g}",
            )]
        if ctx.snapshot is None:
            return []
        out = []
        for instance in ctx.snapshot.instances():
            mine = (lambda i: lambda labels:
                    labels.get("instance") == i)(instance)
            v = ctx.snapshot.value_sum(self.series_name, mine)
            out.append(Reading(
                breached=cmp(v, self.threshold), value=v,
                labels={"instance": instance},
                summary=f"{self.series_name}={v:g} {self.op} "
                        f"{self.threshold:g}",
            ))
        return out


@rule_kind("quantile_threshold")
class QuantileThresholdRule(Rule):
    """Breached when a histogram quantile exceeds ``threshold_s`` —
    the fixed-bar sibling of the adaptive :class:`EWMADriftRule`."""

    def __init__(self, name: str, histogram: str, threshold_s: float,
                 q: float = 0.99, **kw):
        kw.setdefault("series", (histogram,))
        kw.setdefault("description",
                      f"p{int(q * 100)} {histogram} > {threshold_s:g}s")
        super().__init__(name=name, **kw)
        self.histogram = histogram
        self.q = q
        self.threshold_s = float(threshold_s)

    def evaluate(self, ctx: EvalContext) -> list[Reading]:
        if ctx.snapshot is None:
            return []
        out = []
        for instance in ctx.snapshot.instances():
            mine = (lambda i: lambda labels:
                    labels.get("instance") == i)(instance)
            v = ctx.snapshot.quantile(self.histogram, self.q, mine)
            if v is None:
                continue
            out.append(Reading(
                breached=v > self.threshold_s, value=round(v, 6),
                labels={"instance": instance},
                summary=f"p{int(self.q * 100)}={v:.3f}s > "
                        f"{self.threshold_s:g}s",
            ))
        return out


# -- notification sinks -------------------------------------------------------


class JSONLSink:
    """Append one JSON line per notification batch — the machine-
    consumable alert log (``TPU_K8S_ALERTS_FILE``)."""

    name = "jsonl"

    def __init__(self, path: str):
        self.path = path

    def send(self, batch: dict) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(batch, sort_keys=True, default=str) + "\n")


class WebhookSink:
    """POST each batch as JSON to one URL (``TPU_K8S_ALERT_WEBHOOK``)
    with bounded retry/backoff: at most ``retries + 1`` attempts,
    sleeping ``backoff_s * 2^i`` between them, then the error
    propagates to the notifier (which counts it and moves on). The
    worst case is strictly bounded — a dead endpoint costs
    ``(retries+1) * timeout_s + sum(backoffs)`` on the NOTIFIER thread,
    never on the loop that evaluated the alert."""

    name = "webhook"

    def __init__(self, url: str, timeout_s: float = 2.0,
                 retries: int = 2, backoff_s: float = 0.1):
        self.url = url
        self.timeout_s = float(timeout_s)
        self.retries = max(0, int(retries))
        self.backoff_s = max(0.0, float(backoff_s))

    def send(self, batch: dict) -> None:
        body = json.dumps(batch, sort_keys=True, default=str).encode()
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            if attempt and self.backoff_s:
                time.sleep(self.backoff_s * 2.0 ** (attempt - 1))
            try:
                req = urllib.request.Request(
                    self.url, data=body,
                    headers={"Content-Type": "application/json",
                             "User-Agent": "tpu-k8s-alerts"},
                    method="POST",
                )
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as resp:
                    resp.read()
                return
            except Exception as e:  # noqa: BLE001 — bounded retry
                last = e
        raise last if last is not None else RuntimeError("unreachable")


def sinks_from_env(env: dict | None = None) -> list:
    """The sinks one process's env asks for: ``TPU_K8S_ALERTS_FILE``
    (JSONL) and/or ``TPU_K8S_ALERT_WEBHOOK`` (HTTP POST)."""
    env = os.environ if env is None else env
    sinks: list = []
    path = env.get("TPU_K8S_ALERTS_FILE", "")
    if path:
        sinks.append(JSONLSink(path))
    url = env.get("TPU_K8S_ALERT_WEBHOOK", "")
    if url:
        from tpu_kubernetes.util.envparse import env_float, env_int

        sinks.append(WebhookSink(
            url,
            timeout_s=env_float("TPU_K8S_ALERT_WEBHOOK_TIMEOUT_S", 2.0,
                                env=env),
            retries=env_int("TPU_K8S_ALERT_WEBHOOK_RETRIES", 2,
                            env=env),
        ))
    return sinks


class _Notifier:
    """The delivery thread: a bounded queue the manager pushes batches
    into and a daemon that drains it through every sink. Evaluation
    loops (fleet scrape cycle, engine scheduler) only ever pay one
    deque append — a dead webhook endpoint backs up THIS thread, and
    when the queue overflows the oldest batches drop (counted as
    ``status="dropped"``) rather than blocking anyone."""

    MAX_QUEUE = 256

    def __init__(self, sinks: list):
        self.sinks = list(sinks)
        self._queue: deque[dict] = deque()
        self._cond = threading.Condition()
        self._inflight = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="alert-notifier"
        )
        self._thread.start()

    def submit(self, batch: dict) -> None:
        with self._cond:
            if self._closed:
                return
            if len(self._queue) >= self.MAX_QUEUE:
                self._queue.popleft()
                for sink in self.sinks:
                    NOTIFICATIONS_TOTAL.labels(sink.name, "dropped").inc()
            self._queue.append(batch)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                batch = self._queue.popleft()
                self._inflight += 1
            try:
                self._deliver(batch)
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _deliver(self, batch: dict) -> None:
        for sink in self.sinks:
            try:
                FAULTS.fire("obs.alert_sink")
                sink.send(batch)
            except Exception:  # noqa: BLE001 — a sink must not kill others
                NOTIFICATIONS_TOTAL.labels(sink.name, "error").inc()
            else:
                NOTIFICATIONS_TOTAL.labels(sink.name, "ok").inc()

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Block until every submitted batch has been attempted (tests;
        nothing on the serving path calls this)."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._queue or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=left)
        return True

    def close(self, timeout_s: float = 2.0) -> None:
        self.drain(timeout_s)
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# -- silences ----------------------------------------------------------------


@dataclass
class Silence:
    """Suppress notifications for alerts matching every matcher
    (``rule`` matches the rule name; anything else matches a label).
    The alert still tracks state — a silenced page is a known problem,
    not an invisible one."""

    matchers: dict[str, str]
    until: float | None = None
    comment: str = ""

    def active(self, now: float) -> bool:
        return self.until is None or now < self.until

    def matches(self, rule: str, labels: dict[str, str]) -> bool:
        for k, v in self.matchers.items():
            have = rule if k == "rule" else labels.get(k)
            if have != v:
                return False
        return True

    def to_dict(self) -> dict:
        return {"matchers": dict(self.matchers), "until": self.until,
                "comment": self.comment}


@dataclass
class _Tracked:
    """The manager's per-fingerprint lifecycle record."""

    rule: Rule
    labels: dict[str, str]
    state: str = OK
    severity: str = ""
    since: float | None = None          # current pending/firing began
    firing_since: float | None = None
    clear_since: float | None = None    # resolve hold-down anchor
    resolved_at: float | None = None
    value: float = 0.0
    summary: str = ""
    silenced: bool = False
    seen: bool = False                  # reported by its rule this cycle


class AlertManager:
    """The rule registry + lifecycle + dedup + notification fan-out.

    Thread-safe: one evaluation runs at a time under the manager lock
    (the engine scheduler ticks while an HTTP handler snapshots).
    ``evaluate`` never raises and never blocks on sink I/O — a broken
    rule is skipped, deliveries happen on the notifier thread."""

    def __init__(self, rules: list[Rule], sinks: list | None = None,
                 group_interval_s: float = 60.0,
                 resolved_retention_s: float = RESOLVED_RETENTION_S,
                 incidents=None):
        self.rules = list(rules)
        self.group_interval_s = max(0.0, float(group_interval_s))
        self.resolved_retention_s = float(resolved_retention_s)
        # the incident correlator (obs/incidents.py) observes every
        # evaluation's alert list — temporally overlapping firing
        # alerts become one incident bundle
        self.incidents = incidents
        # subscribers beyond the correlator: anything with an
        # ``observe(alerts, now=, snapshot=)`` method (duck-typed — the
        # FleetController in obs/controller.py registers here) sees the
        # same alert list the correlator does, after it, so an acting
        # listener reads the incident the correlator just opened
        self.listeners: list = []
        self._silences: list[Silence] = []
        self._tracked: dict[str, _Tracked] = {}
        self._lock = threading.Lock()
        self._notifier = _Notifier(sinks) if sinks else None
        # per-group notification pacing: pending transition queue + the
        # last flush time (one POST per fingerprint per group interval)
        self._pending_notify: dict[str, dict[str, dict]] = {}
        self._last_flush: dict[str, float] = {}

    # -- silences ----------------------------------------------------------

    def silence(self, matchers: dict[str, str], until: float | None = None,
                comment: str = "") -> Silence:
        s = Silence(dict(matchers), until, comment)
        with self._lock:
            self._silences.append(s)
        return s

    def _silenced(self, rule: str, labels: dict[str, str],
                  now: float) -> bool:
        return any(
            s.active(now) and s.matches(rule, labels)
            for s in self._silences
        )

    # -- evaluation --------------------------------------------------------

    def evaluate(self, ctx: EvalContext | None = None, *,
                 now: float | None = None, snapshot=None, store=None,
                 local: dict | None = None) -> list[dict]:
        """One evaluation cycle: run every rule, advance every
        fingerprint's state machine, emit transition events, update the
        firing gauge, queue grouped notifications, and feed the
        incident correlator. Returns the current alert list (active +
        recently resolved)."""
        if ctx is None:
            ctx = EvalContext(
                now=time.time() if now is None else now,
                snapshot=snapshot, store=store, local=local or {},
            )
        with self._lock:
            transitions = self._advance(ctx)
            alerts = [self._to_dict(t, ctx.now)
                      for t in self._tracked.values()]
            self._set_gauge()
            self._queue_notifications(transitions, ctx.now)
            self._flush_groups(ctx.now)
        for fp, old, new, alert in transitions:
            from tpu_kubernetes.obs import events

            events.emit("alert_transition", fingerprint=fp,
                        rule=alert["rule"], labels=alert["labels"],
                        severity=alert["severity"], from_state=old,
                        to_state=new, value=alert["value"],
                        summary=alert["summary"])
        if self.incidents is not None:
            try:
                self.incidents.observe(alerts, now=ctx.now)
            except Exception:  # noqa: BLE001 — correlation must not
                pass           # fail the evaluation loop
        for listener in list(self.listeners):
            try:
                listener.observe(alerts, now=ctx.now,
                                 snapshot=ctx.snapshot)
            except Exception:  # noqa: BLE001 — a listener must not
                pass           # fail the evaluation loop
        return alerts

    def _advance(self, ctx: EvalContext):
        """Run the rules and move every fingerprint one step; returns
        ``(fingerprint, old_state, new_state, alert_dict)`` for each
        transition. Lock held by the caller."""
        now = ctx.now
        reported: set[str] = set()
        transitions = []
        for rule in self.rules:
            try:
                readings = rule.evaluate(ctx)
            except Exception:  # noqa: BLE001 — one broken rule must not
                continue       # take down the evaluation cycle
            for r in readings:
                fp = fingerprint(rule.name, r.labels)
                reported.add(fp)
                t = self._tracked.get(fp)
                if t is None:
                    if not r.breached:
                        continue
                    t = self._tracked[fp] = _Tracked(
                        rule=rule, labels=dict(r.labels)
                    )
                t.value = r.value
                if r.summary:
                    t.summary = r.summary
                t.severity = r.severity or rule.severity
                t.silenced = self._silenced(rule.name, t.labels, now)
                old = t.state
                if r.state is not None:
                    self._step_external(t, r, now)
                else:
                    self._step(t, r.breached, now)
                if t.state != old:
                    transitions.append(
                        (fp, old, t.state, self._to_dict(t, now))
                    )
                if t.state == OK:   # a pending blip that cleared: forget
                    self._tracked.pop(fp, None)
        # a tracked fingerprint its rule stopped reporting is clean
        # (e.g. a per-instance reading whose instance left the fleet)
        for fp, t in list(self._tracked.items()):
            if fp in reported:
                continue
            old = t.state
            self._step(t, False, now)
            if t.state != old:
                transitions.append((fp, old, t.state, self._to_dict(t, now)))
            if t.state == OK:
                del self._tracked[fp]
        # retention: resolved alerts age out of the listing
        for fp, t in list(self._tracked.items()):
            if (t.state == RESOLVED and t.resolved_at is not None
                    and now - t.resolved_at >= self.resolved_retention_s):
                del self._tracked[fp]
        return transitions

    def _step(self, t: _Tracked, breached: bool, now: float) -> None:
        """The manager-owned state machine, hold-downs both ways."""
        if breached:
            t.clear_since = None
            if t.state in (OK, RESOLVED):
                t.since = now
                t.state = PENDING
                t.resolved_at = None
            since = now if t.since is None else t.since
            if t.state == PENDING and now - since >= t.rule.for_s:
                t.state = FIRING
                t.firing_since = t.firing_since or now
        else:
            if t.state == PENDING:
                t.state = OK
                t.since = None
            elif t.state == FIRING:
                if t.rule.resolve_for_s > 0:
                    if t.clear_since is None:
                        t.clear_since = now
                    if now - t.clear_since < t.rule.resolve_for_s:
                        return
                t.state = RESOLVED
                t.resolved_at = now
                t.clear_since = None

    def _step_external(self, t: _Tracked, r: Reading, now: float) -> None:
        """Mirror an externally-owned lifecycle (the SLO tracker's) —
        the manager only translates its terminal clean state into
        ``resolved`` so receivers see the close."""
        if r.state in (PENDING, FIRING):
            t.state = r.state
            if r.since is not None:
                t.since = r.since
            elif t.since is None:
                t.since = now
            if r.state == FIRING:
                t.firing_since = t.firing_since or now
            t.resolved_at = None
        else:  # ok
            if t.state == FIRING:
                t.state = RESOLVED
                t.resolved_at = now
            elif t.state in (PENDING, OK):
                t.state = OK
                t.since = None

    # -- notifications -----------------------------------------------------

    def _queue_notifications(self, transitions, now: float) -> None:
        for fp, _old, new, alert in transitions:
            if new not in (FIRING, RESOLVED):
                continue
            if alert["silenced"]:
                continue
            group = alert["group"]
            self._pending_notify.setdefault(group, {})[fp] = alert

    def _flush_groups(self, now: float) -> None:
        if self._notifier is None:
            self._pending_notify.clear()
            return
        for group, pending in list(self._pending_notify.items()):
            if not pending:
                continue
            last = self._last_flush.get(group)
            if last is not None and now - last < self.group_interval_s:
                continue
            firing = [
                self._to_dict(t, now) for t in self._tracked.values()
                if t.state == FIRING and t.rule.group == group
                and not t.silenced
            ]
            batch = {
                "schema": SCHEMA,
                "ts": round(now, 3),
                "group": group,
                "alerts": list(pending.values()),
                "firing": firing,
            }
            self._last_flush[group] = now
            self._pending_notify[group] = {}
            self._notifier.submit(batch)

    def _set_gauge(self) -> None:
        counts: dict[str, int] = {}
        for t in self._tracked.values():
            if t.state == FIRING:
                sev = t.severity or "none"
                counts[sev] = counts.get(sev, 0) + 1
        for sev in ("page", "ticket", "warn"):
            counts.setdefault(sev, 0)
        for sev, n in counts.items():
            ALERTS_FIRING.labels(sev).set(float(n))

    # -- read faces --------------------------------------------------------

    def _to_dict(self, t: _Tracked, now: float) -> dict:
        return {
            "fingerprint": fingerprint(t.rule.name, t.labels),
            "rule": t.rule.name,
            "kind": t.rule.kind,
            "group": t.rule.group,
            "labels": dict(t.labels),
            "severity": t.severity,
            "state": t.state,
            "since": t.since,
            "age_s": (None if t.since is None
                      else round(max(0.0, now - t.since), 3)),
            "firing_since": t.firing_since,
            "resolved_at": t.resolved_at,
            "value": t.value,
            "summary": t.summary,
            "description": t.rule.description,
            "series": list(t.rule.series),
            "silenced": t.silenced,
        }

    def active(self, now: float | None = None) -> list[dict]:
        """Current tracked alerts (pending/firing + recently resolved),
        most severe states first."""
        now = time.time() if now is None else now
        order = {FIRING: 0, PENDING: 1, RESOLVED: 2}
        with self._lock:
            alerts = [self._to_dict(t, now) for t in self._tracked.values()]
        return sorted(alerts, key=lambda a: (order.get(a["state"], 3),
                                             a["rule"]))

    def summary(self, now: float | None = None) -> dict:
        """The one-glance healthz mirror: counts by state/severity."""
        alerts = self.active(now)
        by_sev: dict[str, int] = {}
        for a in alerts:
            if a["state"] == FIRING:
                by_sev[a["severity"] or "none"] = (
                    by_sev.get(a["severity"] or "none", 0) + 1
                )
        return {
            "firing": sum(a["state"] == FIRING for a in alerts),
            "pending": sum(a["state"] == PENDING for a in alerts),
            "by_severity": by_sev,
        }

    def snapshot(self, now: float | None = None) -> dict:
        """The full ``GET /debug/alerts`` payload."""
        now = time.time() if now is None else now
        with self._lock:
            silences = [s.to_dict() for s in self._silences
                        if s.active(now)]
            rules = [
                {"name": r.name, "kind": r.kind, "severity": r.severity,
                 "for_s": r.for_s, "resolve_for_s": r.resolve_for_s,
                 "group": r.group, "description": r.description}
                for r in self.rules
            ]
        return {
            "schema": SCHEMA,
            "ts": round(now, 3),
            "alerts": self.active(now),
            "summary": self.summary(now),
            "silences": silences,
            "rules": rules,
        }

    def close(self) -> None:
        if self._notifier is not None:
            self._notifier.close()

    # test/ops hook: block until queued notifications were attempted
    def drain_notifications(self, timeout_s: float = 5.0) -> bool:
        if self._notifier is None:
            return True
        return self._notifier.drain(timeout_s)


# -- standard rule sets -------------------------------------------------------


def _registry_counter_sum(registry, name: str, pred=None) -> float:
    """Sum one counter family's samples straight from a process-local
    registry snapshot (no exposition round-trip)."""
    fam = registry.snapshot(prefix=name).get(name)
    if not fam:
        return 0.0
    return sum(
        s["value"] for s in fam["samples"]
        if pred is None or pred(s["labels"])
    )


def engine_tripwires(*, stats_fn: Callable[[], dict | None],
                     ledger=None, registry=None,
                     for_s: float = 5.0, resolve_for_s: float = 10.0,
                     queue_max_depth: float = 64.0) -> list[Rule]:
    """The engine-local tripwire set the serve scheduler evaluates
    between segments: page-partition, ledger conservation, restart and
    5xx counter deltas, fault injection, counter-stall, and queue
    runaway — all reading THIS process (``stats_fn`` is the engine's
    ``stats()``), no scrape hop involved."""
    registry = REGISTRY if registry is None else registry

    def stat(key):
        def get():
            s = stats_fn()
            return None if s is None else s.get(key)
        return get

    def pages():
        s = stats_fn()
        return None if s is None else s.get("pages")

    def emitted():
        if ledger is None:
            return None
        return ledger.snapshot().get("emitted", 0)

    def inflight():
        s = stats_fn()
        if s is None:
            return None
        return (s.get("occupied") or 0) + (s.get("queued") or 0)

    def errors_5xx():
        return _registry_counter_sum(
            registry, "tpu_serve_requests_total",
            lambda labels: labels.get("code", "").startswith("5"),
        )

    def faults_total():
        return _registry_counter_sum(
            registry, "tpu_k8s_faults_injected_total"
        )

    hold = {"for_s": for_s, "resolve_for_s": resolve_for_s}
    rules = [
        page_partition_rule(resolve_for_s=resolve_for_s),
        ledger_conservation_rule(**hold),
        engine_restart_rule(resolve_for_s=resolve_for_s),
        CounterDeltaRule(
            "error-burst", lambda ctx: errors_5xx(),
            severity="ticket", resolve_for_s=resolve_for_s,
            series=("tpu_serve_requests_total",),
            description="5xx responses served",
        ),
        fault_injection_rule(resolve_for_s=resolve_for_s),
        CounterStallRule(**hold),
        QueueRunawayRule(max_depth=queue_max_depth, **hold),
    ]
    local = {
        "pages": pages, "ledger": ledger, "restarts": stat("restarts"),
        "faults_total": faults_total, "emitted": emitted,
        "inflight": inflight, "queued": stat("queued"),
    }
    # the rules close over nothing process-global; the caller passes
    # this dict as EvalContext.local on every tick
    for r in rules:
        r.local_defaults = local  # type: ignore[attr-defined]
    return rules


def engine_local_context(rules: list[Rule], now: float,
                         store=None) -> EvalContext:
    """Build the engine-local EvalContext from the ``local_defaults``
    the :func:`engine_tripwires` factory attached to its rules."""
    local: dict[str, Any] = {}
    for r in rules:
        local.update(getattr(r, "local_defaults", {}))
    return EvalContext(now=now, store=store, local=local)


def default_fleet_rules(trackers=None, *, queue_max_depth: float = 64.0,
                        ) -> list[Rule]:
    """The fleet-side default registry the monitor evaluates each
    scrape cycle: the SLO burn trackers migrated in as rules, plus
    target-down, restart-delta, latency drift, counter-stall, and
    queue-runaway over the scraped families."""
    rules: list[Rule] = [
        SLOBurnRule(t) for t in (trackers or [])
    ]
    rules += [
        target_down_rule(for_s=0.0, resolve_for_s=60.0),
        engine_restart_rule(resolve_for_s=60.0),
        EWMADriftRule(for_s=0.0, resolve_for_s=60.0, severity="ticket"),
        CounterStallRule(for_s=30.0, resolve_for_s=60.0),
        QueueRunawayRule(max_depth=queue_max_depth, for_s=30.0,
                         resolve_for_s=60.0, severity="ticket"),
    ]
    return rules


# -- the `get alerts` CLI face ------------------------------------------------


def fetch_alerts(target: str, timeout: float = 5.0) -> dict:
    """GET ``/debug/alerts`` from ``host:port`` (scheme/path optional,
    the fetch_flightrec normalization)."""
    t = target.strip()
    if "//" not in t:
        t = "http://" + t
    if not t.rstrip("/").endswith("/debug/alerts"):
        t = t.rstrip("/") + "/debug/alerts"
    with urllib.request.urlopen(t, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def render_alerts(payload: dict) -> str:
    """The operator summary of one ``/debug/alerts`` payload."""
    alerts = payload.get("alerts", [])
    summary = payload.get("summary", {})
    lines = [
        f"alerts — {summary.get('firing', 0)} firing, "
        f"{summary.get('pending', 0)} pending "
        f"({len(payload.get('rules', []))} rules registered)"
    ]
    for a in alerts:
        labels = ",".join(f"{k}={v}" for k, v in
                          sorted(a.get("labels", {}).items()))
        age = a.get("age_s")
        lines.append(
            f"  [{a.get('state', '?').upper():>8}]"
            f" {a.get('rule')}{'{' + labels + '}' if labels else ''}"
            f" severity={a.get('severity') or '-'}"
            f" value={a.get('value')}"
            + (f" for {age:.0f}s" if age is not None else "")
            + (" (silenced)" if a.get("silenced") else "")
            + (f" — {a['summary']}" if a.get("summary") else "")
        )
    if not alerts:
        lines.append("  (none active)")
    for s in payload.get("silences", []):
        matchers = ",".join(f"{k}={v}" for k, v in
                            sorted(s.get("matchers", {}).items()))
        lines.append(f"  silence {{{matchers}}} until={s.get('until')}"
                     + (f" — {s['comment']}" if s.get("comment") else ""))
    return "\n".join(lines) + "\n"
