"""End-to-end distributed tracing for the serve fleet.

PR 1's tracer (util/trace.py) follows a request *inside* one process
— ``GET /debug/trace/<run_id>`` renders the phase tree — but dies at
the process boundary: a fleet-routed request, or a future prefill/
decode KV handoff between tiers, cannot be followed across instances.
This module is the cross-process substrate:

* **W3C ``traceparent`` propagation** — :func:`parse_traceparent` /
  :func:`render_traceparent` speak the standard header
  (``00-<32hex trace>-<16hex span>-<2hex flags>``); the HTTP server
  extracts inbound context and echoes its own, the fleet aggregator
  injects outbound context on every scrape, and the contextvar scope
  (:func:`trace_scope` / :func:`current_trace`) carries the context
  across the handler → engine → SSE-producer thread hops.
* **Head + tail sampling** — the head decision is *deterministic in
  the trace id* (:func:`head_sampled`), so every instance touched by
  one trace agrees without coordination; tail capture
  (:meth:`TraceRuntime.should_export`) additionally keeps error,
  deadline-exceeded, and slow traces that head sampling missed.
* **Bounded background export** — :class:`SpanExporter` buffers span
  records in a bounded queue and delivers them from a daemon thread
  (JSONL file and/or OTLP-HTTP-JSON endpoint), so the scheduler and
  handler threads never block on sink I/O. Delivery runs behind the
  ``obs.trace_export`` fault site; failures drop the batch silently
  and count ``tpu_trace_spans_dropped_total``.
* **Stitching** — :func:`fetch_trace` / :func:`stitch_trace` pull
  ``/debug/trace/<trace_id>`` from every instance and merge the span
  trees plus the scheduler's segment spans (which carry *links* to
  every resident request's trace) into one cross-instance view with a
  :func:`critical_path` breakdown: queue vs admission-wait vs prefill
  vs decode-segments vs SSE stream, annotated with device-seconds and
  ledger token classes.

Everything here is stdlib-only and never raises into the caller's
request path — observability must not take the service down.

Env knobs (all read through util/envparse, bad values degrade):
``TPU_K8S_TRACE_SAMPLE``, ``TPU_K8S_TRACE_SLOW_S``,
``TPU_K8S_TRACE_EXPORT_PATH``, ``TPU_K8S_TRACE_EXPORT_URL``,
``TPU_K8S_TRACE_EXPORT_QUEUE``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import random
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass
from typing import Callable, Mapping

from tpu_kubernetes.obs.faults import FAULTS
from tpu_kubernetes.obs.metrics import REGISTRY
from tpu_kubernetes.util.envparse import env_float, env_int, env_str

TRACEPARENT = "traceparent"
_VERSION = "00"
FLAG_SAMPLED = 0x01

SPANS_EXPORTED = REGISTRY.counter(
    "tpu_trace_spans_exported_total",
    "span records delivered to an export sink (JSONL or OTLP)",
)
SPANS_DROPPED = REGISTRY.counter(
    "tpu_trace_spans_dropped_total",
    "span records dropped: queue overflow or export-sink failure",
)


# ---------------------------------------------------------------------------
# W3C trace context


@dataclass(frozen=True)
class TraceContext:
    """One hop of a W3C trace: the fleet-wide trace id, this process's
    span id (what downstream calls see as their parent), and the
    sampled flag carried in ``traceparent`` flags."""

    trace_id: str
    span_id: str
    sampled: bool = True

    def child(self, rng: random.Random | None = None) -> "TraceContext":
        """Same trace, fresh span id — what an outbound call sends."""
        return TraceContext(self.trace_id, new_span_id(rng), self.sampled)


def _rand_hex(nchars: int, rng: random.Random | None = None) -> str:
    r = rng if rng is not None else random
    value = r.getrandbits(nchars * 4)
    out = format(value, "0" + str(nchars) + "x")
    # the spec forbids the all-zero id; one nudge keeps it valid
    return out if any(c != "0" for c in out) else "1" + out[1:]


def new_trace_id(rng: random.Random | None = None) -> str:
    return _rand_hex(32, rng)


def new_span_id(rng: random.Random | None = None) -> str:
    return _rand_hex(16, rng)


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in "0123456789abcdef" for c in s)


def parse_traceparent(header: str | None) -> TraceContext | None:
    """``TraceContext`` from a ``traceparent`` header, or ``None`` for
    anything malformed (bad field count/length, non-hex, all-zero ids,
    unknown future version with a short header). Unknown versions with
    a well-formed prefix are accepted per spec."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if version == _VERSION and len(parts) != 4:
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id):
        return None
    if len(span_id) != 16 or not _is_hex(span_id):
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return TraceContext(
        trace_id=trace_id, span_id=span_id,
        sampled=bool(int(flags, 16) & FLAG_SAMPLED),
    )


def render_traceparent(ctx: TraceContext) -> str:
    flags = format(FLAG_SAMPLED if ctx.sampled else 0, "02x")
    return f"{_VERSION}-{ctx.trace_id}-{ctx.span_id}-{flags}"


def head_sampled(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling decision: the trace id's low 8 bytes
    as a uniform draw against ``rate``. Every instance that sees the
    same trace id reaches the same verdict with zero coordination."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        draw = int(trace_id[-16:], 16) / float(1 << 64)
    except (ValueError, TypeError):
        return False
    return draw < rate


# ---------------------------------------------------------------------------
# ambient context (contextvars ride the handler → producer-thread hop)

_CURRENT: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "tpu_k8s_trace_ctx", default=None,
)


@contextlib.contextmanager
def trace_scope(ctx: TraceContext | None):
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


def current_trace() -> TraceContext | None:
    return _CURRENT.get()


def current_trace_id() -> str:
    ctx = _CURRENT.get()
    return ctx.trace_id if ctx is not None else ""


def outbound_headers(headers: dict | None = None, *,
                     rng: random.Random | None = None,
                     sample: float = 1.0) -> dict:
    """Headers for an outbound HTTP call: the ambient trace context's
    child hop if one is active, else a fresh root (head-sampled at
    ``sample``) — so aggregator scrapes are traceable end to end."""
    out = dict(headers or {})
    ctx = current_trace()
    if ctx is None:
        trace_id = new_trace_id(rng)
        ctx = TraceContext(trace_id, new_span_id(rng),
                           head_sampled(trace_id, sample))
    else:
        ctx = ctx.child(rng)
    out[TRACEPARENT] = render_traceparent(ctx)
    return out


# ---------------------------------------------------------------------------
# configuration


@dataclass(frozen=True)
class TraceConfig:
    sample: float = 1.0        # head-sampling rate in [0, 1]
    slow_s: float = 1.0        # tail capture: wall latency threshold
    export_path: str = ""      # JSONL sink ("" = off)
    export_url: str = ""       # OTLP-HTTP-JSON sink ("" = off)
    queue_max: int = 2048      # exporter buffer bound (records)

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None) -> "TraceConfig":
        sample = env_float("TPU_K8S_TRACE_SAMPLE", 1.0, env=env)
        return cls(
            sample=min(1.0, max(0.0, sample)),
            slow_s=env_float("TPU_K8S_TRACE_SLOW_S", 1.0, env=env),
            export_path=env_str("TPU_K8S_TRACE_EXPORT_PATH", env=env),
            export_url=env_str("TPU_K8S_TRACE_EXPORT_URL", env=env),
            queue_max=max(1, env_int("TPU_K8S_TRACE_EXPORT_QUEUE", 2048,
                                     env=env)),
        )


# ---------------------------------------------------------------------------
# export


def span_export_record(span, trace_id: str, *, instance: str = "",
                       epoch_offset: float | None = None) -> dict:
    """One util/trace.py Span as a flat export record. Span clocks are
    monotonic; ``epoch_offset`` (default: computed now) rebases them to
    unix time so downstream tooling can order records across hosts."""
    off = (time.time() - time.monotonic()) if epoch_offset is None \
        else epoch_offset
    end = span.end if span.end is not None else time.monotonic()
    return {
        "trace": trace_id,
        "span": span.span_id or "",
        "parent": span.parent_id or "",
        "run": span.run_id or "",
        "name": span.name,
        "start_unix_nano": int((span.start + off) * 1e9),
        "end_unix_nano": int((end + off) * 1e9),
        "attrs": dict(span.meta),
        "instance": instance,
    }


def _otlp_payload(records: list[dict]) -> dict:
    spans = []
    for r in records:
        attrs = [
            {"key": k, "value": {"stringValue": str(v)}}
            for k, v in sorted(r.get("attrs", {}).items())
        ]
        spans.append({
            "traceId": r.get("trace", ""),
            "spanId": (r.get("span", "") or "0").ljust(16, "0")[:16],
            "parentSpanId": (r.get("parent", "") or "").ljust(16, "0")[:16]
            if r.get("parent") else "",
            "name": r.get("name", ""),
            "startTimeUnixNano": str(r.get("start_unix_nano", 0)),
            "endTimeUnixNano": str(r.get("end_unix_nano", 0)),
            "attributes": attrs,
        })
    return {"resourceSpans": [{"scopeSpans": [{"spans": spans}]}]}


class SpanExporter:
    """Bounded, non-blocking span delivery. ``submit`` appends to an
    in-memory buffer under the lock and returns immediately — overflow
    drops the *newest* records (and counts them) rather than blocking a
    request or scheduler thread. A daemon worker drains batches and
    delivers them OUTSIDE the lock; each delivery attempt runs behind
    the ``obs.trace_export`` fault site and any failure (injected or
    real) drops the batch silently."""

    def __init__(self, path: str = "", url: str = "",
                 queue_max: int = 2048, *, timeout_s: float = 2.0):
        self.path = path
        self.url = url
        self.timeout_s = timeout_s
        self._buf: deque[dict] = deque()
        self._max = max(1, int(queue_max))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        self._accepted = 0
        self._done = 0
        self._thread: threading.Thread | None = None
        if self.enabled:
            self._thread = threading.Thread(
                target=self._worker, name="trace-export", daemon=True,
            )
            self._thread.start()

    @property
    def enabled(self) -> bool:
        return bool(self.path or self.url)

    def submit(self, records: list[dict]) -> int:
        """Queue records for delivery; returns how many were accepted.
        Never blocks, never raises."""
        if not records or not self.enabled:
            return 0
        with self._cv:
            if self._closed:
                return 0
            room = self._max - len(self._buf)
            accepted = records[:max(0, room)]
            overflow = len(records) - len(accepted)
            self._buf.extend(accepted)
            self._accepted += len(accepted)
            self._cv.notify_all()
        if overflow:
            SPANS_DROPPED.inc(overflow)
        return len(accepted)

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Wait until every accepted record has been attempted (test
        hook — production callers never wait on the exporter)."""
        with self._cv:
            return self._cv.wait_for(
                lambda: self._done >= self._accepted, timeout=timeout_s,
            )

    def close(self, timeout_s: float = 2.0) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    # -- worker side ----------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._buf and not self._closed:
                    self._cv.wait(timeout=0.5)
                batch = list(self._buf)
                self._buf.clear()
                closed = self._closed
            if batch:
                ok = self._deliver(batch)
                # count BEFORE marking done: a flush() observer must see
                # the batch's counters the moment the wait returns
                (SPANS_EXPORTED if ok else SPANS_DROPPED).inc(len(batch))
                with self._cv:
                    self._done += len(batch)
                    self._cv.notify_all()
            if closed and not batch:
                return

    def _deliver(self, batch: list[dict]) -> bool:
        """One delivery attempt for a batch — sink I/O happens here, on
        the worker thread, outside the lock. Returns False (drop) on
        any failure; export must never take the service down."""
        try:
            FAULTS.fire("obs.trace_export")
            if self.path:
                lines = "".join(
                    json.dumps(r, sort_keys=True) + "\n" for r in batch
                )
                with open(self.path, "a", encoding="utf-8") as fh:
                    fh.write(lines)
            if self.url:
                body = json.dumps(_otlp_payload(batch)).encode("utf-8")
                req = urllib.request.Request(
                    self.url, data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=self.timeout_s):
                    pass
            return True
        except Exception:
            return False


# ---------------------------------------------------------------------------
# per-process runtime policy (the serve server owns one)


class TraceRuntime:
    """The server-side policy bundle: extract/mint context on inbound
    requests, decide head+tail export, and hand finished requests'
    spans to the bounded exporter. RNG and clock are injectable so
    sampling decisions are deterministic under test."""

    def __init__(self, config: TraceConfig | None = None, *,
                 rng: random.Random | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 exporter: SpanExporter | None = None):
        self.config = config if config is not None else TraceConfig()
        self.rng = rng if rng is not None else random.Random()
        self.clock = clock
        self.exporter = exporter if exporter is not None else SpanExporter(
            path=self.config.export_path, url=self.config.export_url,
            queue_max=self.config.queue_max,
        )

    def extract(self, header: str | None) -> TraceContext:
        """Inbound context: continue the caller's trace (their sampled
        flag wins — the fleet honors one coordinated head decision) or
        mint a fresh root, head-sampled deterministically by rate."""
        parsed = parse_traceparent(header)
        if parsed is not None:
            return TraceContext(
                parsed.trace_id, new_span_id(self.rng), parsed.sampled,
            )
        trace_id = new_trace_id(self.rng)
        return TraceContext(
            trace_id, new_span_id(self.rng),
            head_sampled(trace_id, self.config.sample),
        )

    def should_export(self, ctx: TraceContext | None, *, code: int = 0,
                      wall_s: float = 0.0) -> bool:
        """Head decision, plus tail capture: server errors (5xx —
        includes 504 deadline-exceeded), overload rejections (429), and
        slow requests are kept even when head sampling said no."""
        if ctx is None:
            return False
        if ctx.sampled:
            return True
        return code >= 500 or code == 429 or wall_s >= self.config.slow_s

    def finish_request(self, tracer, run_id: str,
                       ctx: TraceContext | None, *, code: int = 0,
                       wall_s: float = 0.0, instance: str = "") -> int:
        """Export one finished request's spans (plus the scheduler
        segment spans linked to its trace) if sampling keeps it.
        Returns records submitted; never raises."""
        try:
            if not self.exporter.enabled:
                return 0
            if not self.should_export(ctx, code=code, wall_s=wall_s):
                return 0
            off = time.time() - time.monotonic()
            records = []
            for s in tracer.spans:
                mine = s.run_id == run_id
                linked = (not mine and s.meta.get("links")
                          and ctx.trace_id in s.meta["links"])
                if mine or linked:
                    records.append(span_export_record(
                        s, ctx.trace_id, instance=instance,
                        epoch_offset=off,
                    ))
            return self.exporter.submit(records)
        except Exception:
            return 0

    def close(self) -> None:
        self.exporter.close()


# ---------------------------------------------------------------------------
# cross-instance fetch + stitch (the `get trace` surface)


def trace_payload(spans, trace_id: str) -> dict:
    """Everything one process knows about a trace id: the span trees of
    every run whose root carried ``meta.trace == trace_id``, plus the
    scheduler segment spans whose ``meta.links`` include it. This is
    what ``GET /debug/trace/<trace_id>`` serves when the id is not a
    local run id."""
    from tpu_kubernetes.util.trace import span_tree

    run_ids = sorted({
        s.run_id for s in spans
        if s.run_id and s.meta.get("trace") == trace_id
    })
    trees: list[dict] = []
    for rid in run_ids:
        trees.extend(span_tree(spans, rid))
    segments = [
        {
            "name": s.name,
            "seconds": round(s.seconds, 6),
            "meta": dict(s.meta),
        }
        for s in spans
        if s.name == "segment"
        and trace_id in (s.meta.get("links") or ())
    ]
    return {
        "trace": trace_id, "runs": run_ids,
        "spans": trees, "segments": segments,
    }


def fetch_trace(target: str, trace_id: str, timeout: float = 5.0) -> dict:
    """GET one instance's view of a trace. ``target`` is a host:port or
    URL, normalized the same way fetch_flightrec normalizes."""
    t = target.strip()
    if "//" not in t:
        t = "http://" + t
    t = t.rstrip("/")
    if not t.endswith("/debug/trace/" + trace_id):
        t = t + "/debug/trace/" + trace_id
    with urllib.request.urlopen(t, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def stitch_trace(trace_id: str, payloads: Mapping[str, dict]) -> dict:
    """Merge per-instance ``/debug/trace`` payloads into one
    cross-instance view keyed by instance, with the critical-path
    breakdown computed over all of it."""
    instances: dict[str, dict] = {}
    for instance in sorted(payloads):
        p = payloads[instance] or {}
        instances[instance] = {
            "spans": list(p.get("spans") or []),
            "segments": list(p.get("segments") or []),
            "runs": list(p.get("runs") or []),
        }
    stitched = {"trace": trace_id, "instances": instances}
    stitched["critical_path"] = critical_path(stitched)
    return stitched


def _walk(nodes, fn, depth=0):
    for n in nodes:
        fn(n, depth)
        _walk(n.get("children") or [], fn, depth + 1)


def critical_path(stitched: dict) -> dict:
    """Phase breakdown of a stitched trace. ``wall_s`` is the longest
    ``request`` root (the client-facing instance); ``phases`` sums that
    request's direct children by span name — queue, prefill, batch
    (the continuous decode segments), decode / stream — so the phase
    durations account for the wall latency within scheduling noise.
    Admission wait, device-seconds, and ledger token classes ride the
    span meta and are surfaced alongside."""
    best: dict | None = None
    device_s = 0.0
    segments = 0
    for inst in (stitched.get("instances") or {}).values():
        for seg in inst.get("segments") or []:
            segments += 1
            try:
                device_s += float((seg.get("meta") or {}).get("device_s", 0))
            except (TypeError, ValueError):
                pass
        for root in inst.get("spans") or []:
            if root.get("name") != "request":
                continue
            if best is None or root.get("seconds", 0) > best.get("seconds", 0):
                best = root
    out: dict = {
        "wall_s": 0.0, "phases": {}, "accounted_s": 0.0,
        "admission_wait_s": 0.0, "device_s": round(device_s, 6),
        "segments": segments, "tokens": {},
    }
    if best is None:
        return out
    out["wall_s"] = round(float(best.get("seconds", 0.0)), 6)
    phases: dict[str, float] = {}
    for child in best.get("children") or []:
        name = child.get("name", "?")
        phases[name] = phases.get(name, 0.0) + float(child.get("seconds", 0))
        meta = child.get("meta") or {}
        try:
            out["admission_wait_s"] += float(meta.get("admission_wait_s", 0))
        except (TypeError, ValueError):
            pass
        if isinstance(meta.get("tokens"), dict):
            for k, v in meta["tokens"].items():
                try:
                    out["tokens"][k] = out["tokens"].get(k, 0) + int(v)
                except (TypeError, ValueError):
                    pass
    out["phases"] = {k: round(v, 6) for k, v in sorted(phases.items())}
    out["accounted_s"] = round(sum(phases.values()), 6)
    out["admission_wait_s"] = round(out["admission_wait_s"], 6)
    return out


def render_trace(stitched: dict) -> str:
    """Human-readable stitched trace for ``get trace`` (non-JSON)."""
    lines: list[str] = []
    cp = stitched.get("critical_path") or {}
    instances = stitched.get("instances") or {}
    lines.append(
        f"trace {stitched.get('trace', '?')}  "
        f"({len(instances)} instance(s), wall {cp.get('wall_s', 0):.3f}s)"
    )
    for instance in sorted(instances):
        inst = instances[instance]
        spans = inst.get("spans") or []
        segs = inst.get("segments") or []
        lines.append(f"  instance {instance}  "
                     f"({len(spans)} root span(s), {len(segs)} segment(s))")

        def emit(node, depth):
            meta = node.get("meta") or {}
            keys = ("endpoint", "trace", "admission_wait_s", "device_s")
            extra = " ".join(
                f"{k}={meta[k]}" for k in keys if k in meta
            )
            lines.append(
                "    " + "  " * depth
                + f"{node.get('name', '?')} ({node.get('seconds', 0):.3f}s)"
                + (f"  {extra}" if extra else "")
            )

        _walk(spans, emit)
    if cp.get("phases"):
        parts = " · ".join(
            f"{name} {secs:.3f}s" for name, secs in cp["phases"].items()
        )
        lines.append(
            f"  critical path: {parts}  "
            f"(accounted {cp.get('accounted_s', 0):.3f}s "
            f"of {cp.get('wall_s', 0):.3f}s wall; "
            f"admission wait {cp.get('admission_wait_s', 0):.3f}s, "
            f"device {cp.get('device_s', 0):.3f}s, "
            f"{cp.get('segments', 0)} segment(s))"
        )
    return "\n".join(lines) + "\n"
