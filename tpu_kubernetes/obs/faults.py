"""Deterministic fault-injection harness for the failure paths.

The resilience layer (serve/resilience.py, the engine watchdog, the
terraform retry loop, the fleet-scrape backoff) exists to bound what
happens when something breaks — but failure paths that only fire in
production are failure paths that have never run. This module makes
every interesting failure *injectable, deterministic, and cheap*:

* **Named sites.** Code that can fail threads one call through
  ``FAULTS.fire("<site>")`` at the spot where the real failure would
  surface (a prefill OOM, a dead scrape target, a terraform network
  blip). The site vocabulary is closed (:data:`SITES`), so chaos tests
  can enumerate every registered site and a typo'd spec fails loudly
  instead of silently arming nothing.
* **Seeded probability.** ``TPU_K8S_FAULTS="site:prob:seed,…"`` arms a
  site with its own ``random.Random(seed)`` stream — the i-th call to a
  site faults or not as a pure function of (seed, i), so a chaos run is
  exactly reproducible and "prob=0.5" tests assert real interleavings,
  not flakes.
* **Zero cost when off.** ``fire`` is a dict miss when nothing is armed
  — the hot serving path pays one attribute load and one ``if``.

Injected faults raise :class:`FaultError` (a ``RuntimeError``), which
deliberately rides the same handling as a real failure at that site:
nothing anywhere catches FaultError specially, so what the chaos suite
proves about injected faults holds for organic ones.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading

from tpu_kubernetes.obs import REGISTRY

ENV_VAR = "TPU_K8S_FAULTS"

# the closed site vocabulary — one name per instrumented failure point.
# Adding a site = add it here AND thread fire() through the code path;
# chaos tests iterate this set, and the static contracts pass
# (`tpu-kubernetes analyze`, docs/guide/static-analysis.md) fails CI on
# a site that exists only here (fault-site-unfired) or only in code
# (fault-site-unknown) — no run required to catch the drift.
SITES = frozenset({
    "serve.prefill",        # prefill/prefill_resume (solo + slot admission)
    "serve.slot_insert",    # _ContinuousEngine._insert (cache graft)
    "serve.segment",        # _ContinuousEngine._run_segment (decode step)
    "serve.spec_verify",    # _ContinuousEngine speculative verify round
    "serve.shard_segment",  # _run_segment under SERVE_MESH (sharded program)
    "serve.prefix_insert",  # prefix KV-cache store insert (best-effort)
    "serve.page_alloc",     # PagePool.allocate (paged admission/top-up)
    "fleet.scrape",         # FleetAggregator per-target fetch
    "fleet.remediate",      # FleetController actuation (obs/controller.py)
    "shell.terraform",      # TerraformExecutor subprocess run
    "obs.alert_sink",       # alert notification delivery (obs/alerts.py)
    "obs.trace_export",     # span exporter delivery (obs/tracing.py)
})

FAULTS_INJECTED = REGISTRY.counter(
    "tpu_k8s_faults_injected_total",
    "faults injected by the TPU_K8S_FAULTS harness, by site "
    "(nonzero outside a chaos run means the env leaked into prod)",
    labelnames=("site",),
)


class FaultError(RuntimeError):
    """An injected fault — handled exactly like the organic failure at
    the same site (nothing catches this class specially by design)."""


class _Arm:
    __slots__ = ("prob", "rng")

    def __init__(self, prob: float, seed: int):
        self.prob = prob
        self.rng = random.Random(seed)


class FaultInjector:
    """Process-wide injector; armed from ``TPU_K8S_FAULTS`` at import
    and re-armable from tests (:meth:`configure` / :func:`injected`)."""

    def __init__(self, spec: str | None = None):
        self._lock = threading.Lock()
        self._arms: dict[str, _Arm] = {}
        if spec:
            self.configure(spec)

    def configure(self, spec: str | None) -> None:
        """Arm sites from a ``site:prob[:seed],…`` spec (seed defaults
        to 0). Replaces the whole previous arming; unknown sites and
        out-of-range probabilities are loud errors — a chaos run that
        silently tests nothing is worse than no run."""
        arms: dict[str, _Arm] = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) not in (2, 3):
                raise ValueError(
                    f"{ENV_VAR}: {part!r} is not site:prob[:seed]"
                )
            site = fields[0].strip()
            if site not in SITES:
                raise ValueError(
                    f"{ENV_VAR}: unknown fault site {site!r} "
                    f"(registered: {sorted(SITES)})"
                )
            prob = float(fields[1])
            if not 0.0 <= prob <= 1.0:
                raise ValueError(
                    f"{ENV_VAR}: {site} probability {prob} not in [0, 1]"
                )
            seed = int(fields[2]) if len(fields) == 3 else 0
            arms[site] = _Arm(prob, seed)
        with self._lock:
            self._arms = arms

    def clear(self) -> None:
        with self._lock:
            self._arms = {}

    def armed(self, site: str | None = None) -> bool:
        with self._lock:
            return bool(self._arms) if site is None else site in self._arms

    def fire(self, site: str) -> None:
        """Raise :class:`FaultError` if ``site`` is armed and its seeded
        stream says this call faults. No-op (one dict check) otherwise."""
        if not self._arms:       # fast path: nothing armed anywhere
            return
        with self._lock:
            arm = self._arms.get(site)
            if arm is None:
                return
            hit = arm.prob > 0.0 and arm.rng.random() < arm.prob
        if hit:
            FAULTS_INJECTED.labels(site).inc()
            raise FaultError(f"injected fault at {site}")


# the process-wide injector: serve/fleet/shell code fires through this
FAULTS = FaultInjector(os.environ.get(ENV_VAR))


@contextlib.contextmanager
def injected(spec: str):
    """Test helper: arm ``spec`` for the block, always disarm after."""
    FAULTS.configure(spec)
    try:
        yield FAULTS
    finally:
        FAULTS.clear()
