"""Incident correlation: temporally overlapping alerts → one bundle.

An alert stream answers "what is wrong right now"; a postmortem needs
"what was wrong TOGETHER, and what did the system look like while it
was". The :class:`IncidentCorrelator` watches every
:class:`~tpu_kubernetes.obs.alerts.AlertManager` evaluation: when any
alert fires while no incident is open, it opens one; every alert that
fires before the incident closes joins it (temporal overlap IS the
correlation — a page-alloc fault storm, the restart tripwire it trips,
and the latency drift it causes land in one bundle, not three); when
nothing has been firing for ``close_after_s``, the incident closes.

Each incident is one JSON bundle on disk — atomic (tmp + rename),
redacted (obs/flightrec.py's ``redact``, prompts never persist),
retention-pruned (keep-newest, a crash-looping fleet cannot fill a
disk), and rewritten in place as the incident evolves so the file is
always the current truth. A bundle carries everything a postmortem
opens four terminals for today:

* the member alerts (fingerprint, rule, lifecycle timestamps, last
  value/summary);
* cross-refs to every flight-recorder dump written during (or just
  before) the incident — opening an incident also TRIGGERS a dump, so
  the cross-ref always exists, and dumps taken while the incident is
  open carry the ``incident_id`` back (tested in both directions);
* the per-site injected-fault counters at close;
* the token ledger snapshot with a goodput-loss breakdown (which
  settlement classes ate the tokens), conservation-checkable offline;
* the last-N history-store samples for every series the member rules
  implicate — the data the alerts fired on.

Structured events (obs/events.py) mark ``incident_open`` /
``incident_close`` with the member fingerprints as correlation ids.
Everything is clock-injectable (``now=``) and the correlator never
raises into its caller — it rides the same evaluation loops that serve
traffic.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque

from tpu_kubernetes.obs import REGISTRY
from tpu_kubernetes.obs.flightrec import redact
from tpu_kubernetes.obs.ledger import LEDGER, USEFUL

SCHEMA = "tpu-k8s-incident/1"

DEFAULT_DIR = os.path.join("runs", "incidents")
DEFAULT_KEEP = 32
DEFAULT_CLOSE_AFTER_S = 60.0

# flightrec dumps written this long before an incident opens are
# adopted into it — the dump that DOCUMENTS a failure usually lands a
# tick before the tripwire that announces it
ADOPT_WINDOW_S = 60.0

# last-N history samples per implicated series in a bundle
TAIL_SAMPLES = 32


def _int_env(env: dict, key: str, default: int) -> int:
    from tpu_kubernetes.util.envparse import env_int

    return env_int(key, default, env=env)


def _float_env(env: dict, key: str, default: float) -> float:
    from tpu_kubernetes.util.envparse import env_float

    return env_float(key, default, env=env)


class IncidentCorrelator:
    """Groups firing alerts into incidents and persists the bundles.

    Thread-safe via an RLock — opening an incident triggers a
    flight-recorder dump, and that dump re-enters the correlator on the
    same thread (``current_incident_id`` for the payload,
    ``note_flightrec_dump`` for the cross-ref)."""

    def __init__(self, directory: str = DEFAULT_DIR,
                 keep: int = DEFAULT_KEEP,
                 close_after_s: float = DEFAULT_CLOSE_AFTER_S,
                 registry=None, ledger=None, store=None, flightrec=None,
                 tail_n: int = TAIL_SAMPLES):
        self.directory = directory
        self.keep = max(1, int(keep))
        self.close_after_s = max(0.0, float(close_after_s))
        self._registry = REGISTRY if registry is None else registry
        self._ledger = LEDGER if ledger is None else ledger
        self._store = store
        self._flightrec = flightrec
        self._tail_n = max(1, int(tail_n))
        self._lock = threading.RLock()
        self._open: dict | None = None      # the live incident record
        self._clear_since: float | None = None
        self._counts = {"opened": 0, "closed": 0, "write_failures": 0}
        # (ts, path) of recent dumps with no incident to attach to yet
        self._recent_dumps: deque[tuple[float, str]] = deque(maxlen=16)

    @classmethod
    def from_env(cls, env: dict, **kw) -> "IncidentCorrelator":
        return cls(
            directory=env.get("TPU_K8S_INCIDENTS_DIR", "") or DEFAULT_DIR,
            keep=_int_env(env, "TPU_K8S_INCIDENTS_KEEP", DEFAULT_KEEP),
            close_after_s=_float_env(env, "TPU_K8S_INCIDENTS_CLOSE_S",
                                     DEFAULT_CLOSE_AFTER_S),
            **kw,
        )

    # -- the alert-manager feed --------------------------------------------

    def observe(self, alerts: list[dict], now: float | None = None) -> None:
        """One evaluation cycle's alert list. Never raises."""
        try:
            self._observe(alerts, time.time() if now is None else now)
        except Exception:  # noqa: BLE001 — correlation must not take
            pass           # down the loop that evaluated the alerts

    def _observe(self, alerts: list[dict], now: float) -> None:
        firing = [a for a in alerts if a.get("state") == "firing"]
        with self._lock:
            if self._open is None:
                if not firing:
                    return
                self._open_incident(firing, now)
                return
            inc = self._open
            if firing:
                self._clear_since = None
                changed = False
                for a in firing:
                    fp = a.get("fingerprint", "")
                    prev = inc["alerts"].get(fp)
                    inc["alerts"][fp] = self._member(a, prev, now)
                    if prev is None:
                        changed = True
                if changed:
                    inc["updated_at"] = now
                    self._write(inc, now)
                return
            # nothing firing: hold, then close
            if self._clear_since is None:
                self._clear_since = now
            if now - self._clear_since >= self.close_after_s:
                self._close_incident(now)

    def _member(self, alert: dict, prev: dict | None, now: float) -> dict:
        m = {
            "fingerprint": alert.get("fingerprint"),
            "rule": alert.get("rule"),
            "kind": alert.get("kind"),
            "labels": alert.get("labels", {}),
            "severity": alert.get("severity"),
            "summary": alert.get("summary"),
            "value": alert.get("value"),
            "series": alert.get("series", []),
            "first_seen": prev.get("first_seen") if prev else now,
            "last_seen": now,
            "firing_since": alert.get("firing_since"),
        }
        return m

    def _open_incident(self, firing: list[dict], now: float) -> None:
        fps = sorted(a.get("fingerprint", "") for a in firing)
        incident_id = f"{int(now * 1e3):x}-{fps[0][:6]}"
        inc = {
            "incident_id": incident_id,
            "status": "open",
            "opened_at": now,
            "updated_at": now,
            "closed_at": None,
            "alerts": {},
            "flightrec_dumps": [],
            # the controller's audit trail: every action record emitted
            # while this incident is open (obs/controller.py) — the
            # bundle reads detect → decide → actuate → resolve
            "actions": [],
        }
        for a in firing:
            inc["alerts"][a.get("fingerprint", "")] = self._member(
                a, None, now
            )
        # adopt the dumps that immediately preceded the tripwire — the
        # postmortem usually lands before the page does
        for ts, path in self._recent_dumps:
            if now - ts <= ADOPT_WINDOW_S:
                inc["flightrec_dumps"].append(path)
        self._recent_dumps.clear()
        self._open = inc
        self._clear_since = None
        self._counts["opened"] += 1
        from tpu_kubernetes.obs import events

        events.emit("incident_open", incident_id=incident_id,
                    fingerprints=fps,
                    rules=sorted({a.get("rule") for a in firing}))
        # every incident gets at least one flight-recorder cross-ref:
        # the dump re-enters note_flightrec_dump under our RLock
        if self._flightrec is not None:
            try:
                self._flightrec.dump(f"incident-{incident_id}")
            except Exception:  # noqa: BLE001 — recorder is best-effort
                pass
        self._write(inc, now)

    def _close_incident(self, now: float) -> None:
        inc = self._open
        inc["status"] = "closed"
        inc["closed_at"] = now
        inc["updated_at"] = now
        self._open = None
        self._clear_since = None
        self._counts["closed"] += 1
        self._write(inc, now)
        from tpu_kubernetes.obs import events

        events.emit("incident_close", incident_id=inc["incident_id"],
                    fingerprints=sorted(inc["alerts"]),
                    duration_s=round(now - inc["opened_at"], 3),
                    dumps=len(inc["flightrec_dumps"]),
                    actions=len(inc.get("actions", [])))

    # -- the flight-recorder cross-ref -------------------------------------

    def current_incident_id(self) -> str | None:
        """The open incident's id, if any — stamped into flight-recorder
        dumps taken while an incident is open."""
        with self._lock:
            return self._open["incident_id"] if self._open else None

    def note_flightrec_dump(self, path: str,
                            now: float | None = None) -> None:
        """A dump landed: attach it to the open incident, or remember it
        briefly so an incident opening moments later adopts it."""
        now = time.time() if now is None else now
        with self._lock:
            if self._open is not None:
                if path not in self._open["flightrec_dumps"]:
                    self._open["flightrec_dumps"].append(path)
            else:
                self._recent_dumps.append((now, path))

    # -- the fleet-controller cross-ref -------------------------------------

    def note_action(self, action: dict, now: float | None = None) -> None:
        """A controller action record (obs/controller.py) landed while
        this incident is open: append it to the bundle's audit trail
        and rewrite. Actions with no open incident only live in the
        action ledger — the standalone audit trail."""
        now = time.time() if now is None else now
        with self._lock:
            if self._open is None:
                return
            inc = self._open
            inc.setdefault("actions", []).append(dict(action))
            inc["updated_at"] = now
            self._write(inc, now)

    # -- the bundle ---------------------------------------------------------

    def _fault_totals(self) -> dict[str, float]:
        fam = self._registry.snapshot(
            prefix="tpu_k8s_faults_injected_total"
        ).get("tpu_k8s_faults_injected_total")
        if not fam:
            return {}
        return {
            s["labels"].get("site", ""): s["value"]
            for s in fam["samples"]
        }

    def _ledger_block(self) -> dict:
        try:
            snap = self._ledger.snapshot(timeline=16)
        except Exception:  # noqa: BLE001
            return {}
        classes = snap.get("classes", {})
        emitted = snap.get("emitted", 0)
        # the goodput-loss breakdown: which settlement classes ate the
        # non-useful tokens (what the incident COST, in tokens)
        loss = {
            cls: n for cls, n in classes.items()
            if cls != USEFUL and n
        }
        snap["loss_breakdown"] = {
            "lost_tokens": sum(loss.values()),
            "lost_fraction": (round(sum(loss.values()) / emitted, 6)
                              if emitted else None),
            "by_class": loss,
        }
        return snap

    def _history_block(self, inc: dict) -> dict:
        if self._store is None:
            return {}
        series: set[str] = set()
        for m in inc["alerts"].values():
            series.update(m.get("series") or [])
        out = {}
        for name in sorted(series):
            try:
                if self._store.has_samples(name):
                    out[name] = self._store.tail(name, self._tail_n)
            except Exception:  # noqa: BLE001
                continue
        return out

    def _payload(self, inc: dict) -> dict:
        payload = {
            "schema": SCHEMA,
            "incident_id": inc["incident_id"],
            "status": inc["status"],
            "opened_at": inc["opened_at"],
            "updated_at": inc["updated_at"],
            "closed_at": inc["closed_at"],
            "alerts": inc["alerts"],
            "rules": sorted({m.get("rule") for m in
                             inc["alerts"].values()}),
            "flightrec_dumps": list(inc["flightrec_dumps"]),
            "actions": list(inc.get("actions", [])),
            "faults_injected": self._fault_totals(),
            "ledger": self._ledger_block(),
            "history": self._history_block(inc),
        }
        return redact(payload)

    def _write(self, inc: dict, now: float) -> None:
        """Rewrite the incident's bundle atomically; prune; count
        failures instead of raising."""
        try:
            payload = self._payload(inc)
            os.makedirs(self.directory, exist_ok=True)
            name = (f"incident-{int(inc['opened_at'] * 1e3)}-"
                    f"{inc['incident_id']}.json")
            path = os.path.join(self.directory, name)
            fd, tmp = tempfile.mkstemp(
                prefix=".incident-", suffix=".tmp", dir=self.directory
            )
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(payload, f, sort_keys=True, default=str)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self._prune()
        except Exception:  # noqa: BLE001 — the bundle writer must not
            self._counts["write_failures"] += 1  # crash the patient

    def _prune(self) -> None:
        names = sorted(
            n for n in os.listdir(self.directory)
            if n.startswith("incident-") and n.endswith(".json")
        )
        for stale in names[:-self.keep]:
            try:
                os.remove(os.path.join(self.directory, stale))
            except OSError:
                pass

    def counts(self) -> dict:
        with self._lock:
            return dict(self._counts)


# -- the `get incidents` CLI face --------------------------------------------


def list_incidents(directory: str = DEFAULT_DIR) -> list[dict]:
    """Parse every incident bundle in ``directory``, newest first.
    Unparseable files are skipped (a half-written tmp never matches the
    glob; a corrupt bundle should not hide its siblings)."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in sorted(os.listdir(directory), reverse=True):
        if not (name.startswith("incident-") and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        payload["_path"] = path
        out.append(payload)
    return out


def render_incidents(payloads: list[dict]) -> str:
    """The operator summary of a directory of incident bundles."""
    if not payloads:
        return "no incident bundles found\n"
    lines = []
    for p in payloads:
        alerts = p.get("alerts", {})
        opened = p.get("opened_at")
        closed = p.get("closed_at")
        dur = (f"{closed - opened:.0f}s" if opened and closed
               else "open")
        lines.append(
            f"incident {p.get('incident_id')} "
            f"[{p.get('status', '?').upper()}] — "
            f"{len(alerts)} alerts, duration {dur}, "
            f"{len(p.get('flightrec_dumps', []))} flightrec dumps"
        )
        for fp, m in sorted(alerts.items()):
            labels = ",".join(f"{k}={v}" for k, v in
                              sorted((m.get("labels") or {}).items()))
            lines.append(
                f"    {fp} {m.get('rule')}"
                f"{'{' + labels + '}' if labels else ''}"
                f" severity={m.get('severity') or '-'}"
                + (f" — {m['summary']}" if m.get("summary") else "")
            )
        for a in p.get("actions", []):
            lines.append(
                f"    action {a.get('id') or '-'}: {a.get('kind')}"
                f" -> {a.get('outcome')}"
                + (f" target={a['target']}" if a.get("target") else "")
                + (f" — {a['reason']}" if a.get("reason") else "")
                + (f" [{a['error']}]" if a.get("error") else "")
            )
        ledger = p.get("ledger", {})
        loss = ledger.get("loss_breakdown", {})
        if loss.get("lost_tokens"):
            by = ", ".join(f"{c}={n}" for c, n in
                           sorted(loss.get("by_class", {}).items()))
            frac = loss.get("lost_fraction")
            lines.append(
                f"    goodput loss: {loss['lost_tokens']} tokens"
                + (f" ({frac:.1%})" if isinstance(frac, float) else "")
                + (f" — {by}" if by else "")
            )
    return "\n".join(lines) + "\n"
