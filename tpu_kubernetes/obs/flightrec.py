"""The serve-engine flight recorder: a black box for the slot engine.

Watchdog cold-restarts, drains, and chaos faults (PRs 6-9) used to
leave only log lines behind — no capture of what the continuous-
batching engine was actually doing when it died. This module is the
black box: the engine feeds it one bounded snapshot per decode segment
(slot occupancy, live rows, page-pool partition, ledger deltas,
admission/reap/preemption counts, faults injected so far, recent span
ids), and on every way a serving process can end work — watchdog
restart, hard-fail, drain, SIGTERM — it atomically dumps a redacted
JSON postmortem under ``runs/``-style retention. One file tells the
whole story: the segment ring, the token-ledger snapshot (whose
conservation invariant a postmortem can check offline), the SLO alerts
that were pending or firing, the recent history-store samples for the
serve series, and the fault counters.

Operational stance (the obs/events.py contract): the recorder NEVER
raises — a broken disk must not take down serving, and a postmortem
writer that crashes the patient is worse than no postmortem. Dumps are
atomic (tmp + rename) and pruned newest-kept like runs/ reports
(``TPU_K8S_FLIGHTREC_KEEP``), so a crash-looping engine cannot fill a
disk with its own obituaries.

On-demand access: ``GET /debug/flightrec`` on the serve port and
``tpu-kubernetes get flightrec`` return the same payload live, without
writing a file — the pre-incident view of the same black box.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import urllib.request
from collections import deque
from typing import Any

from tpu_kubernetes.obs import REGISTRY, expfmt
from tpu_kubernetes.obs.ledger import LEDGER
from tpu_kubernetes.obs.tsdb import TSDB
from tpu_kubernetes.util.trace import TRACER

SCHEMA = "tpu-k8s-flightrec/1"

DEFAULT_DIR = os.path.join("runs", "flightrec")
DEFAULT_KEEP = 8
DEFAULT_SEGMENTS = 256

# how often (seconds) a segment feed also refreshes the local SLO /
# history view from the registry — parsing the exposition every segment
# would tax the decode loop for telemetry nobody reads that fast
OBSERVE_EVERY_S = 2.0

# last-N raw samples per serve series embedded in a dump
TAIL_SAMPLES = 32
TAIL_SERIES = (
    "tpu_serve_requests_total",
    "tpu_serve_tokens_emitted_total",
    "tpu_serve_inflight_requests",
    "tpu_serve_kv_pages",
    "tpu_serve_slot_occupancy",
)

# payload keys whose values never belong in a postmortem — prompts and
# generations are user data; a flight recorder records the airplane,
# not the passengers' conversations
REDACT_KEYS = frozenset({
    "prompt", "prompts", "text", "completion", "messages", "tokens",
    "token_ids", "ids", "content",
})
MAX_STR = 512


def redact(obj: Any) -> Any:
    """Recursively strip user-content keys (replaced by a length
    marker) and truncate oversized strings — applied to every payload
    before it leaves the process."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if isinstance(k, str) and k.lower() in REDACT_KEYS:
                n = len(v) if isinstance(v, (str, list, tuple, dict)) else 1
                out[k] = f"<redacted:{n}>"
            else:
                out[k] = redact(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [redact(v) for v in obj]
    if isinstance(obj, str) and len(obj) > MAX_STR:
        return obj[:MAX_STR] + f"…<truncated {len(obj) - MAX_STR}>"
    return obj


def _int_env(env: dict, key: str, default: int) -> int:
    from tpu_kubernetes.util.envparse import env_int

    return env_int(key, default, env=env)


class FlightRecorder:
    """The bounded black box one serving process feeds.

    Thread-safe: the engine scheduler records segments while HTTP
    handler threads snapshot and failure paths dump.
    """

    def __init__(self, directory: str = DEFAULT_DIR, keep: int = DEFAULT_KEEP,
                 capacity: int = DEFAULT_SEGMENTS, registry=None,
                 ledger=None, tracer=None, slos=None, incidents=None):
        self.directory = directory
        self.keep = max(1, int(keep))
        # the incident correlator (obs/incidents.py), when one is wired:
        # dumps taken during an open incident carry its id, and every
        # dump path is reported back for the bundle's cross-ref list
        self.incidents = incidents
        self._registry = REGISTRY if registry is None else registry
        self._ledger = LEDGER if ledger is None else ledger
        self._tracer = TRACER if tracer is None else tracer
        self._segments: deque[dict] = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._counts = {"segments": 0, "dumps": 0, "dump_failures": 0}
        self._last_observe = 0.0
        # the recorder's own retained history + objectives, observed
        # from THIS process's registry (no HTTP hop: render → parse →
        # the same FleetSnapshot queries the fleet monitor runs)
        self.store = TSDB(max_bytes=2 << 20)
        if slos is None:
            from tpu_kubernetes.obs.slo import default_slos
            slos = default_slos(store=self.store)
        self._slos = slos

    @classmethod
    def from_env(cls, env: dict) -> "FlightRecorder":
        return cls(
            directory=env.get("TPU_K8S_FLIGHTREC_DIR", "") or DEFAULT_DIR,
            keep=_int_env(env, "TPU_K8S_FLIGHTREC_KEEP", DEFAULT_KEEP),
            capacity=_int_env(env, "TPU_K8S_FLIGHTREC_SEGMENTS",
                              DEFAULT_SEGMENTS),
        )

    # -- the local registry view -------------------------------------------

    def _local_snapshot(self, now: float):
        """This process's registry as a single-instance FleetSnapshot —
        what the SLO trackers and the history store ingest."""
        from tpu_kubernetes.obs.aggregate import FleetSnapshot

        families = {f.name: f for f in expfmt.parse(self._registry.render())}
        return FleetSnapshot(ts=now, health={}, families=families)

    def _observe(self, now: float, force: bool = False) -> None:
        """Refresh the local history/SLO view, throttled unless forced
        (dump time always refreshes: the postmortem must carry the final
        instant, not one from two seconds ago)."""
        with self._lock:
            if not force and now - self._last_observe < OBSERVE_EVERY_S:
                return
            self._last_observe = now
        try:
            snapshot = self._local_snapshot(now)
            self.store.ingest(snapshot)
            for tracker in self._slos:
                tracker.observe(snapshot, now=now)
        except Exception:  # noqa: BLE001 — telemetry must not hurt serving
            pass

    # -- feeds --------------------------------------------------------------

    def record_segment(self, **fields: Any) -> None:
        """One decode segment's snapshot, straight from the engine
        scheduler (before its per-segment counters reset). Never
        raises."""
        try:
            now = time.time()
            seg = {"ts": round(now, 3)}
            seg.update(fields)
            with self._lock:
                self._segments.append(seg)
                self._counts["segments"] += 1
            self._observe(now)
        except Exception:  # noqa: BLE001 — the decode loop is the patient
            pass

    # -- payload ------------------------------------------------------------

    def _fault_totals(self) -> dict[str, float]:
        fam = self._registry.snapshot(
            prefix="tpu_k8s_faults_injected_total"
        ).get("tpu_k8s_faults_injected_total")
        if not fam:
            return {}
        return {
            s["labels"].get("site", ""): s["value"]
            for s in fam["samples"]
        }

    def _recent_spans(self, n: int = 20) -> list[dict]:
        spans = self._tracer.spans[-n:]
        return [
            {
                "span_id": s.span_id,
                "name": s.name,
                "seconds": round(s.seconds, 6),
                "run_id": s.run_id,
            }
            for s in spans
        ]

    def snapshot(self, reason: str = "on-demand",
                 extra: dict | None = None) -> dict:
        """The full, redacted payload — what a dump writes and what
        ``GET /debug/flightrec`` returns live."""
        now = time.time()
        self._observe(now, force=True)
        with self._lock:
            segments = list(self._segments)
            counts = dict(self._counts)
        try:
            alerts = [t.evaluate(now=now).to_dict() for t in self._slos]
        except Exception:  # noqa: BLE001
            alerts = []
        try:
            ledger = self._ledger.snapshot(timeline=16)
        except Exception:  # noqa: BLE001
            ledger = {}
        payload = {
            "schema": SCHEMA,
            "reason": reason,
            "ts": round(now, 3),
            "pid": os.getpid(),
            "recorder": counts,
            "segments": segments,
            "ledger": ledger,
            "alerts": alerts,
            "faults_injected": self._fault_totals(),
            "spans": self._recent_spans(),
            "history": {
                name: self.store.tail(name, TAIL_SAMPLES)
                for name in TAIL_SERIES
                if self.store.has_samples(name)
            },
        }
        if extra:
            payload["extra"] = extra
        if self.incidents is not None:
            try:
                payload["incident_id"] = self.incidents.current_incident_id()
            except Exception:  # noqa: BLE001
                payload["incident_id"] = None
        return redact(payload)

    # -- persistence --------------------------------------------------------

    def _prune(self) -> None:
        names = sorted(
            n for n in os.listdir(self.directory)
            if n.startswith("flightrec-") and n.endswith(".json")
        )
        for stale in names[:-self.keep]:
            try:
                os.remove(os.path.join(self.directory, stale))
            except OSError:
                pass

    def dump(self, reason: str, extra: dict | None = None) -> str | None:
        """Write one postmortem atomically; prune to ``keep`` newest;
        return the path (None on any failure — never raises)."""
        try:
            payload = self.snapshot(reason=reason, extra=extra)
            os.makedirs(self.directory, exist_ok=True)
            safe = "".join(
                c if c.isalnum() or c in "-_" else "-" for c in reason
            ) or "dump"
            name = f"flightrec-{int(time.time() * 1e3)}-{safe}.json"
            path = os.path.join(self.directory, name)
            fd, tmp = tempfile.mkstemp(
                prefix=".flightrec-", suffix=".tmp", dir=self.directory
            )
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(payload, f, sort_keys=True, default=str)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self._prune()
            with self._lock:
                self._counts["dumps"] += 1
            from tpu_kubernetes.obs import events

            events.emit("flightrec_dump", reason=reason, path=path,
                        segments=len(payload.get("segments", [])),
                        incident_id=payload.get("incident_id"))
            if self.incidents is not None:
                try:
                    self.incidents.note_flightrec_dump(path)
                except Exception:  # noqa: BLE001
                    pass
            return path
        except Exception:  # noqa: BLE001 — the postmortem writer must not
            with self._lock:  # crash the patient
                self._counts["dump_failures"] += 1
            return None


# -- the `get flightrec` CLI face -------------------------------------------


def fetch_flightrec(target: str, timeout: float = 5.0) -> dict:
    """GET ``/debug/flightrec`` from ``host:port`` (scheme/path
    optional, mirroring fetch_profile's target normalization)."""
    t = target.strip()
    if "//" not in t:
        t = "http://" + t
    if not t.rstrip("/").endswith("/debug/flightrec"):
        t = t.rstrip("/") + "/debug/flightrec"
    with urllib.request.urlopen(t, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def render_flightrec(payload: dict) -> str:
    """The operator summary of one recorder payload: how much the ring
    holds, what the engine was doing last, whether the ledger balances,
    and what was alerting."""
    lines = []
    counts = payload.get("recorder", {})
    lines.append(
        f"flight recorder ({payload.get('reason', '?')}) — "
        f"{len(payload.get('segments', []))} segments in ring, "
        f"{counts.get('segments', 0)} recorded, "
        f"{counts.get('dumps', 0)} dumps"
    )
    segments = payload.get("segments", [])
    if segments:
        seg = segments[-1]
        lines.append(
            f"  last segment: occupied {seg.get('occupied')}/"
            f"{seg.get('slots')} slots, live_steps={seg.get('live_steps')}"
            f", admitted={seg.get('admitted')}, reaped={seg.get('reaped')}"
            f", queued={seg.get('queued')}"
        )
        traces = seg.get("trace_ids")
        if traces:
            shown = ", ".join(traces[:4])
            more = len(traces) - 4
            lines.append(
                "  traces resident: " + shown
                + (f" (+{more} more)" if more > 0 else "")
            )
        pages = seg.get("pages")
        if pages:
            lines.append(
                f"  pages: free={pages.get('free')} live={pages.get('live')}"
                f" pinned={pages.get('pinned')} / total={pages.get('total')}"
                f" (stalls={pages.get('stalls')})"
            )
    ledger = payload.get("ledger", {})
    classes = ledger.get("classes")
    if classes is not None:
        settled = sum(classes.values())
        lines.append(
            f"  ledger: emitted={ledger.get('emitted')} settled={settled}"
            f" unsettled={ledger.get('unsettled')}"
        )
    active = [a for a in payload.get("alerts", [])
              if a.get("state") != "ok"]
    for a in active:
        lines.append(
            f"  alert [{a.get('state', '?').upper()}] {a.get('slo')}"
            f" burn fast={a.get('burn_fast')}x slow={a.get('burn_slow')}x"
        )
    faults = {k: v for k, v in payload.get("faults_injected", {}).items() if v}
    if faults:
        lines.append("  faults injected: " + ", ".join(
            f"{site}={int(n)}" for site, n in sorted(faults.items())
        ))
    return "\n".join(lines) + "\n"
