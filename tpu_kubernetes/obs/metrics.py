"""Dependency-free Prometheus-style metrics registry.

The reference provisioner has no observability beyond bare fmt.Println
progress lines (SURVEY §5.1/§5.5); the serving/training stack here needs
request-level metrics to be operable at all (ROADMAP north star). This is
the one process-wide place those numbers live:

* :class:`Counter`, :class:`Gauge`, :class:`Histogram` — with labels,
  thread-safe, zero dependencies (stdlib only — the serve path must stay
  air-gap friendly, same stance as serve/server.py).
* Text exposition in the Prometheus format, so ``GET /metrics`` on the
  inference server (serve/server.py) and ``tpu-k8s get metrics`` are
  scrape-ready without a client library.
* :meth:`Registry.snapshot` — the same numbers as plain JSON, which is
  what run reports persist (util/runlog.py).

Metric families are get-or-create: instrumentation sites call
``REGISTRY.counter(...)`` every time and always receive the same family,
so import order never matters and tests can reset the registry wholesale.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Any, Iterable

# Prometheus' default latency buckets, extended upward: terraform applies
# and TPU compiles legitimately take minutes, and a histogram whose last
# finite bucket is 10s would flatten exactly the tail being tuned.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0, 600.0,
)


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\"", "\\\"")
        .replace("\n", "\\n")
    )


def _format_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _label_str(names: tuple[str, ...], values: tuple[str, ...],
               extra: str = "") -> str:
    pairs = [
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    ]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


class MetricError(ValueError):
    pass


class _Child:
    """One labeled time series. Updates lock on the parent family's mutex
    (updates are a few arithmetic ops; one lock per family keeps the
    memory overhead O(families), not O(series))."""

    __slots__ = ("_family",)

    def __init__(self, family: _Family):
        self._family = family


class _CounterChild(_Child):
    __slots__ = ("value",)

    def __init__(self, family: _Family):
        super().__init__(family)
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise MetricError("counters only go up — use a Gauge")
        with self._family._lock:
            self.value += amount


class _GaugeChild(_Child):
    __slots__ = ("value",)

    def __init__(self, family: _Family):
        super().__init__(family)
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._family._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._family._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class _HistogramChild(_Child):
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, family: _Family):
        super().__init__(family)
        self.counts = [0] * (len(family.buckets) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        # bucket index -> (trace_id, observed value): the latest exemplar
        # per bucket, so a p99 bucket links to a real slow trace
        self.exemplars: dict[int, tuple[str, float]] = {}

    def observe(self, value: float, exemplar: str | None = None) -> None:
        v = float(value)
        i = bisect_left(self._family.buckets, v)
        with self._family._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if exemplar:
                self.exemplars[i] = (str(exemplar), v)


_CHILD_TYPES = {
    "counter": _CounterChild,
    "gauge": _GaugeChild,
    "histogram": _HistogramChild,
}


class _Family:
    """One named metric family: its children keyed by label values."""

    def __init__(self, name: str, help: str, kind: str,
                 labelnames: tuple[str, ...],
                 buckets: tuple[float, ...] = ()):
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = labelnames
        self.buckets = tuple(sorted(buckets)) if kind == "histogram" else ()
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], _Child] = {}
        if not labelnames:
            # unlabeled families still have exactly one series — exposed
            # directly so call sites write ``family.inc()`` not
            # ``family.labels().inc()``
            self._children[()] = _CHILD_TYPES[kind](self)

    def labels(self, *values: Any, **kwargs: Any) -> Any:
        if kwargs:
            if values:
                raise MetricError("pass labels positionally or by name, not both")
            try:
                values = tuple(kwargs[n] for n in self.labelnames)
            except KeyError as e:
                raise MetricError(
                    f"{self.name}: missing label {e.args[0]!r} "
                    f"(wants {list(self.labelnames)})"
                ) from None
            if len(kwargs) != len(self.labelnames):
                extra = set(kwargs) - set(self.labelnames)
                raise MetricError(f"{self.name}: unknown labels {sorted(extra)}")
        if len(values) != len(self.labelnames):
            raise MetricError(
                f"{self.name} wants labels {list(self.labelnames)}, "
                f"got {len(values)} values"
            )
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _CHILD_TYPES[self.kind](self)
        return child

    # unlabeled convenience: family IS the single child
    def _solo(self) -> Any:
        if self.labelnames:
            raise MetricError(
                f"{self.name} has labels {list(self.labelnames)} — "
                "call .labels(...) first"
            )
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def observe(self, value: float, exemplar: str | None = None) -> None:
        self._solo().observe(value, exemplar)

    @property
    def value(self) -> float:
        return self._solo().value

    # -- exposition --------------------------------------------------------

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._children.items())
            for key, child in items:
                if self.kind == "histogram":
                    acc = 0
                    for i, (bound, n) in enumerate(zip(
                        (*self.buckets, math.inf), child.counts
                    )):
                        acc += n
                        le = _format_value(bound)
                        labels = _label_str(
                            self.labelnames, key, extra=f'le="{le}"'
                        )
                        line = f"{self.name}_bucket{labels} {acc}"
                        ex = child.exemplars.get(i)
                        if ex is not None:
                            # OpenMetrics exemplar: trace id riding the
                            # bucket the observation landed in
                            line += (
                                f' # {{trace_id="{ex[0]}"}} '
                                f"{_format_value(ex[1])}"
                            )
                        lines.append(line)
                    labels = _label_str(self.labelnames, key)
                    lines.append(
                        f"{self.name}_sum{labels} {_format_value(child.sum)}"
                    )
                    lines.append(f"{self.name}_count{labels} {child.count}")
                else:
                    labels = _label_str(self.labelnames, key)
                    lines.append(
                        f"{self.name}{labels} {_format_value(child.value)}"
                    )
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            samples = []
            for key, child in sorted(self._children.items()):
                labels = dict(zip(self.labelnames, key))
                if self.kind == "histogram":
                    samples.append({
                        "labels": labels,
                        "count": child.count,
                        "sum": round(child.sum, 6),
                    })
                else:
                    samples.append({"labels": labels, "value": child.value})
        return {"type": self.kind, "samples": samples}


# public aliases — what instrumentation sites name in annotations
Counter = _Family
Gauge = _Family
Histogram = _Family


class Registry:
    """Process-wide family store; families are created once and shared."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get_or_create(self, name: str, help: str, kind: str,
                       labelnames: Iterable[str],
                       buckets: tuple[float, ...] = ()) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise MetricError(
                        f"metric {name!r} re-registered as {kind} with labels "
                        f"{list(labelnames)} (was {fam.kind} "
                        f"{list(fam.labelnames)})"
                    )
                return fam
            fam = _Family(name, help, kind, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> Counter:
        return self._get_or_create(name, help, "counter", labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(name, help, "gauge", labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            name, help, "histogram", labelnames, buckets=buckets
        )

    def families(self) -> list[str]:
        with self._lock:
            return sorted(self._families)

    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4) of every
        family, name-ordered so output is diffable/golden-testable."""
        with self._lock:
            fams = [self._families[n] for n in sorted(self._families)]
        return "".join(f.render() for f in fams)

    def snapshot(self, prefix: str = "") -> dict[str, Any]:
        """The registry as plain JSON-ready data; ``prefix`` filters to
        one subsystem (run reports persist only the terraform families)."""
        with self._lock:
            fams = {
                n: f for n, f in self._families.items()
                if n.startswith(prefix)
            }
        return {n: fams[n].snapshot() for n in sorted(fams)}

    def reset(self) -> None:
        """Drop every family — test isolation only."""
        with self._lock:
            self._families.clear()


# the process-wide default registry: the serve server exposes it at
# GET /metrics, the CLI dumps it via `get metrics`, run reports snapshot it
REGISTRY = Registry()

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def register_build_info(registry: Registry | None = None) -> Gauge:
    """The Prometheus ``*_info`` idiom: a constant-1 gauge whose labels
    carry the facts — here the package version, so a fleet scrape
    (obs/aggregate.py keeps all families, instance-tagged) can answer
    "which workers run which build" during a rollout."""
    import tpu_kubernetes

    fam = (registry if registry is not None else REGISTRY).gauge(
        "tpu_k8s_build_info",
        "build/version info; constant 1 — the version rides the label",
        labelnames=("version",),
    )
    fam.labels(tpu_kubernetes.__version__).set(1.0)
    return fam


BUILD_INFO = register_build_info()
