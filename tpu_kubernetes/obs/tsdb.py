"""A bounded in-memory time-series store — the fleet's retained history.

Everything upstream of this module answers "what is the number NOW":
the registry (obs/metrics.py) holds cumulative counters, the aggregator
(obs/aggregate.py) merges one instant across workers, and ``monitor``
derived rates from exactly two consecutive scrapes. The ROADMAP's next
consumers — the autoscaling fleet controller and disaggregated
prefill/decode routing — need *trends*: goodput over the last minute,
page-pressure slope, SLO burn windows. This is that layer, kept
dependency-free and strictly bounded so it can live inside the monitor
process, the serve process (the flight recorder, obs/flightrec.py), and
tests alike.

Design:

* One :class:`TSDB` holds many series keyed by ``(name, labels)``. Each
  series is an append-only **raw ring** (newest ``raw_max`` samples)
  plus **downsample tiers** (10s and 1m buckets by default) that retain
  coarse history long after the raw ring wrapped — a bucket keeps
  first/last/min/max/sum/count so rates, averages, and extremes survive
  downsampling.
* A **hard memory cap** (``max_bytes``, estimated accounting): when the
  store would exceed it, the *coldest* series — the one appended to
  least recently — are evicted whole. Hot serve series survive; a
  one-off label explosion cannot OOM the monitor.
* Query helpers mirror the PromQL verbs the SLO evaluator and monitor
  need: :meth:`rate_over_time` / :meth:`increase` with counter-reset
  detection (a delta < 0 means the worker restarted; the increase since
  the reset is the new value itself), :meth:`avg_over_time`,
  :meth:`max_over_time`, and :meth:`quantile_over_time` which re-uses
  expfmt's bucket interpolation over per-window bucket increases.

Timestamps are caller-supplied (``ts=``) so injectable clocks flow all
the way down — the SLO lifecycle tests drive hours of window arithmetic
in milliseconds through this store exactly as they did through the old
private deques.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Callable, Iterable

from tpu_kubernetes.obs import expfmt

# (bucket width seconds, max buckets) per downsample tier, finest first.
# 10s x 1080 = 3h of mid-resolution history; 60s x 370 ≈ 6h10m — enough
# to answer the slowest SLO burn window (21600s) plus the same 600s of
# slack the old in-tracker deque kept.
DEFAULT_TIERS: tuple[tuple[float, int], ...] = ((10.0, 1080), (60.0, 370))
DEFAULT_RAW_MAX = 240
DEFAULT_MAX_BYTES = 8 << 20

# estimated bytes per stored object — accounting, not accounting-grade;
# the cap is a guard rail against unbounded growth, not a malloc audit
_SERIES_BYTES = 512
_SAMPLE_BYTES = 64
_BUCKET_BYTES = 120

Labels = tuple[tuple[str, str], ...]


class _Bucket:
    """One downsample bucket: the fold of every raw sample whose ts
    landed in [start, start + width)."""

    __slots__ = ("start", "first_ts", "first", "last_ts", "last",
                 "vmin", "vmax", "vsum", "count")

    def __init__(self, start: float, ts: float, value: float):
        self.start = start
        self.first_ts = ts
        self.first = value
        self.last_ts = ts
        self.last = value
        self.vmin = value
        self.vmax = value
        self.vsum = value
        self.count = 1

    def fold(self, ts: float, value: float) -> None:
        self.last_ts = ts
        self.last = value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        self.vsum += value
        self.count += 1


class _Series:
    __slots__ = ("name", "labels", "kind", "raw", "tiers", "last_append")

    def __init__(self, name: str, labels: Labels, kind: str,
                 raw_max: int, tiers: tuple[tuple[float, int], ...]):
        self.name = name
        self.labels = labels
        self.kind = kind
        self.raw: deque[tuple[float, float]] = deque(maxlen=raw_max)
        self.tiers: list[tuple[float, int, deque[_Bucket]]] = [
            (width, cap, deque()) for width, cap in tiers
        ]
        self.last_append = 0.0


def _labels_key(labels: Any) -> Labels:
    if isinstance(labels, dict):
        return tuple(sorted(labels.items()))
    return tuple(sorted(tuple(pair) for pair in labels))


def _reset_aware_increase(samples: list[tuple[float, float]]) -> float:
    """Counter increase over consecutive samples with Prometheus-style
    reset detection: a negative delta means the counter restarted near
    zero, so the whole new value counts as increase."""
    inc = 0.0
    prev = samples[0][1]
    for _, v in samples[1:]:
        d = v - prev
        inc += v if d < 0 else d
        prev = v
    return inc


class TSDB:
    """The bounded, thread-safe store. All public methods may be called
    concurrently (scraper thread appends while the monitor renderer and
    SLO evaluator query)."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES,
                 raw_max: int = DEFAULT_RAW_MAX,
                 tiers: tuple[tuple[float, int], ...] = DEFAULT_TIERS):
        self.max_bytes = max(_SERIES_BYTES, int(max_bytes))
        self.raw_max = max(2, int(raw_max))
        self.tiers = tuple((float(w), max(1, int(c))) for w, c in tiers)
        self._series: dict[tuple[str, Labels], _Series] = {}
        self._bytes = 0
        self._evicted = 0
        self._lock = threading.RLock()

    # -- writes -------------------------------------------------------------

    def append(self, name: str, value: float, labels: Any = (),
               ts: float | None = None, kind: str = "gauge") -> None:
        """Record one sample. ``labels`` may be a dict or pairs; ``ts``
        defaults to the wall clock. Out-of-order timestamps land in the
        raw ring but do not rewrite already-closed downsample buckets."""
        if ts is None:
            import time
            ts = time.time()
        value = float(value)
        key = (name, _labels_key(labels))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _Series(
                    name, key[1], kind, self.raw_max, self.tiers
                )
                self._bytes += _SERIES_BYTES
            if len(s.raw) < self.raw_max:
                self._bytes += _SAMPLE_BYTES
            s.raw.append((ts, value))
            for width, cap, ring in s.tiers:
                start = ts - (ts % width)
                if ring and ring[-1].start == start:
                    ring[-1].fold(ts, value)
                elif not ring or ring[-1].start < start:
                    ring.append(_Bucket(start, ts, value))
                    self._bytes += _BUCKET_BYTES
                    while len(ring) > cap:
                        ring.popleft()
                        self._bytes -= _BUCKET_BYTES
                # ring[-1].start > start: stale timestamp — raw keeps it,
                # the closed bucket stays immutable
            s.last_append = ts
            self._evict_cold(keep=key)

    def ingest(self, snapshot: Any) -> None:
        """Feed one fleet scrape cycle (a FleetSnapshot, duck-typed:
        ``.ts`` + ``.families`` of expfmt Families) — every sample of
        every family becomes a point in its series."""
        ts = snapshot.ts
        for fam in snapshot.families.values():
            kind = fam.kind
            for sample in fam.samples:
                k = kind
                if kind == "histogram":
                    # bucket/count/sum components are all cumulative
                    k = "counter"
                self.append(sample.name, sample.value, sample.labels,
                            ts=ts, kind=k)

    def _evict_cold(self, keep: tuple[str, Labels]) -> None:
        while self._bytes > self.max_bytes and len(self._series) > 1:
            coldest = min(
                (k for k in self._series if k != keep),
                key=lambda k: self._series[k].last_append,
                default=None,
            )
            if coldest is None:
                return
            s = self._series.pop(coldest)
            self._bytes -= (
                _SERIES_BYTES + _SAMPLE_BYTES * len(s.raw)
                + _BUCKET_BYTES * sum(len(r) for _, _, r in s.tiers)
            )
            self._evicted += 1

    # -- series access ------------------------------------------------------

    def _match(self, name: str,
               where: Callable[[dict[str, str]], bool] | None) -> list[_Series]:
        out = []
        for (n, _), s in self._series.items():
            if n != name:
                continue
            if where is None or where(dict(s.labels)):
                out.append(s)
        return out

    def names(self) -> list[str]:
        with self._lock:
            return sorted({n for n, _ in self._series})

    def series_labels(self, name: str) -> list[dict[str, str]]:
        with self._lock:
            return [dict(s.labels) for s in self._match(name, None)]

    def has_samples(self, name: str,
                    where: Callable[[dict[str, str]], bool] | None = None,
                    ) -> bool:
        with self._lock:
            return any(s.raw for s in self._match(name, where))

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "series": len(self._series),
                "bytes_estimated": self._bytes,
                "max_bytes": self.max_bytes,
                "evicted_series": self._evicted,
            }

    # -- per-series sample reconstruction ----------------------------------

    @staticmethod
    def _merged(s: _Series, start: float, end: float
                ) -> list[tuple[float, float]]:
        """Samples in [start, end] merged across resolutions: raw wins
        where it still covers; tier buckets (their last reading) fill in
        the older history the ring already dropped — coarse first so
        finer data overwrites it."""
        points: dict[float, float] = {}
        for _, _, ring in reversed(s.tiers):  # coarsest → finest
            for b in ring:
                if start <= b.last_ts <= end:
                    points[b.last_ts] = b.last
                if start <= b.first_ts <= end:
                    points.setdefault(b.first_ts, b.first)
        for ts, v in s.raw:
            if start <= ts <= end:
                points[ts] = v
        return sorted(points.items())

    def _at_or_before(self, s: _Series, ts: float
                      ) -> tuple[float, float] | None:
        """Newest sample with timestamp ≤ ts, searching raw first and
        then each downsample tier (bucket last-readings)."""
        best: tuple[float, float] | None = None
        for t, v in reversed(s.raw):
            if t <= ts:
                best = (t, v)
                break
        if best is not None:
            return best
        for _, _, ring in s.tiers:  # finest first
            for b in reversed(ring):
                if b.last_ts <= ts:
                    return (b.last_ts, b.last)
                if b.first_ts <= ts:
                    return (b.first_ts, b.first)
        return None

    @staticmethod
    def _first(s: _Series) -> tuple[float, float] | None:
        """The oldest retained reading — coarsest tier first (it reaches
        furthest back), then the raw ring."""
        for _, _, ring in reversed(s.tiers):
            if ring:
                b = ring[0]
                return (b.first_ts, b.first)
        if s.raw:
            return tuple(s.raw[0])
        return None

    def sample_at_or_before(self, name: str, labels: Any, ts: float
                            ) -> tuple[float, float] | None:
        key = (name, _labels_key(labels))
        with self._lock:
            s = self._series.get(key)
            return None if s is None else self._at_or_before(s, ts)

    def first_sample(self, name: str, labels: Any
                     ) -> tuple[float, float] | None:
        key = (name, _labels_key(labels))
        with self._lock:
            s = self._series.get(key)
            return None if s is None else self._first(s)

    def latest(self, name: str,
               where: Callable[[dict[str, str]], bool] | None = None,
               ) -> float | None:
        """Sum of each matching series' newest value (the fleet-wide
        instant, like FleetSnapshot.value_sum but from history)."""
        with self._lock:
            vals = [s.raw[-1][1] for s in self._match(name, where) if s.raw]
        return sum(vals) if vals else None

    def window(self, name: str, start: float, end: float,
               where: Callable[[dict[str, str]], bool] | None = None,
               ) -> list[tuple[dict[str, str], list[tuple[float, float]]]]:
        """Per-series samples over [start, end] — what ``get history``
        renders."""
        with self._lock:
            return [
                (dict(s.labels), self._merged(s, start, end))
                for s in self._match(name, where)
            ]

    # -- PromQL-style verbs -------------------------------------------------

    def _series_span(self, s: _Series, window: float, now: float
                     ) -> list[tuple[float, float]]:
        """The window's samples plus the baseline reading just before it
        (so an increase over the window has its left edge)."""
        start = now - window
        samples = self._merged(s, start, now)
        baseline = self._at_or_before(s, start)
        if baseline is not None and (not samples or baseline[0] < samples[0][0]):
            samples.insert(0, baseline)
        return samples

    def increase(self, name: str, window: float, now: float,
                 where: Callable[[dict[str, str]], bool] | None = None,
                 ) -> float | None:
        """Counter increase over [now - window, now] summed across
        matching series, reset-aware. None when no series has two
        readings to difference."""
        total, found = 0.0, False
        with self._lock:
            for s in self._match(name, where):
                span = self._series_span(s, window, now)
                if len(span) < 2:
                    continue
                total += _reset_aware_increase(span)
                found = True
        return total if found else None

    def rate_over_time(self, name: str, window: float, now: float,
                       where: Callable[[dict[str, str]], bool] | None = None,
                       ) -> float | None:
        """Per-second rate over the window: each series' increase over
        the span its data actually covers (cold starts and ``--once``
        seeds must not be diluted by an empty window), summed."""
        total, found = 0.0, False
        with self._lock:
            for s in self._match(name, where):
                span = self._series_span(s, window, now)
                if len(span) < 2:
                    continue
                elapsed = span[-1][0] - span[0][0]
                if elapsed <= 0 or not math.isfinite(elapsed):
                    continue
                total += _reset_aware_increase(span) / elapsed
                found = True
        return total if found else None

    def avg_over_time(self, name: str, window: float, now: float,
                      where: Callable[[dict[str, str]], bool] | None = None,
                      ) -> float | None:
        """Mean of every matching sample in the window (gauges)."""
        vals: list[float] = []
        with self._lock:
            for s in self._match(name, where):
                vals.extend(v for _, v in self._merged(s, now - window, now))
        return sum(vals) / len(vals) if vals else None

    def max_over_time(self, name: str, window: float, now: float,
                      where: Callable[[dict[str, str]], bool] | None = None,
                      ) -> float | None:
        """Max over the window — consults downsample buckets' vmax so a
        spike that fell off the raw ring still answers."""
        best: float | None = None
        start = now - window
        with self._lock:
            for s in self._match(name, where):
                for _, v in self._merged(s, start, now):
                    if best is None or v > best:
                        best = v
                for _, _, ring in s.tiers:
                    for b in ring:
                        if b.last_ts >= start and b.first_ts <= now:
                            if best is None or b.vmax > best:
                                best = b.vmax
        return best

    def quantile_over_time(self, name: str, q: float, window: float,
                           now: float,
                           where: Callable[[dict[str, str]], bool] | None = None,
                           ) -> float | None:
        """Histogram quantile from per-``le`` bucket increases over the
        window (the family's ``<name>_bucket`` series), interpolated by
        expfmt — the windowed sibling of FleetSnapshot.quantile."""
        acc: dict[float, float] = {}
        with self._lock:
            for s in self._match(f"{name}_bucket", where):
                le = expfmt.parse_value(dict(s.labels).get("le", "+Inf"))
                span = self._series_span(s, window, now)
                if len(span) < 2:
                    continue
                acc[le] = acc.get(le, 0.0) + _reset_aware_increase(span)
        if not acc:
            return None
        return expfmt.bucket_quantile(sorted(acc.items()), q)

    def binned(self, name: str, window: float, now: float, bins: int = 8,
               mode: str = "value",
               where: Callable[[dict[str, str]], bool] | None = None,
               ) -> list[float | None]:
        """The window cut into ``bins`` equal slots, oldest first — the
        sparkline feed. ``mode="value"``: per-bin mean of gauge samples.
        ``mode="rate"``: per-bin per-second counter increase (reset-
        aware), each inter-sample delta attributed to the bin holding
        the later sample. Bins with no data are None."""
        bins = max(1, int(bins))
        width = window / bins
        start = now - window
        sums = [0.0] * bins
        counts = [0] * bins
        with self._lock:
            for s in self._match(name, where):
                span = self._series_span(s, window, now)
                if mode == "rate":
                    if len(span) < 2:
                        continue
                    prev_ts, prev_v = span[0]
                    for ts, v in span[1:]:
                        d = v - prev_v
                        inc = v if d < 0 else d
                        i = min(bins - 1, max(0, int((ts - start) / width)))
                        sums[i] += inc
                        counts[i] += 1
                        prev_ts, prev_v = ts, v
                else:
                    for ts, v in span:
                        if ts < start:
                            continue
                        i = min(bins - 1, max(0, int((ts - start) / width)))
                        sums[i] += v
                        counts[i] += 1
        if mode == "rate":
            return [
                (sums[i] / width) if counts[i] else None for i in range(bins)
            ]
        return [
            (sums[i] / counts[i]) if counts[i] else None for i in range(bins)
        ]

    def tail(self, name: str, n: int = 32,
             where: Callable[[dict[str, str]], bool] | None = None,
             ) -> list[dict[str, Any]]:
        """The last ``n`` raw samples of every matching series — what a
        flight-recorder dump embeds so a postmortem carries the recent
        timeline, not just the final instant."""
        out = []
        with self._lock:
            for s in self._match(name, where):
                recent = list(s.raw)[-max(0, int(n)):]
                out.append({
                    "name": s.name,
                    "labels": dict(s.labels),
                    "kind": s.kind,
                    "samples": [[round(t, 3), v] for t, v in recent],
                })
        return out


# -- presentation helpers (shared by monitor and `get history`) -------------

SPARK_BARS = "▁▂▃▄▅▆▇█"


def sparkline(values: Iterable[float | None]) -> str:
    """Unicode sparkline, scaled to the series' own max; None (no data
    in that slot) renders as '·' so gaps — a dead target's cycles — stay
    visible instead of reading as zero."""
    vals = list(values)
    finite = [v for v in vals if v is not None and math.isfinite(v)]
    top = max(finite) if finite else 0.0
    chars = []
    for v in vals:
        if v is None or not math.isfinite(v):
            chars.append("·")
        elif top <= 0:
            chars.append(SPARK_BARS[0])
        else:
            idx = int(v / top * (len(SPARK_BARS) - 1) + 0.5)
            chars.append(SPARK_BARS[max(0, min(len(SPARK_BARS) - 1, idx))])
    return "".join(chars)
